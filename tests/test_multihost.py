"""Multi-host (DCN analog) tests: 2 processes x 4 virtual CPU devices.

Two complementary shapes (both 2-process x 4-device dryruns):

1. multi-controller SPMD — both processes run the same program over one
   8-device global mesh via jax.distributed (coordinator = PD analog);
   collectives ride the inter-process transport (DCN on real slices).
2. coordinator/worker MPP — the DCN fragment scheduler
   (parallel/dcn.py) dispatches per-host fragment plans over the
   engine-RPC seam to two worker processes, each executing SPMD on its
   own 4-device mesh (hierarchical shuffle: ICI within the host,
   host-staged exchange between), with partial-agg-before-DCN and
   failure recovery (kill-one-worker retry parity below).

Reference: cross-store MPP dispatch over gRPC (pkg/store/copr/mpp.go:93)
with PD-coordinated membership, and the MPP recovery loop
(pkg/executor/internal/mpp/recovery_handler.go:26).
"""

import os
import re
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

#: the TPC-H subset both dryruns assert parity on: scalar aggregate
#: (Q6 shape), grouped aggregate with avg (Q1 shape), join + group-by
#: (Q4/Q18 shape), top-k group-by
TPCH_QUERIES = [
    "select sum(l_extendedprice * l_discount) from lineitem "
    "where l_discount between 0.05 and 0.07 and l_quantity < 24",
    "select l_returnflag, l_linestatus, sum(l_quantity), "
    "sum(l_extendedprice), avg(l_discount), count(*) from lineitem "
    "where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus",
    "select o_orderpriority, count(*) from orders join lineitem "
    "on o_orderkey = l_orderkey where l_quantity < 10 "
    "group by o_orderpriority order by o_orderpriority",
    "select l_suppkey, count(*) from lineitem group by l_suppkey "
    "order by count(*) desc, l_suppkey limit 5",
]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _worker_env() -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the pytest process forces an 8-device host platform (conftest);
    # each worker must contribute exactly 4
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    return env


def test_two_process_mesh_sql_parity():
    worker = os.path.join(HERE, "_multihost_worker.py")
    coord = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", coord],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=_worker_env(),
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-4000:]}"
        assert "MULTIHOST_OK" in out, out[-2000:]


# ---------------------------------------------------------------------------
# DCN fragment scheduler dryruns (coordinator here, 2 worker processes)
# ---------------------------------------------------------------------------


def _spawn_dcn_worker(extra=()):
    p = subprocess.Popen(
        [
            sys.executable, "-m", "tidb_tpu.parallel.dcn_worker",
            "--port", "0", "--mesh-devices", "4",
            "--tpch-sf", "0.002", "--seed", "3",
            "--tables", "orders,lineitem", *extra,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=_worker_env(),
        cwd=REPO,
    )
    line = p.stdout.readline()
    m = re.match(r"DCN_WORKER_READY port=(\d+)", line)
    if not m:
        rest = ""
        try:
            rest, _ = p.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
        raise AssertionError(f"worker not ready: {line!r}\n{rest[-3000:]}")
    return p, int(m.group(1))


@pytest.fixture()
def tpch_single():
    """Single-process reference session over the same deterministic
    data every worker loads."""
    from tidb_tpu.bench import load_tpch
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog

    cat = Catalog()
    load_tpch(cat, sf=0.002, seed=3, tables=["orders", "lineitem"])
    return Session(cat, db="tpch")


def _plan(sess, q):
    from tidb_tpu.parser.sqlparse import parse
    from tidb_tpu.planner.logical import build_query

    return build_query(
        parse(q)[0], sess.catalog, "tpch", sess._scalar_subquery
    )


def test_dcn_fragment_scheduler_tpch_parity(tpch_single):
    """2-process x 4-device dryrun: the TPC-H subset runs through the
    cross-host fragment scheduler with results identical to
    single-process execution."""
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
    )
    try:
        for q in TPCH_QUERIES:
            exp = tpch_single.must_query(q).rows
            _cols, got = sched.execute_plan(_plan(tpch_single, q))
            assert got == exp, f"{q}\n got={got}\n exp={exp}"
        # every query fanned out: both hosts stayed in rotation
        assert len(sched.alive_endpoints()) == 2
    finally:
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_explain_analyze_and_metrics(tpch_single):
    """Distributed EXPLAIN ANALYZE on the 2-process x 4-device dryrun:
    the plan tree carries per-host fragment rows with nonzero execution
    times and DCN byte counts, and /metrics afterwards exposes the
    tidbtpu_dcn_* counters plus tidbtpu_engine_jit_compilations
    consistent with the run."""
    import json
    import urllib.request

    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.http_status import StatusServer
    from tidb_tpu.utils.metrics import REGISTRY

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
    )
    http = StatusServer(tpch_single.catalog, port=0, dcn=sched)
    http.start_background()
    dispatches0 = sum(
        v for n, _k, v in REGISTRY.rows()
        if n.startswith("tidbtpu_dcn_dispatches")
    )
    try:
        q = TPCH_QUERIES[1]  # grouped aggregate with avg (Q1 shape)
        exp = tpch_single.must_query(q).rows
        _cols, rows, lines = sched.explain_analyze(_plan(tpch_single, q))
        assert rows == exp  # the instrumented run still returns parity
        text = "\n".join(lines)
        assert "DCNFragments fragments=2 hosts=2" in text
        frag_lines = [
            ln for ln in lines if ln.lstrip().startswith("Fragment#")
        ]
        assert len(frag_lines) == 2
        for ln in frag_lines:
            m = re.search(
                r"host=(\S+) attempt=1 rows=(\d+) "
                r"time=([0-9.]+)ms bytes=(\d+)", ln
            )
            assert m, ln
            assert float(m.group(3)) > 0  # nonzero per-host exec time
            assert int(m.group(4)) > 0    # nonzero DCN byte count
        # the two fragments ran on distinct worker hosts
        assert len({re.search(r"host=(\S+)", ln).group(1)
                    for ln in frag_lines}) == 2
        # min/avg/max across hosts + total bytes shipped in the summary
        assert re.search(
            r"bytes_shipped=[1-9]\d* time min=[0-9.]+ms "
            r"avg=[0-9.]+ms max=[0-9.]+ms", text
        )

        # /metrics after the run: dcn counters + engine jit accounting
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/metrics", timeout=10
        ).read().decode()
        assert "tidbtpu_dcn_dispatches" in body
        assert "tidbtpu_dcn_bytes_staged" in body
        jit = re.search(
            r"^tidbtpu_engine_jit_compilations (\d+)", body, re.M
        )
        assert jit and int(jit.group(1)) > 0
        dispatches1 = sum(
            v for n, _k, v in REGISTRY.rows()
            if n.startswith("tidbtpu_dcn_dispatches")
        )
        assert dispatches1 >= dispatches0 + 2  # both fragments dispatched
        # /dcn: per-fragment stats of the run we just made
        dcn = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{http.port}/dcn", timeout=10
        ).read().decode())
        assert dcn["alive"] == 2
        assert [f["fid"] for f in dcn["last_query"]["fragments"]] == [0, 1]
    finally:
        http.shutdown()
        sched.close()
        for w in (w1, w2):
            w.kill()


def _counter_total(prefix):
    from tidb_tpu.utils.metrics import REGISTRY

    return sum(
        v for n, _k, v in REGISTRY.rows() if n.startswith(prefix)
    )


#: joins and distinct group-bys routed over worker-to-worker tunnels
SHUFFLE_QUERIES = [
    # repartition join: orders join lineitem, neither side small
    TPCH_QUERIES[2],
    # fragment-sliced GROUP BY with DISTINCT (the old single-host
    # fallback): complete groups per partition
    "select o_orderpriority, count(distinct o_custkey) from orders "
    "group by o_orderpriority order by o_orderpriority",
]

#: STRING-keyed repartition join (un-gated by the binary columnar wire
#: format: values hash stably, receivers re-key dictionary codes into a
#: stage-local unified dictionary). Filters keep the F/O status match
#: explosion small at SF 0.002.
STRING_KEY_JOIN = (
    "select o_orderstatus, count(*) from orders join lineitem "
    "on o_orderstatus = l_linestatus "
    "where o_totalprice > 150000 and l_quantity >= 47 "
    "group by o_orderstatus order by o_orderstatus"
)


def test_dcn_shuffle_repartition_join_parity(tpch_single):
    """2-process x 4-device dryrun of the worker-to-worker shuffle
    service: repartition join + distinct GROUP BY + STRING-keyed join
    run with results identical to single-process execution, the
    shuffled bytes provably BYPASS the coordinator —
    tidbtpu_shuffle_bytes_total (incremented only in the worker
    processes, shipped back via the piggybacked registry deltas) grows,
    while tidbtpu_dcn_bytes_staged does not move at all — and the
    binary columnar wire codec puts <= 0.5x the JSON codec's bytes on
    the tunnels for the same query at row-level result parity."""
    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    try:
        _shuffle_codec_ab_body(tpch_single, p1, p2)
    finally:
        for w in (w1, w2):
            w.kill()


def _shuffle_codec_ab_body(tpch_single, p1, p2):
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler

    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
    )
    staged0 = _counter_total("tidbtpu_dcn_bytes_staged")
    shuffled0 = _counter_total("tidbtpu_shuffle_bytes_total")
    bytes_binary = {}
    try:
        for q in SHUFFLE_QUERIES + [STRING_KEY_JOIN]:
            exp = tpch_single.must_query(q).rows
            _cols, got = sched.execute_plan(_plan(tpch_single, q))
            assert got == exp, f"{q}\n got={got}\n exp={exp}"
            bytes_binary[q] = sched.last_query["shuffle"]["bytes_tunneled"]
            assert sched.last_query["shuffle"]["codec"] == "binary"
        # the string-keyed join really rode the shuffle path (no
        # single-host fallback) and really exchanged partition data
        assert sched.last_query["shuffle"]["kind"] == "join"
        assert bytes_binary[STRING_KEY_JOIN] > 0
        last = sched.last_query
        assert last["shuffle"]["m"] == 2
        assert last["shuffle"]["bytes_tunneled"] > 0
        # the acceptance criterion: inter-worker data rode the tunnels,
        # not the coordinator
        staged1 = _counter_total("tidbtpu_dcn_bytes_staged")
        shuffled1 = _counter_total("tidbtpu_shuffle_bytes_total")
        assert shuffled1 > shuffled0  # fleet counters merged from replies
        assert staged1 == staged0
        # per-partition results DID return to the coordinator (they are
        # final rows, not exchange data) under their own counter
        assert _counter_total("tidbtpu_shuffle_result_bytes") > 0
        assert len(sched.alive_endpoints()) == 2
    finally:
        sched.close()

    # codec A/B on the same workers: the JSON escape hatch gives the
    # same rows while the binary codec's tunnel bytes are <= 0.5x
    sched_json = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
        shuffle_codec="json",
    )
    try:
        q = SHUFFLE_QUERIES[0]
        exp = tpch_single.must_query(q).rows
        _cols, got = sched_json.execute_plan(_plan(tpch_single, q))
        assert got == exp  # row-level cross-codec parity
        bytes_json = sched_json.last_query["shuffle"]["bytes_tunneled"]
        assert sched_json.last_query["shuffle"]["codec"] == "json"
        assert bytes_json > 0
        assert bytes_binary[q] <= 0.5 * bytes_json, (
            f"binary codec shipped {bytes_binary[q]}B vs JSON "
            f"{bytes_json}B — expected <= 0.5x"
        )
    finally:
        sched_json.close()


def test_dcn_flight_recorder_surfaces(tpch_single, tmp_path):
    """PR 6 acceptance: a 2-process x 4-device shuffle dryrun driven
    through the SESSION (an attached scheduler now routes fragmentable
    SELECTs across the fleet, not just EXPLAIN ANALYZE) lands all
    three flight-recorder surfaces:

    - statements_summary rows with NON-ZERO shuffle-wait phase time
      and p99 >= p50 (the per-digest streaming histogram);
    - slow_query rows carrying captured EXPLAIN ANALYZE text (the
      instrumented lines for an over-threshold EXPLAIN ANALYZE, the
      plan tree + distributed runtime summary for a routed SELECT),
      also written to the tidb_slow_query_file sink;
    - cluster_links rows with per-peer RTT and stall seconds.
    """
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.utils.metrics import STMT_SUMMARY, sql_digest

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
    )
    sess = tpch_single
    q = SHUFFLE_QUERIES[0]
    exp = sess.must_query(q).rows  # local reference BEFORE attaching
    sess.attach_dcn_scheduler(sched)
    try:
        sess.execute("set tidb_slow_log_threshold = 0")
        slow_file = tmp_path / "slow.log"
        sess.execute(f"set tidb_slow_query_file = '{slow_file}'")
        for _ in range(3):
            r = sess.execute(q)
            assert r.rows == exp  # scheduler-routed result parity

        # -- statements_summary: shuffle phases + percentiles ----------
        d = sql_digest(q)
        ent = next(
            e for e in STMT_SUMMARY.rows_full() if e["digest_text"] == d
        )
        assert ent["phases"]["shuffle-wait"][0] > 0
        assert ent["phases"]["shuffle-produce"][0] > 0
        assert ent["phases"]["shuffle-push"][1] > 0  # tunneled bytes
        assert ent["phases"]["fragment-dispatch"][0] > 0
        assert ent["p99_latency"] >= ent["p50_latency"] > 0
        r = sess.must_query(
            "select avg_shuffle_wait, p50_latency, p99_latency,"
            " shuffle_bytes from information_schema.statements_summary"
            f" where digest_text = '{d}'"
        )
        avg_wait, p50, p99, sbytes = r.rows[0]
        assert avg_wait > 0 and p99 >= p50 > 0 and sbytes > 0

        # -- slow_query: captured EXPLAIN ANALYZE / plan text ----------
        sess.execute(f"explain analyze {q}")
        r = sess.must_query(
            "select query, plan from information_schema.slow_query"
            " where plan != ''"
        )
        routed_plans = [p for (txt, p) in r.rows if txt == q]
        assert routed_plans and any(
            "DCNShuffle" in p for p in routed_plans
        ), "routed SELECT's capture lacks the distributed summary"
        ea_plans = [
            p for (txt, p) in r.rows if txt == f"explain analyze {q}"
        ]
        assert ea_plans and any("DCNShuffle" in p for p in ea_plans), (
            "EXPLAIN ANALYZE capture is not the instrumented text"
        )
        text = slow_file.read_text()
        assert "# Query_time:" in text and "# Phases:" in text
        assert "# Plan: " in text and "DCNShuffle" in text

        # -- cluster_links: per-peer link health -----------------------
        sched.heartbeat.beat_once()
        r = sess.must_query(
            "select kind, dst, rtt_ms, heartbeat_age_s, stall_seconds,"
            " bytes, frames, codec from"
            " information_schema.cluster_links"
        )
        controls = [row for row in r.rows if row[0] == "control"]
        tunnels = [row for row in r.rows if row[0] == "tunnel"]
        worker_addrs = {f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"}
        assert worker_addrs <= {row[1] for row in controls}
        assert any(row[2] > 0 for row in controls)  # handshake RTT
        assert all(row[3] >= 0 for row in controls)  # heartbeat age
        # worker-to-worker tunnels merged from fenced shuffle replies:
        # real bytes/frames per link, stall seconds present (>= 0)
        assert any(
            row[1] in worker_addrs and row[5] > 0 and row[6] > 0
            for row in tunnels
        )
        assert all(row[4] >= 0.0 for row in tunnels)
        assert any(row[7] == "binary" for row in tunnels)
    finally:
        sess.attach_dcn_scheduler(None)
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_metrics_schema_fleet_history(tpch_single):
    """PR 12 acceptance: the 2-process x 4-device dryrun accretes
    SQL-queryable metric HISTORY for the whole fleet. Worker processes
    sample their own registries and ship the rows piggybacked on
    fenced shuffle replies (plus the heartbeat idle-flush);
    `SELECT ... FROM metrics_schema.tidbtpu_shuffle_codec_bytes WHERE
    time >= ...` then returns sampled points for BOTH worker hosts
    with the codec label column intact, under bounded store memory,
    with the time predicate pushed into the retention rings."""
    import time as _time

    from tidb_tpu.obs.tsdb import TSDB
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
    )
    sess = tpch_single
    t_run0 = _time.time()
    try:
        q = SHUFFLE_QUERIES[0]
        exp = sess.must_query(q).rows
        for _ in range(2):
            # >= 2 rounds spaced past the worker's sample cadence so
            # each host ships at least two time points (history, not
            # a single snapshot)
            _cols, got = sched.execute_plan(_plan(sess, q))
            assert got == exp
            _time.sleep(1.1)
        # the heartbeat idle-flush: pending worker samples land even
        # with no dispatch in flight
        sched.heartbeat.beat_once()

        worker_addrs = {f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"}
        r = sess.must_query(
            "select time, instance, codec, value from "
            "metrics_schema.tidbtpu_shuffle_codec_bytes "
            f"where time >= {t_run0 - 5.0}"
        )
        assert r.rows, "no sampled shuffle history reached the store"
        hosts = {row[1] for row in r.rows}
        assert worker_addrs <= hosts, (
            f"history missing a worker host: {hosts}"
        )
        # label columns intact: the codec label survives as a column
        assert {row[2] for row in r.rows} <= {"binary", "json"}
        assert all(row[3] > 0 for row in r.rows)
        # both hosts shipped HISTORY (>= 2 distinct sample times)
        for addr in worker_addrs:
            times = {row[0] for row in r.rows if row[1] == addr}
            assert len(times) >= 2, (
                f"{addr} shipped {len(times)} sample time(s)"
            )
        # the time predicate genuinely pushed into the store: a
        # future-bounded scan materializes ZERO points while the
        # unbounded family is non-empty (were the session's hint
        # extraction deleted, the store would materialize everything
        # and last_scan_points would equal the total)
        r = sess.must_query(
            "select time from "
            "metrics_schema.tidbtpu_shuffle_codec_bytes "
            f"where time >= {t_run0 + 10 ** 6}"
        )
        assert r.rows == []
        assert TSDB.last_scan_points == 0
        assert len(TSDB.query("tidbtpu_shuffle_codec_bytes")) > 0
        # bounded memory: every ring respects the retention caps
        cap = 2 * TSDB.retention_points
        assert TSDB.point_count() <= TSDB.series_count() * cap
    finally:
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_many_session_serving_dryrun(tpch_single):
    """PR 8 serving tier: a 2-process x 4-device fleet serves 8+
    CONCURRENT session threads (each session its own Session object
    over the shared catalog, scheduler attached, admission-gated).
    Asserts per-session result parity for a mixed short/scan workload
    (HIGH_PRIORITY grouped aggregate + LOW_PRIORITY repartition join),
    that every statement was admitted through the controller, and that
    the cross-session compiled-plan cache was actually hit (> 0) — the
    per-connection worker executors and pooled control connections
    mean two sessions' identical fragments reuse one compile."""
    import threading

    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.parallel.serving import AdmissionController
    from tidb_tpu.session import Session

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    admission = AdmissionController(queue_timeout_s=300.0)
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        shuffle_min_rows=1,  # joins ride the tunnels even at dryrun SF
        admission=admission,
    )
    short_q = (
        "select high_priority l_returnflag, count(*), sum(l_quantity) "
        "from lineitem group by l_returnflag order by l_returnflag"
    )
    scan_q = (
        "select low_priority o_orderpriority, count(*), "
        "sum(l_extendedprice) from orders join lineitem "
        "on o_orderkey = l_orderkey where l_quantity < 24 "
        "group by o_orderpriority order by o_orderpriority"
    )
    exp_short = tpch_single.must_query(short_q).rows
    exp_scan = tpch_single.must_query(scan_q).rows
    hits0 = _counter_total(
        "tidbtpu_executor_shared_plan_cache_cross_session_hits_total"
    )
    errors, done = [], []

    def session_thread(i):
        try:
            sess = Session(tpch_single.catalog, db="tpch")
            sess.attach_dcn_scheduler(sched)
            for rnd in range(2):
                q, exp = (
                    (scan_q, exp_scan) if (i + rnd) % 4 == 0
                    else (short_q, exp_short)
                )
                r = sess.execute(q)
                assert r.rows == exp, (
                    f"session {i} round {rnd} parity broke"
                )
            done.append(i)
        except Exception as e:
            errors.append((i, f"{type(e).__name__}: {e}"))

    threads = [
        threading.Thread(target=session_thread, args=(i,), daemon=True)
        for i in range(8)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=480)
        hung = [t.name for t in threads if t.is_alive()]
        assert not hung, f"session threads hung: {hung}"
        assert not errors, f"serving dryrun failed: {errors[:3]}"
        assert sorted(done) == list(range(8))
        # no statement dodged the gate, none was shed on a healthy fleet
        outcomes = admission.status()["outcomes"]
        assert outcomes["admit"] >= 16, outcomes
        assert outcomes["reject"] == 0 and outcomes["timeout"] == 0
        # cross-session compile reuse really happened (worker-side
        # counters ship back on the fenced replies; coordinator-side
        # final stages share through the same cache)
        hits1 = _counter_total(
            "tidbtpu_executor_shared_plan_cache_cross_session_hits_total"
        )
        assert hits1 > hits0, (
            "no cross-session shared-plan-cache hits under 8 sessions"
        )
        assert len(sched.alive_endpoints()) == 2
    finally:
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_timeline_trace_cross_host(tpch_single):
    """PR 9 acceptance: a 2-process x 4-device shuffle dryrun captured
    by the fleet timeline tracer produces a VALID Chrome trace with:

    - process tracks for the coordinator AND both worker hosts (worker
      events ship piggybacked on the fenced replies);
    - clock-offset monotonicity: no worker event starts before its
      fragment's dispatch event on the rebased coordinator timeline;
    - the overlap proof: pipelined tasks' produce/push windows overlap
      in time, the barrier escape hatch's do not;
    - compile events carrying non-empty XLA cost_analysis attributes,
      and the per-digest cost columns populated in statements_summary.
    """
    import json as _json

    from tidb_tpu.obs.timeline import (
        TIMELINE,
        shuffle_overlap_report,
    )
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.planner.physical import SHARED_PLAN_CACHE

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    worker_addrs = {f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"}
    q = SHUFFLE_QUERIES[0]
    exp = tpch_single.must_query(q).rows
    # the compile-event assertion needs a REAL coordinator compile
    # under capture: an earlier test in the session may still pin this
    # final-stage shape in the process-wide shared plan cache (weak
    # entries live as long as any executor's LRU does), which would
    # make the fresh scheduler import instead of compile
    SHARED_PLAN_CACHE._map.clear()
    TIMELINE.start(capacity=65536)
    try:
        for pipeline in (True, False):
            sched = DCNFragmentScheduler(
                [("127.0.0.1", p1), ("127.0.0.1", p2)],
                catalog=tpch_single.catalog,
                shuffle_mode="always",
                shuffle_pipeline=pipeline,
            )
            try:
                for _ in range(2):
                    _cols, got = sched.execute_plan(
                        _plan(tpch_single, q)
                    )
                    assert got == exp
            finally:
                sched.close()
        TIMELINE.stop()

        # -- valid Chrome trace JSON with both hosts' process tracks --
        trace = _json.loads(
            _json.dumps(TIMELINE.dump())  # round-trips (serializable)
        )
        evs = trace["traceEvents"]
        procs = {
            e["args"]["name"]
            for e in evs
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert "coordinator" in procs
        assert worker_addrs <= procs, (
            f"missing worker process tracks: {procs}"
        )
        for e in evs:
            if e.get("ph") == "X":
                assert isinstance(e["ts"], float) and e["ts"] >= 0
                assert isinstance(e["dur"], float) and e["dur"] >= 0
                assert e["cat"] and e["name"] and e["pid"]

        # -- clock-offset monotonicity --------------------------------
        raw = TIMELINE.events()
        dispatches = {}
        for ph, cat, name, t0, dur, host, track, args in raw:
            if ph == "X" and cat == "fragment" and args and (
                name.startswith("dispatch")
            ):
                key = (args["host"], f"q{args['qid']}/{args['unit']}")
                dispatches[key] = min(
                    dispatches.get(key, t0), t0
                )
        assert dispatches, "no coordinator dispatch events captured"
        checked = 0
        for ph, cat, name, t0, dur, host, track, args in raw:
            if ph != "X" or host not in worker_addrs:
                continue
            if cat not in ("shuffle", "fragment"):
                continue
            d0 = dispatches.get((host, track))
            if d0 is None:
                continue
            checked += 1
            assert t0 >= d0 - 0.05, (
                f"worker event {name} on {host}/{track} starts "
                f"{d0 - t0:.3f}s before its dispatch (clock rebase "
                "broke monotonicity)"
            )
        assert checked > 0, "no worker events matched a dispatch"

        # -- overlap: pipelined yes, barrier no -----------------------
        rep = shuffle_overlap_report(raw)
        pipe_overlap = max(
            (r["produce_push_overlap_s"]
             for r in rep.values() if r["pipeline"]),
            default=0.0,
        )
        barrier_tracks = [
            r for r in rep.values()
            if not r["pipeline"] and r["push_windows"]
        ]
        assert pipe_overlap > 0.0, (
            f"pipelined produce/push windows never overlapped: {rep}"
        )
        # tolerance: event windows mix a wall-clock start with a
        # perf_counter duration, so strictly-sequential barrier phases
        # can show microsecond-scale numeric overlap — anything at ms
        # scale would be REAL overlap and a bug
        assert barrier_tracks and all(
            r["produce_push_overlap_s"] < 0.005 for r in barrier_tracks
        ), f"barrier stage shows overlap: {rep}"

        # -- compile events carry cost analysis -----------------------
        compile_costs = [
            (args or {}).get("cost_analysis")
            for ph, cat, name, t0, dur, host, track, args in raw
            if ph == "X" and cat == "compile"
        ]
        assert any(
            c and c.get("flops", 0) > 0 for c in compile_costs
        ), "no compile event carries non-empty cost_analysis"
    finally:
        TIMELINE.stop()
        TIMELINE.clear()
        for w in (w1, w2):
            w.kill()


def test_dcn_worker_death_mid_shuffle_retry_parity(tpch_single):
    """Failpoint-killed worker MID-SHUFFLE with PIPELINING ON: worker 2
    hard-exits on the first partition packet a peer pushes to it (the
    shuffle/recv site), mid-way through the survivor's chunk-granular
    pipelined push with frames already decoded-on-arrival on both ends.
    Worker 1's tunnel reports the dead peer, the coordinator verifies
    and quarantines it, re-runs the WHOLE stage on the survivor set
    (attempt 2, m=1 — upstream partitions re-shuffled to the
    survivors), the dead attempt's partially-decoded stage is fenced
    out by the attempt bump, and the rerun still matches the reference
    exactly once."""
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_pool import FailedEngineProber

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker(
        ["--die-on-fragment", "1", "--die-at", "shuffle-recv"]
    )
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
        shuffle_pipeline=True,  # explicit: retry parity WITH overlap
        shuffle_wait_timeout_s=20.0,
        prober=FailedEngineProber(initial_backoff_s=60),
    )
    try:
        q = SHUFFLE_QUERIES[0]
        exp = tpch_single.must_query(q).rows
        _cols, got = sched.execute_plan(_plan(tpch_single, q))
        assert got == exp, f"\n got={got}\n exp={exp}"
        # the stage really retried on the survivor set, pipelined
        assert sched.last_query["shuffle"]["attempts"] >= 2
        assert sched.last_query["shuffle"]["m"] == 1
        assert sched.last_query["shuffle"]["pipeline"] is True
        assert [e.port for e in sched.prober.failed_endpoints()] == [p2]
        w2.wait(timeout=30)
        assert w2.returncode == 3
        # the survivor keeps serving shuffle stages alone
        q2 = SHUFFLE_QUERIES[1]
        exp2 = tpch_single.must_query(q2).rows
        _cols, got2 = sched.execute_plan(_plan(tpch_single, q2))
        assert got2 == exp2
    finally:
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_worker_death_mid_query_retry_parity(tpch_single):
    """Failpoint-killed worker mid-query: worker 2 hard-exits AFTER
    computing its first fragment but BEFORE replying (the
    dcn/result-send site — work done, reply lost). The coordinator must
    quarantine it, re-dispatch the fragment onto the survivor, and
    still return correct results exactly once."""
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_pool import FailedEngineProber

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker(
        ["--die-on-fragment", "1", "--die-at", "result-send"]
    )
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        prober=FailedEngineProber(initial_backoff_s=60),
    )
    try:
        q = TPCH_QUERIES[2]  # join + group-by
        exp = tpch_single.must_query(q).rows
        _cols, got = sched.execute_plan(_plan(tpch_single, q))
        assert got == exp, f"\n got={got}\n exp={exp}"
        # the dead worker was quarantined, and really died via os._exit
        assert [e.port for e in sched.prober.failed_endpoints()] == [p2]
        w2.wait(timeout=30)
        assert w2.returncode == 3
        # the survivor keeps serving (fewer fragments per query)
        q2 = TPCH_QUERIES[0]
        exp2 = tpch_single.must_query(q2).rows
        _cols, got2 = sched.execute_plan(_plan(tpch_single, q2))
        assert got2 == exp2
    finally:
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_fleet_cancellation_kill_and_max_execution_time(tpch_single):
    """ISSUE 10 acceptance: KILL and max_execution_time on a routed
    query cancel WORKER-SIDE fragments and shuffle tasks. Both workers
    are armed with a worker-side hang failpoint (shuffle/produce
    sleeps 30s via --chaos-spec); the kill must broadcast cancel_query
    so worker task threads exit and staged buffers free LONG before
    the hang would, and the killed statement's flight record still
    lands in statements_summary with its phase breakdown."""
    import json as _json
    import threading
    import time

    from tidb_tpu.chaos.schedule import Fault
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_rpc import EngineClient
    from tidb_tpu.utils.metrics import STMT_SUMMARY, sql_digest

    # a 2-hit hang window per worker: the KILL statement consumes the
    # first hit, the max_execution_time statement the second, and the
    # final parity query runs against healthy workers
    spec = _json.dumps([
        Fault("worker-hang", "shuffle/produce", "hang", n=2,
              param=30.0).to_dict(),
    ])
    w1, p1 = _spawn_dcn_worker(["--chaos-spec", spec])
    w2, p2 = _spawn_dcn_worker(["--chaos-spec", spec])
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
        shuffle_wait_timeout_s=60.0,
    )
    sess = tpch_single
    q = SHUFFLE_QUERIES[0]
    sess.attach_dcn_scheduler(sched)

    def assert_workers_clean():
        """Worker task threads exited and staged buffers freed —
        polled over the engine_status introspection frame."""
        deadline = time.monotonic() + 10.0
        while True:
            states = []
            for port in (p1, p2):
                c = EngineClient("127.0.0.1", port, timeout_s=5.0)
                try:
                    states.append(c.engine_status())
                finally:
                    c.close()
            if all(
                st["stages_buffered"] == 0
                and not st["shuffle_threads"]
                for st in states
            ):
                return
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"worker-side work outlived the kill: {states}"
                )
            time.sleep(0.1)

    try:
        # -- KILL QUERY mid-hang ---------------------------------------
        errors = []

        def runner():
            try:
                sess.execute(q)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        t = threading.Thread(target=runner, daemon=True)
        t0 = time.monotonic()
        t.start()
        # kill only once the dispatch REACHED the workers (their
        # stores opened a stage record) — a blind sleep races worker
        # startup and can kill before/never-reaching the hung produce
        wait_deadline = time.monotonic() + 30.0
        while time.monotonic() < wait_deadline:
            opened = 0
            for port in (p1, p2):
                c = EngineClient("127.0.0.1", port, timeout_s=5.0)
                try:
                    opened += c.engine_status()["stages_buffered"]
                finally:
                    c.close()
            if opened >= 2:
                break
            time.sleep(0.1)
        assert opened >= 2, "dispatch never reached the workers"
        time.sleep(0.3)  # both tasks are in the hung produce now
        sess.killer.kill()
        t.join(timeout=30)
        assert not t.is_alive(), "killed statement never returned"
        wall = time.monotonic() - t0
        assert errors and "interrupted" in errors[0], errors
        assert wall < 25.0, (
            f"kill took {wall:.1f}s — the 30s worker hang was not "
            "cancelled"
        )
        assert_workers_clean()
        # the killed statement's flight record landed, phases intact
        ent = next(
            e for e in STMT_SUMMARY.rows_full()
            if e["digest_text"] == sql_digest(q)
        )
        assert ent["exec_count"] >= 1
        assert ent["max_latency"] > 0  # the wait it paid is visible
        assert "parse" in ent["phases"] and "plan" in ent["phases"]

        # -- max_execution_time mid-hang -------------------------------
        # (the second --chaos-spec hang hit arms each worker's n=1
        # once; re-arm by statement: the deadline also PROPAGATES so
        # the worker self-cancels even without the coordinator watch)
        sess.execute("set max_execution_time = 1200")
        t0 = time.monotonic()
        try:
            sess.execute(q)
            raise AssertionError("max_execution_time never fired")
        except Exception as e:
            assert "interrupted" in str(e), e
        wall = time.monotonic() - t0
        assert wall < 20.0, f"deadline abort took {wall:.1f}s"
        sess.execute("set max_execution_time = 0")
        assert_workers_clean()
        # the fleet is healthy after both aborts: same query, parity
        exp = None
        sess.attach_dcn_scheduler(None)
        exp = sess.must_query(q).rows
        sess.attach_dcn_scheduler(sched)
        r = sess.execute(q)
        assert r.rows == exp
    finally:
        sess.attach_dcn_scheduler(None)
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_multihost_chaos_composed_faults(tpch_single):
    """ISSUE 10 acceptance: a seeded chaos schedule composing crash +
    hang + frame loss over the 2-process dryrun — worker 1 hard-exits
    (os._exit) on a pushed frame, worker 0 hangs a produce and drops
    frames probabilistically — passes all fleet invariants with exact
    row parity, and the same seed replays the same fault schedule
    deterministically."""
    import json as _json
    import time

    from tidb_tpu.chaos.schedule import generate_worker_specs
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_pool import FailedEngineProber
    from tidb_tpu.server.engine_rpc import EngineClient

    SEED = 1310
    specs = generate_worker_specs(SEED, 2)
    assert specs == generate_worker_specs(SEED, 2)  # replayable
    classes = {f["cls"] for spec in specs for f in spec}
    assert {"worker-crash", "worker-hang", "frame-drop"} <= classes
    workers, ports = [], []
    for spec in specs:
        w, p = _spawn_dcn_worker(
            ["--chaos-spec", _json.dumps(spec)]
        )
        workers.append(w)
        ports.append(p)
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p) for p in ports],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
        shuffle_wait_timeout_s=15.0,
        retry_backoff_s=0.05,
        prober=FailedEngineProber(initial_backoff_s=60),
    )
    t0 = time.monotonic()
    try:
        for q in SHUFFLE_QUERIES:
            exp = tpch_single.must_query(q).rows
            _cols, got = sched.execute_plan(_plan(tpch_single, q))
            assert got == exp, (
                f"chaos parity broke (seed {SEED}):\n got={got}\n"
                f" exp={exp}"
            )
        # the crash CLASS really fired: the last worker died via
        # os._exit(3) and was quarantined; survivors carried parity
        workers[-1].wait(timeout=30)
        assert workers[-1].returncode == 3
        assert [e.port for e in sched.prober.failed_endpoints()] == (
            [ports[-1]]
        )
        # bounded recovery wall for the whole composed run
        assert time.monotonic() - t0 < 120.0
        # no leaked coordinator-side leases, no orphaned buffers on
        # the SURVIVING worker
        assert all(v == 0 for v in sched.pool_leased().values())
        c = EngineClient("127.0.0.1", ports[0], timeout_s=5.0)
        try:
            st = c.engine_status()
        finally:
            c.close()
        assert st["stages_buffered"] == 0
        assert not st["shuffle_threads"]
    finally:
        sched.close()
        for w in workers:
            w.kill()


#: the ISSUE 11 acceptance shape: join -> RE-KEYED GROUP BY (the group
#: key is not a join key, and the DISTINCT makes the aggregate
#: non-decomposable — the single-cut group-by re-scans the unsliced
#: orders side on every host) -> ORDER BY LIMIT (a range exchange with
#: per-partition top-K)
DAG_QUERY = (
    "select o_orderpriority, count(distinct l_suppkey), "
    "sum(l_extendedprice) from orders join lineitem "
    "on o_orderkey = l_orderkey group by o_orderpriority "
    "order by sum(l_extendedprice) desc limit 3"
)


def test_dcn_shuffle_dag_tpch_parity(tpch_single):
    """ISSUE 11 acceptance: the join -> re-keyed GROUP BY -> ORDER BY
    LIMIT query executes as >= 2 chained shuffle stages on the
    2-process dryrun with BOTH join sides fragment-sliced — per-host
    scanned base rows ~ total/N, vs the single-cut group-by baseline
    that re-scans the whole unsliced orders side on every host — the
    range exchange returns exact global order at row parity, the
    exchange bytes bypass the coordinator (staged-delta invariant),
    and the sampled boundaries are deterministic across runs."""
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_rpc import EngineClient

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    cat = tpch_single.catalog
    n_orders = cat.table("tpch", "orders").nrows
    n_lineitem = cat.table("tpch", "lineitem").nrows
    total = n_orders + n_lineitem
    exp = tpch_single.must_query(DAG_QUERY).rows
    plan = _plan(tpch_single, DAG_QUERY)

    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=cat, shuffle_mode="always", shuffle_dag="always",
    )
    try:
        # the planner really chained stages: hash join -> hash re-key
        # -> range order-by
        kind, cut = sched._choose_cut(plan)
        assert kind == "dag"
        assert [s.exchange for s in cut.stages] == [
            "hash", "hash", "range",
        ]
        staged0 = _counter_total("tidbtpu_dcn_bytes_staged")
        _cols, got = sched.execute_plan(plan, cut_hint=(kind, cut))
        # exact global order parity against local execution (the
        # order-preserving concat, not a coordinator re-sort)
        assert got == exp, f"\n got={got}\n exp={exp}"
        # exchange data rode worker-to-worker tunnels, NOT the
        # coordinator (the staged-delta invariant of PR 3, now held
        # across a 3-stage chain)
        assert _counter_total("tidbtpu_dcn_bytes_staged") == staged0
        stages = sched.last_query["shuffle_stages"]
        frags = sched.last_query["fragments"]
        assert [s["stage"] for s in stages] == [0, 1, 2]
        # BOTH join sides fragment-sliced: each host scanned ~ total/2
        # base rows in stage 0 and NOTHING after (stages 1-2 re-stage
        # held outputs)
        for f in [f for f in frags if f["stage"] == 0]:
            assert abs(f["scan_rows"] - total / 2) <= 2, f
        assert all(
            f["scan_rows"] == 0 for f in frags if f["stage"] > 0
        )
        # per-partition top-K: the range stage shipped at most K rows
        # per partition
        for f in [f for f in frags if f["stage"] == 2]:
            assert f["rows"] <= 3
        # boundary-sampling determinism: a second run cuts the SAME
        # boundaries (fixed sample seed)
        b1 = stages[2]["boundaries"]
        sched.execute_plan(plan, cut_hint=(kind, cut))
        b2 = sched.last_query["shuffle_stages"][2]["boundaries"]
        assert b1 == b2 and b1  # non-trivial and identical
        # no held stage outputs or buffered stages linger on workers
        for port in (p1, p2):
            c = EngineClient("127.0.0.1", port, timeout_s=10.0)
            try:
                st = c.engine_status()
            finally:
                c.close()
            assert st["stages_buffered"] == 0
            assert st["held_outputs"] == 0
    finally:
        sched.close()

    # the single-cut BASELINE (shuffle_dag="never"): the DISTINCT
    # group-by cut slices only lineitem — every host re-scans the
    # whole orders side (the N x wasted scan work the DAG removes)
    sched2 = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=cat, shuffle_mode="always", shuffle_dag="never",
    )
    try:
        kind2, cut2 = sched2._choose_cut(plan)
        assert kind2 == "shuffle" and cut2.kind == "groupby"
        _cols, got2 = sched2.execute_plan(plan, cut_hint=(kind2, cut2))
        assert got2 == exp
        for f in sched2.last_query["fragments"]:
            # per-host scan = its lineitem slice + ALL of orders
            assert abs(
                f["scan_rows"] - (n_lineitem / 2 + n_orders)
            ) <= 2, f
    finally:
        sched2.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_multihost_chaos_interstage_kill(tpch_single):
    """ISSUE 11 chaos acceptance: a composed-fault episode killing a
    worker BETWEEN stage N and stage N+1 of the DAG (os._exit the
    first time it reads a held StageInput, while every worker also
    drops pushed frames probabilistically). The coordinator must
    quarantine the dead worker, restart the WHOLE chain on the
    survivor under a new attempt (the superseded attempt's held
    partitions are fenced by the attempt key), and still return exact
    parity — with no leaked held outputs, buffers, threads, or
    leases."""
    import json as _json
    import time

    from tidb_tpu.chaos.schedule import generate_interstage_kill_specs
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_pool import FailedEngineProber
    from tidb_tpu.server.engine_rpc import EngineClient

    SEED = 2718
    specs = generate_interstage_kill_specs(SEED, 2)
    assert specs == generate_interstage_kill_specs(SEED, 2)
    assert specs[-1][-1]["site"] == "shuffle/stage-input"
    assert specs[-1][-1]["kind"] == "exit"
    workers, ports = [], []
    for spec in specs:
        w, p = _spawn_dcn_worker(["--chaos-spec", _json.dumps(spec)])
        workers.append(w)
        ports.append(p)
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p) for p in ports],
        catalog=tpch_single.catalog,
        shuffle_mode="always", shuffle_dag="always",
        shuffle_wait_timeout_s=15.0,
        retry_backoff_s=0.05,
        prober=FailedEngineProber(initial_backoff_s=60),
    )
    t0 = time.monotonic()
    try:
        exp = tpch_single.must_query(DAG_QUERY).rows
        _cols, got = sched.execute_plan(_plan(tpch_single, DAG_QUERY))
        assert got == exp, (
            f"interstage-kill parity broke (seed {SEED}):\n"
            f" got={got}\n exp={exp}"
        )
        # the kill really happened BETWEEN stages: worker 2 died via
        # os._exit(3) on the stage-input site and was quarantined
        workers[-1].wait(timeout=30)
        assert workers[-1].returncode == 3
        assert [e.port for e in sched.prober.failed_endpoints()] == (
            [ports[-1]]
        )
        # the chain retried on the survivor set
        assert any(
            s["attempts"] >= 2
            for s in sched.last_query["shuffle_stages"]
        )
        assert time.monotonic() - t0 < 120.0
        # invariant audit on the survivor: nothing leaked
        assert all(v == 0 for v in sched.pool_leased().values())
        c = EngineClient("127.0.0.1", ports[0], timeout_s=5.0)
        try:
            st = c.engine_status()
        finally:
            c.close()
        assert st["stages_buffered"] == 0
        assert st["held_outputs"] == 0
        assert not st["shuffle_threads"]
    finally:
        sched.close()
        for w in workers:
            w.kill()


def test_dcn_delta_writes_mid_run_freshness_modes(tpch_single):
    """HTAP delta tier on the REAL 2-process x 4-device dryrun
    (workers are delta replicas): coordinator writes land mid-run —
    INSERT/DELETE on a loaded table plus a table the workers never
    loaded — and routed SELECTs honor both freshness modes with zero
    local fallbacks and exact parity against a full local reload."""
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.session import Session

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    sess = tpch_single
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=sess.catalog,
    )
    sess.attach_dcn_scheduler(sched)
    fb0 = _counter_total("tidbtpu_session_dcn_route_fallbacks")
    q_orders = (
        "select o_orderstatus, count(*), sum(o_shippriority) "
        "from orders group by o_orderstatus order by o_orderstatus"
    )
    q_hot = "select count(*), sum(v) from hot_writes"
    try:
        base = sess.must_query(q_orders).rows
        assert sess._last_dcn_routed

        # writes land mid-run: a loaded table takes typed deltas, a
        # NEW table materializes on the replicas from the sync frames
        sess.execute(
            "insert into orders values "
            "(4000001, 1, 'O', 123.45, '1995-01-01', '1-URGENT', 7, 'dx'),"
            "(4000002, 2, 'F', 456.78, '1996-02-02', '2-HIGH', 7, 'dx')"
        )
        sess.execute("delete from orders where o_orderkey = 4000002")
        sess.execute(
            "create table hot_writes (k bigint primary key, v bigint)"
        )
        sess.execute("insert into hot_writes values (1, 10), (2, 20)")

        # read-your-writes: every committed write visible, routed
        fresh = Session(sess.catalog, db="tpch")
        for q in (q_orders, q_hot):
            got = sess.execute(q)
            assert got.rows == fresh.execute(q).rows, q
            assert sess._last_dcn_routed, q
        assert got.rows == [(2, 30)]  # q_hot: exact committed image

        # bounded staleness: still routed, zero waits — and because
        # the read-your-writes reads above already shipped the log,
        # the acked floor covers every write
        sess.execute("set tidb_tpu_read_freshness = 'bounded'")
        w0 = _counter_total("tidbtpu_delta_ryw_wait_seconds")
        for q in (q_orders, q_hot):
            got = sess.execute(q)
            assert got.rows == fresh.execute(q).rows, q
            assert sess._last_dcn_routed, q
        assert _counter_total("tidbtpu_delta_ryw_wait_seconds") == w0

        # bounded lags behind an unshipped write (staleness is real,
        # not a fresh read in disguise)...
        sess.execute("insert into hot_writes values (3, 30)")
        assert sess.execute(q_hot).rows == [(2, 30)]
        assert sess._last_dcn_routed
        # ...until read-your-writes ships + waits
        sess.execute("set tidb_tpu_read_freshness = 'read_your_writes'")
        assert sess.execute(q_hot).rows == [(3, 60)]
        assert sess._last_dcn_routed

        # a compaction barrier folds the deltas into BOTH worker
        # processes' base blocks; parity holds after
        assert sched.delta.compact_now(catalog=sess.catalog)
        post = sess.execute(q_orders)
        assert post.rows == fresh.execute(q_orders).rows
        assert sess._last_dcn_routed
        assert post.rows != base  # the writes are visible in the fold
        assert sess.execute(q_hot).rows == [(3, 60)]

        # ZERO local fallbacks across the whole scenario
        assert _counter_total(
            "tidbtpu_session_dcn_route_fallbacks"
        ) == fb0
    finally:
        sess.attach_dcn_scheduler(None)
        sched.close()
        for w in (w1, w2):
            w.kill()

def test_dcn_topsql_fleet_attribution(tpch_single):
    """PR 14 acceptance: with ``tidb_enable_top_sql = ON`` the
    2-process x 4-device dryrun attributes sampled CPU per statement
    digest on EVERY host — workers arm their samplers from the
    dispatch-carried config, attribute task samples to the dispatched
    digest (so a finished/foreign qid can never be charged), and ship
    windows + collapsed stacks piggybacked on the fenced replies.
    information_schema.top_sql then shows per-instance rows for both
    workers, the tsdb series carry clock-rebased worker windows, and
    the merged /profile export is non-empty."""
    import time as _time

    from tidb_tpu.obs.profiler import OTHERS_DIGEST, TOPSQL, digest_of
    from tidb_tpu.obs.tsdb import TSDB
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.utils.metrics import sql_digest

    w1, p1 = _spawn_dcn_worker()
    w2, p2 = _spawn_dcn_worker()
    sess = tpch_single
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p1), ("127.0.0.1", p2)],
        catalog=sess.catalog,
        shuffle_mode="always",
    )
    sess.attach_dcn_scheduler(sched)
    TOPSQL.store.reset()
    t_run0 = _time.time()
    worker_addrs = {f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"}
    try:
        sess.execute("set global tidb_enable_top_sql = ON")
        assert TOPSQL.running()
        q = SHUFFLE_QUERIES[0]
        exp = sess.must_query(q).rows
        # several rounds so worker samplers (armed by the FIRST
        # dispatch's config) accumulate samples on later tasks
        for _ in range(4):
            got = sess.execute(q)
            assert [tuple(r) for r in got.rows] == exp
        # the heartbeat idle-flush ships anything still pending
        sched.heartbeat.beat_once()

        rows = sess.execute(
            "select rank, instance, digest, cpu_ms, device_ms, "
            "stall_ms, samples from information_schema.top_sql "
            "order by rank, instance"
        ).rows
        assert rows
        hosts = {r[1] for r in rows}
        assert worker_addrs <= hosts, (
            f"top_sql missing a worker instance: {hosts}"
        )
        assert "coordinator" in hosts
        # every worker row carries real sampled attribution
        for r in rows:
            if r[1] in worker_addrs:
                assert r[6] > 0  # samples
                assert r[3] + r[4] + r[5] > 0  # cpu+device+stall ms

        # zero attribution to finished/foreign qids: workers learn
        # digests ONLY from dispatches, so every worker-side digest
        # must be one this coordinator actually ran (or the fold-in
        # aggregate) — a foreign coordinator's digest cannot appear
        ran = {
            digest_of(sql_digest(stmt))
            for stmt in (q, "set global tidb_enable_top_sql = ON")
        }
        for r in TOPSQL.store.rows():
            if r["instance"] in worker_addrs:
                assert r["digest"] in ran | {OTHERS_DIGEST}, (
                    f"foreign digest {r['digest']} attributed on "
                    f"{r['instance']}"
                )

        # worker windows reached the tsdb CLOCK-REBASED: every stored
        # point of the topsql families sits inside the run's
        # coordinator-clock window (a skewed/unrebased worker stamp
        # would land outside)
        pts = [
            (t, host)
            for t, host, _lv, _v, _res in TSDB.query(
                "tidbtpu_topsql_cpu_seconds"
            )
            if host in worker_addrs
        ]
        assert pts, "no worker topsql series reached the tsdb"
        now = _time.time()
        for t, host in pts:
            assert t_run0 - 30 <= t <= now + 30, (
                f"unrebased worker window ts {t} from {host}"
            )

        # the /profile export half: fleet-merged collapsed stacks are
        # non-empty and include worker-shipped towers
        merged = TOPSQL.store.collapsed()
        assert merged
        for addr in worker_addrs:
            assert TOPSQL.store.collapsed(instance=addr), (
                f"no collapsed stacks shipped from {addr}"
            )
        for line in merged:
            stack, count = line.rsplit(" ", 1)
            assert int(count) >= 1 and ";" in stack
    finally:
        sess.execute("set global tidb_enable_top_sql = OFF")
        sess.attach_dcn_scheduler(None)
        TOPSQL.store.reset()
        sched.close()
        for w in (w1, w2):
            w.kill()


def test_dcn_aqe_replan_crash_retry_parity(tpch_single):
    """ISSUE 15 chaos acceptance (replan-crash): worker 2 hard-exits
    (os._exit) the first time an ADAPTIVE stage task reaches it — the
    window between the coordinator's re-plan decision (a probe-observed
    collapsed join side switching repartition to broadcast) and the
    switched stage's completion — while both workers also drop a
    seeded fraction of pushed frames. The coordinator must quarantine
    the dead worker and retry the WHOLE stage, probe round included,
    on the survivor set (m=1: the probe gate stands down, the stage
    runs plain) with exact row parity and the adaptive decision
    counted from the first attempt."""
    import json as _json

    from tidb_tpu.chaos.schedule import generate_replan_kill_specs
    from tidb_tpu.parallel import aqe
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_pool import FailedEngineProber

    SEED = 1501
    specs = generate_replan_kill_specs(SEED, 2)
    assert specs == generate_replan_kill_specs(SEED, 2)  # replayable
    assert any(
        f["site"] == "aqe/switched-stage" and f["kind"] == "exit"
        for f in specs[-1]
    )
    workers, ports = [], []
    for spec in specs:
        w, p = _spawn_dcn_worker(["--chaos-spec", _json.dumps(spec)])
        workers.append(w)
        ports.append(p)
    # static est (orders at full table size) says repartition; the
    # o_custkey filter collapses the observed side under the bar, so
    # the probe's broadcast-switch decision targets worker 2 with an
    # adaptive stage task — its armed exit fires exactly there
    orders_rows = tpch_single.catalog.table("tpch", "orders").nrows
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p) for p in ports],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
        shuffle_dag="never",
        shuffle_skew_ratio=1.5,
        shuffle_broadcast_rows=max(orders_rows // 4, 64),
        # the killed worker dies BEFORE producing, so the survivor
        # detects the loss only by wait expiry (the serve-load 10s
        # loopback stance) — the healthy retry is m=1 and never waits
        shuffle_wait_timeout_s=10.0,
        prober=FailedEngineProber(initial_backoff_s=60),
    )
    try:
        q = (
            "select count(*), sum(l_quantity) from lineitem "
            "join orders on l_orderkey = o_orderkey "
            "where o_custkey < 5"
        )
        exp = tpch_single.must_query(q).rows
        before = aqe.decision_counts().get("broadcast-switch", 0.0)
        _cols, got = sched.execute_plan(_plan(tpch_single, q))
        assert got == exp, f"\n got={got}\n exp={exp}"
        st = sched.last_query["shuffle"]
        # the whole stage retried on the survivor set after the kill
        assert st["attempts"] >= 2
        assert st["m"] == 1
        # the decision genuinely fired before the crash
        assert aqe.decision_counts().get(
            "broadcast-switch", 0.0
        ) >= before + 1
        # ...but the m=1 retry ran the PLAIN cut: the superseded
        # attempt's token must not linger on the reported summary
        # (adaptive= has to agree with what the survivor actually
        # ran; the counter above is the record that it fired)
        assert not st.get("adaptive")
        assert [e.port for e in sched.prober.failed_endpoints()] == [
            ports[-1]
        ]
        workers[-1].wait(timeout=30)
        assert workers[-1].returncode == 3
        # the survivor keeps serving adaptive-eligible queries alone
        _cols, got2 = sched.execute_plan(_plan(tpch_single, q))
        assert got2 == exp
    finally:
        sched.close()
        for w in workers:
            w.kill()


def test_dcn_runtime_filter_crash_retry_parity(tpch_single):
    """ISSUE 19 chaos acceptance (filter-crash): worker 2 hard-exits
    (os._exit) the first time the broadcast runtime filter reaches its
    produce path — the window between the coordinator's probe-round
    merge + broadcast and the filtered stage's completion — while both
    workers also drop a seeded fraction of pushed frames. The
    coordinator must quarantine the dead worker and retry the whole
    stage on the survivor set (m=1: the filter stands down, the stage
    ships unfiltered) with exact row parity and no stale rf= on the
    reported summary."""
    import json as _json

    from tidb_tpu.chaos.schedule import generate_filter_kill_specs
    from tidb_tpu.parallel import aqe
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.server.engine_pool import FailedEngineProber

    SEED = 1901
    specs = generate_filter_kill_specs(SEED, 2)
    assert specs == generate_filter_kill_specs(SEED, 2)  # replayable
    assert any(
        f["site"] == "shuffle/filter" and f["kind"] == "exit"
        for f in specs[-1]
    )
    workers, ports = [], []
    for spec in specs:
        w, p = _spawn_dcn_worker(["--chaos-spec", _json.dumps(spec)])
        workers.append(w)
        ports.append(p)
    sched = DCNFragmentScheduler(
        [("127.0.0.1", p) for p in ports],
        catalog=tpch_single.catalog,
        shuffle_mode="always",
        shuffle_dag="never",
        runtime_filter="always",
        # the killed worker dies mid-produce, so the survivor detects
        # the loss only by wait expiry; the healthy retry never waits
        shuffle_wait_timeout_s=10.0,
        prober=FailedEngineProber(initial_backoff_s=60),
    )
    try:
        q = (
            "select count(*), sum(l_quantity) from lineitem "
            "join orders on l_orderkey = o_orderkey "
            "where o_custkey < 5"
        )
        exp = tpch_single.must_query(q).rows
        before = aqe.decision_counts().get("runtime-filter", 0.0)
        _cols, got = sched.execute_plan(_plan(tpch_single, q))
        assert got == exp, f"\n got={got}\n exp={exp}"
        st = sched.last_query["shuffle"]
        # the whole stage retried on the survivor set after the kill
        assert st["attempts"] >= 2
        assert st["m"] == 1
        # the decision genuinely fired before the crash...
        assert aqe.decision_counts().get(
            "runtime-filter", 0.0
        ) >= before + 1
        # ...but the m=1 retry stood the filter down: the superseded
        # attempt's rf must not linger on the reported summary
        assert "rf" not in st
        assert [e.port for e in sched.prober.failed_endpoints()] == [
            ports[-1]
        ]
        workers[-1].wait(timeout=30)
        assert workers[-1].returncode == 3
        # the survivor keeps serving filter-eligible queries alone
        _cols, got2 = sched.execute_plan(_plan(tpch_single, q))
        assert got2 == exp
    finally:
        sched.close()
        for w in workers:
            w.kill()
