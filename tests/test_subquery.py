"""Correlated and uncorrelated subqueries (reference:
pkg/planner/core/expression_rewriter.go semi-join rewrites and
decorrelateSolver in optimizer.go:98-123; null-aware anti join in
pkg/executor/join/joiner.go)."""

import pytest

from tidb_tpu.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.must_exec("create database if not exists test")
    s.must_exec(
        "create table emp (id int, dept int, salary int, name varchar(20))"
    )
    s.must_exec(
        "insert into emp values (1, 10, 100, 'a'), (2, 10, 200, 'b'), "
        "(3, 20, 150, 'c'), (4, 20, 50, 'd'), (5, 30, 300, 'e'), "
        "(6, null, 75, 'f')"
    )
    s.must_exec("create table dept (id int, dname varchar(20))")
    s.must_exec("insert into dept values (10, 'x'), (20, 'y'), (40, 'z')")
    return s


def test_uncorrelated_in(sess):
    r = sess.must_query(
        "select id from emp where dept in (select id from dept) order by id"
    )
    assert [t[0] for t in r.rows] == [1, 2, 3, 4]


def test_uncorrelated_not_in_null_aware(sess):
    # dept has no NULLs -> rows with emp.dept NULL are dropped (NULL NOT IN
    # (...) is UNKNOWN), rows 5 survive
    r = sess.must_query(
        "select id from emp where dept not in (select id from dept) order by id"
    )
    assert [t[0] for t in r.rows] == [5]
    # now a NULL in the build side: NOT IN returns no rows at all
    sess.must_exec("insert into dept values (null, 'w')")
    r = sess.must_query(
        "select id from emp where dept not in (select id from dept)"
    )
    assert r.rows == []


def test_uncorrelated_exists(sess):
    r = sess.must_query(
        "select count(*) from emp where exists (select 1 from dept where id = 40)"
    )
    assert r.rows[0][0] == 6
    r = sess.must_query(
        "select count(*) from emp where exists (select 1 from dept where id = 99)"
    )
    assert r.rows[0][0] == 0
    r = sess.must_query(
        "select count(*) from emp where not exists (select 1 from dept where id = 99)"
    )
    assert r.rows[0][0] == 6


def test_correlated_exists(sess):
    r = sess.must_query(
        "select id from emp e where exists "
        "(select 1 from dept d where d.id = e.dept) order by id"
    )
    assert [t[0] for t in r.rows] == [1, 2, 3, 4]


def test_correlated_not_exists(sess):
    # NULL dept never matches -> NOT EXISTS keeps it (3-valued logic only
    # bites for NOT IN)
    r = sess.must_query(
        "select id from emp e where not exists "
        "(select 1 from dept d where d.id = e.dept) order by id"
    )
    assert [t[0] for t in r.rows] == [5, 6]


def test_correlated_exists_with_filter(sess):
    r = sess.must_query(
        "select id from emp e where exists "
        "(select 1 from dept d where d.id = e.dept and d.dname = 'x') "
        "order by id"
    )
    assert [t[0] for t in r.rows] == [1, 2]


def test_correlated_in(sess):
    r = sess.must_query(
        "select e.id from emp e where e.dept in "
        "(select d.id from dept d where d.id = e.dept) order by id"
    )
    assert [t[0] for t in r.rows] == [1, 2, 3, 4]


def test_correlated_scalar_avg(sess):
    # employees above their department average
    r = sess.must_query(
        "select id from emp e where salary > "
        "(select avg(salary) from emp e2 where e2.dept = e.dept) order by id"
    )
    assert [t[0] for t in r.rows] == [2, 3]


def test_correlated_scalar_in_arithmetic(sess):
    # TPC-H Q17 pattern: compare against a scaled aggregate
    r = sess.must_query(
        "select id from emp e where salary < "
        "(select 0.5 * max(salary) from emp e2 where e2.dept = e.dept) "
        "order by id"
    )
    assert [t[0] for t in r.rows] == [4]


def test_correlated_scalar_count_empty_group(sess):
    # count over an empty correlated set is 0, not NULL
    r = sess.must_query(
        "select id from emp e where "
        "(select count(*) from dept d where d.id = e.dept) = 0 order by id"
    )
    assert [t[0] for t in r.rows] == [5, 6]


def test_scalar_uncorrelated_still_works(sess):
    r = sess.must_query(
        "select id from emp where salary > (select avg(salary) from emp) "
        "order by id"
    )
    # avg = 875/6 = 145.83 -> salaries 200, 150, 300 qualify
    assert [t[0] for t in r.rows] == [2, 3, 5]


def test_exists_respects_limit_zero(sess):
    r = sess.must_query(
        "select count(*) from emp where exists (select 1 from dept limit 0)"
    )
    assert r.rows[0][0] == 0


def test_correlated_not_in_rejected(sess):
    with pytest.raises(Exception, match="NOT IN"):
        sess.execute(
            "select id from emp e where dept not in "
            "(select d.id from dept d where d.id = e.dept)"
        )


def test_tpch_q21_q22_shapes(sess):
    """Nested EXISTS + NOT EXISTS in one WHERE (the Q21 shape)."""
    r = sess.must_query(
        "select e.id from emp e where "
        "exists (select 1 from emp e2 where e2.dept = e.dept and e2.id <> e.id) "
        "and not exists (select 1 from emp e3 where e3.dept = e.dept "
        "and e3.salary > e.salary) order by e.id"
    )
    # depts with >1 member: 10 (1,2), 20 (3,4); top earners: 2 and 3
    assert [t[0] for t in r.rows] == [2, 3]


def test_exists_aggregate_subquery_always_true(sess):
    """An aggregate subquery without GROUP BY returns exactly one row,
    so EXISTS over it is unconditionally true (MySQL semantics)."""
    r = sess.must_query(
        "select count(*) from emp where exists "
        "(select count(*) from dept d where d.id = emp.dept)"
    )
    assert r.rows[0][0] == 6
    r = sess.must_query(
        "select count(*) from emp where not exists "
        "(select count(*) from dept d where d.id = emp.dept)"
    )
    assert r.rows[0][0] == 0


def test_correlated_scalar_count_in_expression(sess):
    """count nested in arithmetic still folds to 0 over empty groups."""
    r = sess.must_query(
        "select id from emp e where "
        "(select count(*) * 1 from dept d where d.id = e.dept) = 0 "
        "order by id"
    )
    assert [t[0] for t in r.rows] == [5, 6]


def test_correlated_in_aggregate_rejected(sess):
    with pytest.raises(Exception, match="aggregate"):
        sess.execute(
            "select id from emp e where id in "
            "(select max(d.id) from dept d where d.id = e.dept)"
        )


class TestMarkJoins:
    """IN/EXISTS subqueries in VALUE positions (select items, CASE,
    DML WHERE) via mark joins — the reference's LeftOuterSemiJoin with
    a mark column (expression_rewriter.go). The mark's validity carries
    the three-valued IN NULL semantics."""

    @pytest.fixture()
    def s(self):
        from tidb_tpu.session.session import Session

        s = Session()
        s.execute("create table t (a int, b varchar(6))")
        s.execute("insert into t values (1,'x'),(2,'y'),(3,'x'),(null,'z')")
        s.execute("create table u (a int)")
        s.execute("insert into u values (1),(3)")
        s.execute("create table un (a int)")
        s.execute("insert into un values (1),(null)")
        return s

    def test_in_as_value(self, s):
        assert s.execute(
            "select a, a in (select a from u) from t order by a"
        ).rows == [(None, None), (1, True), (2, False), (3, True)]

    def test_three_valued_null_semantics(self, s):
        # build side contains NULL: no-match becomes NULL, not False
        assert s.execute(
            "select a, a in (select a from un) from t order by a"
        ).rows == [(None, None), (1, True), (2, None), (3, None)]
        assert s.execute(
            "select a, a not in (select a from un) from t order by a"
        ).rows == [(None, None), (1, False), (2, None), (3, None)]

    def test_case_when_in(self, s):
        assert s.execute(
            "select case when a in (select a from u) then 'in' else 'out' "
            "end from t order by a"
        ).rows == [("out",), ("in",), ("out",), ("in",)]

    def test_update_where_in_subquery(self, s):
        r = s.execute("update t set b = 'm' where a in (select a from u)")
        assert r.affected == 2
        assert s.execute(
            "select b from t where a is not null order by a"
        ).rows == [("m",), ("y",), ("m",)]

    def test_delete_where_in_subquery(self, s):
        r = s.execute("delete from t where a in (select a from u)")
        assert r.affected == 2
        assert s.execute("select count(*) from t").rows == [(2,)]

    def test_correlated_exists_as_value(self, s):
        assert s.execute(
            "select exists (select 1 from u where u.a = t.a) from t "
            "order by t.a"
        ).rows == [(False,), (True,), (False,), (True,)]

    def test_aggregate_over_mark(self, s):
        assert s.execute(
            "select count(*), sum(a in (select a from u)) from t"
        ).rows == [(4, 2)]

    def test_uncorrelated_exists_folds(self, s):
        assert s.execute(
            "select a, exists (select 1 from u) from t where a = 1"
        ).rows == [(1, True)]
        assert s.execute(
            "select a, not exists (select 1 from u where a > 100) from t "
            "where a = 1"
        ).rows == [(1, True)]

    def test_mesh_parity(self):
        from tidb_tpu.session.session import Session

        sm, s1 = Session(mesh_devices=8), Session()
        for ss in (sm, s1):
            ss.execute("create table t (a int)")
            ss.execute("create table u (a int)")
            ss.execute(
                "insert into t values "
                + ",".join(f"({i % 50})" for i in range(400))
            )
            ss.execute(
                "insert into u values " + ",".join(f"({i})" for i in range(25))
            )
        q = "select a, a in (select a from u) from t order by a limit 60"
        assert sm.execute(q).rows == s1.execute(q).rows

    def test_in_empty_set_is_false_even_for_null(self, s):
        s.execute("create table e (a int)")
        assert s.execute(
            "select a, a in (select a from e), a not in (select a from e) "
            "from t order by a"
        ).rows == [
            (None, False, True), (1, False, True), (2, False, True),
            (3, False, True),
        ]

    def test_exists_respects_having_and_limit(self, s):
        assert s.execute(
            "select a, exists (select count(*) from u having count(*) > 100) "
            "from t where a = 1"
        ).rows == [(1, False)]
        assert s.execute(
            "select a, exists (select count(*) from u limit 0) from t "
            "where a = 1"
        ).rows == [(1, False)]

    def test_tableless_exists(self, s):
        s.execute("create table e (a int)")
        assert s.execute(
            "select exists (select 1 from u), not exists (select a from e)"
        ).rows == [(True, True)]


class TestValuePositionScalarsAndQuantified:
    """Correlated scalar subqueries in select items (agg-pull-up left
    join), ANY/ALL quantified comparisons, HAVING subqueries via the
    derived-table wrap, CONVERT()."""

    @pytest.fixture()
    def s(self):
        from tidb_tpu.session.session import Session

        s = Session()
        s.execute("create table t (a int, b int)")
        s.execute("insert into t values (1,10),(1,20),(2,30),(3,40)")
        s.execute("create table u (a int, v int)")
        s.execute("insert into u values (1,100),(1,200),(3,300)")
        return s

    def test_correlated_scalar_in_items(self, s):
        assert s.execute(
            "select distinct a, (select count(*) from u where u.a = t.a) c "
            "from t order by a"
        ).rows == [(1, 2), (2, 0), (3, 1)]
        assert s.execute(
            "select distinct a, (select sum(v) from u where u.a = t.a) sv "
            "from t order by a"
        ).rows == [(1, 300), (2, None), (3, 300)]

    def test_correlated_scalar_in_arithmetic(self, s):
        assert s.execute(
            "select a, b + (select count(*) from u where u.a = t.a) "
            "from t order by a, b"
        ).rows == [(1, 12), (1, 22), (2, 30), (3, 41)]

    def test_quantified_comparisons(self, s):
        assert s.execute(
            "select distinct a from t where a = any (select a from u) order by a"
        ).rows == [(1,), (3,)]
        assert s.execute(
            "select distinct a from t where a <> all (select a from u) order by a"
        ).rows == [(2,)]
        assert s.execute(
            "select distinct a from t where a < all (select a from u) order by a"
        ).rows == []
        assert s.execute(
            "select distinct a from t where a >= all (select a from u) order by a"
        ).rows == [(3,)]

    def test_quantified_empty_null_and_derived_semantics(self, s):
        s.execute("create table e (a int)")
        s.execute("create table un (a int)")
        s.execute("insert into un values (2),(null)")
        # ALL over the empty set is TRUE; ANY is FALSE
        assert s.execute(
            "select distinct a from t where a < all (select a from e) order by a"
        ).rows == [(1,), (2,), (3,)]
        assert s.execute(
            "select a from t where a > any (select a from e)"
        ).rows == []
        # a NULL in the set poisons undecided comparisons (3-valued)
        assert s.execute(
            "select a from t where a < all (select a from un)"
        ).rows == []
        # the subquery's own ORDER BY/LIMIT is honored (derived table):
        # with LIMIT 1 the set is {1}, without it {1,1,3}
        assert s.execute(
            "select distinct a from t where "
            "a >= all (select a from u order by a limit 1) order by a"
        ).rows == [(1,), (2,), (3,)]
        assert s.execute(
            "select distinct a from t where "
            "a >= all (select a from u) order by a"
        ).rows == [(3,)]

    def test_having_subqueries(self, s):
        assert s.execute(
            "select a from t group by a having a in (select a from u) "
            "order by a"
        ).rows == [(1,), (3,)]
        assert s.execute(
            "select a, sum(b) sb from t group by a having sb > 15 "
            "and a not in (select a from u) order by a"
        ).rows == [(2, 30)]

    def test_convert_is_cast(self, s):
        assert s.execute(
            "select convert(a, double) from t where a = 2"
        ).rows == [(2.0,)]


class TestRowValues:
    """Row-value constructors under = / <> / IN (MySQL row
    comparisons); NOT IN over rows is rejected (its per-column
    3-valued NULL semantics don't fit the multi-key anti join)."""

    @pytest.fixture()
    def s(self):
        from tidb_tpu.session.session import Session

        s = Session()
        s.execute("create table t (a int, b int)")
        s.execute("insert into t values (1,10),(2,20),(3,30),(1,99)")
        s.execute("create table u (x int, y int)")
        s.execute("insert into u values (1,10),(3,30)")
        return s

    def test_row_in_subquery(self, s):
        assert s.execute(
            "select a, b from t where (a, b) in (select x, y from u) order by a"
        ).rows == [(1, 10), (3, 30)]

    def test_row_eq_ne(self, s):
        assert s.execute(
            "select a, b from t where (a, b) = (1, 10)"
        ).rows == [(1, 10)]
        assert s.execute(
            "select a, b from t where (a, b) <> (1, 10) order by a, b"
        ).rows == [(1, 99), (2, 20), (3, 30)]

    def test_row_in_literal_list(self, s):
        assert s.execute(
            "select a, b from t where (a, b) in ((1,10),(2,20)) order by a"
        ).rows == [(1, 10), (2, 20)]

    def test_row_not_in_rejected(self, s):
        with pytest.raises(Exception):
            s.execute("select 1 from t where (a,b) not in (select x,y from u)")

    def test_arity_mismatch_rejected(self, s):
        with pytest.raises(Exception):
            s.execute("select 1 from t where (a, b) in (select x from u)")
        with pytest.raises(Exception):
            s.execute("select 1 from t where (a, b) = (1, 2, 3)")
