"""Instance watchdogs: memory alarm, expensive-query log, server
memory limit (reference: pkg/util/memoryusagealarm,
pkg/util/expensivequery, pkg/util/servermemorylimit/servermemorylimit.go:51).
"""

import threading
import time

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.utils.watchdog import (
    InstanceWatchdog, host_memory, parse_mem_limit,
)


def test_host_memory_and_limit_parse():
    rss, total = host_memory()
    assert rss > 0 and total > rss
    assert parse_mem_limit("0", total) == 0
    assert parse_mem_limit("50%", 1000) == 500
    assert parse_mem_limit("12345", total) == 12345
    assert parse_mem_limit("", total) == 0


def test_expensive_query_logged():
    cat = Catalog()
    s = Session(cat)
    s.execute("set global tidb_expensive_query_time_threshold = 0")
    wd = InstanceWatchdog(cat, interval=0.05)  # sample manually

    done = []

    def runner():
        s.execute("select sleep(1.2)")
        done.append(1)

    t = threading.Thread(target=runner)
    t.start()
    hits = 0
    for _ in range(40):
        time.sleep(0.05)
        wd.sample()
        if wd.expensive_seen:
            hits += 1
            break
    t.join()
    assert hits, "expensive query was never flagged"
    from tidb_tpu.utils.metrics import SLOW_LOG

    assert any("[expensive_query]" in r[1] for r in SLOW_LOG.rows())


def test_expensive_query_honors_slow_log_switch():
    """Satellite (PR 6): the watchdog's expensive-query slow-log entry
    honors the same slow_query_log on/off switch as the session call
    site (its admission bar stays its own
    tidb_expensive_query_time_threshold sysvar)."""
    cat = Catalog()
    s = Session(cat)
    s.execute("set global tidb_expensive_query_time_threshold = 0")
    s.execute("set global slow_query_log = 0")
    wd = InstanceWatchdog(cat, interval=0.05)

    from tidb_tpu.utils.metrics import SLOW_LOG

    before = len(SLOW_LOG.rows())

    def runner():
        s.execute("select sleep(0.6)")

    t = threading.Thread(target=runner)
    t.start()
    flagged = False
    for _ in range(40):
        time.sleep(0.05)
        wd.sample()
        if wd.expensive_seen:
            flagged = True
            break
    t.join()
    # the expensive flag still fires; only the slow-log entry is gated
    assert flagged
    assert not any(
        f"conn={s.conn_id} " in r[1]
        for r in SLOW_LOG.rows()[before:]
        if "[expensive_query]" in r[1]
    )


def test_memory_limit_kills_top_consumer():
    cat = Catalog()
    s = Session(cat)
    s.execute("set global tidb_server_memory_limit = 1")  # always breached
    wd = InstanceWatchdog(cat, interval=0.05)
    cat._watchdog = wd  # registered view for information_schema

    errors = []

    def runner():
        try:
            s.execute("select sleep(5)")
        except Exception as e:
            errors.append(str(e))

    t = threading.Thread(target=runner)
    t.start()
    for _ in range(60):
        time.sleep(0.05)
        if wd.kill_records:
            break
        wd.sample()
    t.join(timeout=10)
    assert not t.is_alive()
    assert wd.kill_records and wd.kill_records[0]["conn_id"] == s.conn_id
    assert errors and "interrupted" in errors[0]
    # observable through information_schema
    s.killer.clear()
    rows = s.execute(
        "select op, conn_id from information_schema.memory_usage_ops_history"
    ).rows
    assert ("kill", s.conn_id) in rows


def test_memory_usage_table():
    cat = Catalog()
    s = Session(cat)
    r = s.execute(
        "select memory_total, memory_current from "
        "information_schema.memory_usage"
    ).rows
    assert r[0][0] > r[0][1] > 0


def test_set_knob_starts_daemon():
    cat = Catalog()
    s = Session(cat)
    s.execute("set global tidb_memory_usage_alarm_ratio = 0.9")
    base = getattr(s.catalog, "_base", s.catalog)
    wd = getattr(base, "_watchdog", None)
    assert wd is not None and wd.is_alive()
    wd.stop_flag.set()


def test_kill_interrupts_sleep():
    cat = Catalog()
    s = Session(cat)
    errors = []

    def runner():
        try:
            s.execute("select sleep(10)")
        except Exception as e:
            errors.append(str(e))

    t = threading.Thread(target=runner)
    t.start()
    time.sleep(0.3)
    s.killer.kill()
    t.join(timeout=5)
    assert not t.is_alive() and errors and "interrupted" in errors[0]
