"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

The reference's tests run the whole engine against an embedded unistore
(pkg/testkit/mockstore.go:49) so no real cluster is needed; our analog is
JAX CPU with xla_force_host_platform_device_count=8 so multi-chip sharding
paths execute without TPU hardware. Must be set before jax imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # keep TPU tunnel out of tests
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's sitecustomize registers a TPU-tunnel PJRT plugin in
# every interpreter; its backend init serializes processes on the tunnel
# even when JAX_PLATFORMS=cpu. Deregister the factory before any jax op
# initializes backends so tests run pure-CPU and in parallel.
try:
    import jax as _jax
    from jax._src import xla_bridge as _xb

    # sitecustomize imported jax before this file ran, so the env var was
    # already latched — update the live config too.
    _jax.config.update("jax_platforms", "cpu")
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: scale-tier tests (SF0.1+ TPC-H parity, forced-spill runs); "
        "skipped unless RUN_SLOW=1 or -m slow",
    )


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest

    if os.environ.get("RUN_SLOW") == "1" or "slow" in config.getoption("-m", ""):
        return
    skip = _pytest.mark.skip(reason="scale tier: set RUN_SLOW=1 or -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
