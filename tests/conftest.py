"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

The reference's tests run the whole engine against an embedded unistore
(pkg/testkit/mockstore.go:49) so no real cluster is needed; our analog is
JAX CPU with xla_force_host_platform_device_count=8 so multi-chip sharding
paths execute without TPU hardware. Must be set before jax imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
