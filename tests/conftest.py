"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

The reference's tests run the whole engine against an embedded unistore
(pkg/testkit/mockstore.go:49) so no real cluster is needed; our analog is
JAX CPU with xla_force_host_platform_device_count=8 so multi-chip sharding
paths execute without TPU hardware. Must be set before jax imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # keep TPU tunnel out of tests
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's sitecustomize registers a TPU-tunnel PJRT plugin in
# every interpreter; its backend init serializes processes on the tunnel
# even when JAX_PLATFORMS=cpu. Deregister the factory before any jax op
# initializes backends so tests run pure-CPU and in parallel.
try:
    import jax as _jax
    from jax._src import xla_bridge as _xb

    # sitecustomize imported jax before this file ran, so the env var was
    # already latched — update the live config too.
    _jax.config.update("jax_platforms", "cpu")
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: scale-tier tests (SF0.1+ TPC-H parity, forced-spill runs); "
        "skipped unless RUN_SLOW=1 or -m slow",
    )
    # Single-core host: the TPU capture watcher (scripts/tpu_capture_all.sh)
    # must not run a bench's numpy-baseline phase while a suite holds the
    # CPU — that would inflate vs_baseline. Per-pid lock files make
    # creation/removal atomic (no read-modify-write race between two
    # finishing sessions); the watcher skips benching while any fresh
    # /tmp/suite.lock.* exists. Symmetrically, if a bench is mid-flight
    # (the watcher holds /tmp/bench.lock) we wait for it to finish
    # before the suite starts competing for the core.
    import threading as _threading
    import time as _time

    def _bench_live() -> bool:
        try:
            st = os.stat("/tmp/bench.lock")
        except OSError:  # lock released (or never held)
            return False
        return _time.time() - st.st_mtime <= 2400  # old = crashed bench

    mine = f"/tmp/suite.lock.{os.getpid()}"
    deadline = _time.time() + 1500
    while _time.time() < deadline:
        if not _bench_live():
            try:
                with open(mine, "w") as f:
                    f.write("held\n")
            except OSError:
                return
            # symmetric re-check: the watcher touches bench.lock THEN
            # looks for suite locks; we write ours THEN look for
            # bench.lock — whichever claims second sees the other and
            # backs off, so both can never proceed from the race window
            if not _bench_live():
                break
            try:
                os.unlink(mine)
            except OSError:
                pass
        _time.sleep(10)
    else:
        # deadline hit: proceed anyway (tests matter more than a bench;
        # the watcher's own suite-lock check keeps the NEXT bench away)
        try:
            with open(mine, "w") as f:
                f.write("held\n")
        except OSError:
            return

    def _refresh():
        # mtime heartbeat: a single >30min test (RUN_SLOW scale tier)
        # must not age the lock past the watcher's freshness cutoff
        while os.path.exists(mine):
            try:
                os.utime(mine)
            except OSError:
                return
            _time.sleep(60)

    _threading.Thread(target=_refresh, daemon=True).start()


def pytest_unconfigure(config):
    try:
        os.unlink(f"/tmp/suite.lock.{os.getpid()}")
    except OSError:
        pass


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest

    if os.environ.get("RUN_SLOW") == "1" or "slow" in config.getoption("-m", ""):
        return
    skip = _pytest.mark.skip(reason="scale tier: set RUN_SLOW=1 or -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
