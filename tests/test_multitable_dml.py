"""Multi-table DML: UPDATE ... JOIN, DELETE ... FROM <join>, DELETE USING.

Reference behavior: MySQL multi-table UPDATE/DELETE semantics as
implemented by TiDB's buildUpdate/buildDelete
(pkg/planner/core/logical_plan_builder.go) and executed row-at-a-time in
pkg/executor/update.go / delete.go: each target row is updated/deleted
once no matter how many join rows match it; outer-join no-match rows
update nothing.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture()
def sess():
    cat = Catalog()
    s = Session(cat)
    s.execute("create database d")
    s.execute("use d")
    s.execute("create table emp (id int primary key, dept int, salary int, name varchar(20))")
    s.execute("create table dept (id int primary key, bonus int, active int)")
    s.execute(
        "insert into emp values (1, 10, 100, 'a'), (2, 10, 200, 'b'), "
        "(3, 20, 300, 'c'), (4, 30, 400, 'd')"
    )
    s.execute("insert into dept values (10, 5, 1), (20, 7, 1), (30, 9, 0)")
    return s


class TestMultiTableUpdate:
    def test_update_join_basic(self, sess):
        r = sess.execute(
            "update emp join dept on emp.dept = dept.id "
            "set emp.salary = emp.salary + dept.bonus where dept.active = 1"
        )
        assert r.affected == 3
        rows = sess.execute("select id, salary from emp order by id").rows
        assert rows == [(1, 105), (2, 205), (3, 307), (4, 400)]

    def test_update_join_unqualified_set_col(self, sess):
        sess.execute(
            "update emp join dept on emp.dept = dept.id set salary = 0 "
            "where dept.id = 20"
        )
        rows = sess.execute("select id, salary from emp order by id").rows
        assert rows == [(1, 100), (2, 200), (3, 0), (4, 400)]

    def test_update_two_targets(self, sess):
        r = sess.execute(
            "update emp join dept on emp.dept = dept.id "
            "set emp.salary = 1, dept.bonus = 2 where dept.id = 10"
        )
        # 2 emp rows + 1 dept row
        assert r.affected == 3
        assert sess.execute("select bonus from dept where id = 10").rows == [(2,)]
        assert sess.execute(
            "select salary from emp where dept = 10 order by id"
        ).rows == [(1,), (1,)]

    def test_update_multiple_matches_updates_once(self, sess):
        # dept 10 matches two emp rows; the dept row must be updated once
        sess.execute(
            "update dept join emp on emp.dept = dept.id "
            "set dept.bonus = dept.bonus + 1"
        )
        rows = sess.execute("select id, bonus from dept order by id").rows
        assert rows == [(10, 6), (20, 8), (30, 10)]

    def test_update_join_string_set(self, sess):
        sess.execute(
            "update emp join dept on emp.dept = dept.id "
            "set emp.name = 'boosted' where dept.bonus >= 7"
        )
        rows = sess.execute("select id, name from emp order by id").rows
        assert rows == [(1, "a"), (2, "b"), (3, "boosted"), (4, "boosted")]

    def test_update_with_aliases(self, sess):
        sess.execute(
            "update emp e join dept d on e.dept = d.id "
            "set e.salary = d.bonus * 100 where d.id = 30"
        )
        assert sess.execute("select salary from emp where id = 4").rows == [(900,)]

    def test_update_left_join_no_match_rows_untouched(self, sess):
        sess.execute("insert into emp values (5, 99, 500, 'e')")  # no dept 99
        sess.execute(
            "update emp left join dept on emp.dept = dept.id "
            "set emp.salary = coalesce(dept.bonus, emp.salary)"
        )
        rows = sess.execute("select id, salary from emp order by id").rows
        assert rows == [(1, 5), (2, 5), (3, 7), (4, 9), (5, 500)]

    def test_update_comma_join(self, sess):
        sess.execute(
            "update emp, dept set emp.salary = emp.salary + dept.bonus "
            "where emp.dept = dept.id and dept.id = 20"
        )
        assert sess.execute("select salary from emp where id = 3").rows == [(307,)]


class TestMultiTableDelete:
    def test_delete_from_join(self, sess):
        r = sess.execute(
            "delete emp from emp join dept on emp.dept = dept.id "
            "where dept.active = 0"
        )
        assert r.affected == 1
        assert sess.execute("select count(*) from emp").rows == [(3,)]

    def test_delete_two_targets(self, sess):
        r = sess.execute(
            "delete emp, dept from emp join dept on emp.dept = dept.id "
            "where dept.id = 10"
        )
        assert r.affected == 3  # 2 emp + 1 dept
        assert sess.execute("select count(*) from emp").rows == [(2,)]
        assert sess.execute("select count(*) from dept").rows == [(2,)]

    def test_delete_using(self, sess):
        sess.execute(
            "delete from emp using emp join dept on emp.dept = dept.id "
            "where dept.bonus > 5"
        )
        rows = sess.execute("select id from emp order by id").rows
        assert rows == [(1,), (2,)]

    def test_delete_with_alias_targets(self, sess):
        sess.execute(
            "delete e from emp e join dept d on e.dept = d.id "
            "where d.id = 20"
        )
        assert sess.execute("select count(*) from emp").rows == [(3,)]

    def test_delete_duplicate_matches_counted_once(self, sess):
        # dept 10 joins 2 emp rows -> dept row matched twice, deleted once
        r = sess.execute(
            "delete dept from dept join emp on emp.dept = dept.id "
            "where dept.id = 10"
        )
        assert r.affected == 1
        assert sess.execute("select count(*) from dept").rows == [(2,)]

    def test_single_table_alias_delete(self, sess):
        sess.execute("delete from emp e where e.salary > 250")
        assert sess.execute("select count(*) from emp").rows == [(2,)]


class TestMultiDMLIntegrity:
    def test_update_join_pk_conflict_rolls_back(self, sess):
        import pytest as _pt

        with _pt.raises(Exception):
            sess.execute(
                "update emp join dept on emp.dept = dept.id "
                "set emp.id = 1 where dept.id = 10"
            )  # both dept-10 rows -> id 1: duplicate PK
        # table unchanged
        rows = sess.execute("select id from emp order by id").rows
        assert rows == [(1,), (2,), (3,), (4,)]

    def test_delete_join_respects_fk_restrict(self, sess):
        sess.execute("create table child (eid int, foreign key (eid) references emp (id))")
        sess.execute("insert into child values (3)")
        import pytest as _pt

        with _pt.raises(Exception):
            sess.execute(
                "delete emp from emp join dept on emp.dept = dept.id "
                "where dept.id = 20"
            )
        assert sess.execute("select count(*) from emp").rows == [(4,)]

    def test_delete_cascade_does_not_shift_later_targets(self, sess):
        # regression: a cascade fired by an earlier target must not shift
        # row positions a later target's handles refer to
        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c (id int primary key, pid int, "
            "foreign key (pid) references p (id) on delete cascade)"
        )
        sess.execute("insert into p values (0), (1)")
        sess.execute("insert into c values (0, 0), (1, 1), (2, 1), (3, 1), (4, 1)")
        sess.execute(
            "delete p, c from p join c on p.id = 0 and c.id = 3 where p.id = 0"
        )
        # p0 deleted (cascades c0), c3 deleted explicitly
        assert sess.execute("select id from c order by id").rows == [
            (1,), (2,), (4,)
        ]

    def test_delete_with_star_subquery(self, sess):
        # regression: rowid exposure must not leak into subquery stars
        sess.execute("create table keys_ (k int)")
        sess.execute("insert into keys_ values (10)")
        sess.execute(
            "delete emp from emp join dept on emp.dept = dept.id "
            "where emp.dept in (select * from keys_)"
        )
        assert sess.execute("select count(*) from emp").rows == [(2,)]

    def test_update_through_derived_table_source(self, sess):
        # derived tables are row sources, never SET binding candidates
        sess.execute(
            "update emp join (select id as did from dept where active = 1) d "
            "on emp.dept = d.did set emp.salary = 1 where d.did = 20"
        )
        assert sess.execute("select salary from emp where id = 3").rows == [(1,)]

    def test_update_join_in_txn_rollback(self, sess):
        sess.execute("begin")
        sess.execute(
            "update emp join dept on emp.dept = dept.id set emp.salary = 0"
        )
        # read-your-own-writes through the txn shadow
        assert sess.execute("select max(salary) from emp").rows == [(0,)]
        sess.execute("rollback")
        assert sess.execute("select max(salary) from emp").rows == [(400,)]


class TestMultiTableFKOnUpdate:
    """UPDATE ... JOIN honors FK ON UPDATE actions like the single-table
    path (reference: pkg/executor/foreign_key.go onUpdate)."""

    def test_join_update_cascades(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table p (id int primary key, tag int)")
        s.execute("create table d (tag int)")
        s.execute(
            "create table c (pid int, constraint f foreign key (pid) "
            "references p (id) on update cascade)"
        )
        s.execute("insert into p values (1, 5), (2, 6)")
        s.execute("insert into d values (5)")
        s.execute("insert into c values (1), (2)")
        s.execute(
            "update p join d on p.tag = d.tag set p.id = p.id + 100"
        )
        assert sorted(
            r[0] for r in s.execute("select pid from c").rows
        ) == [2, 101]

    def test_join_update_restrict_still_raises(self):
        import pytest

        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table p (id int primary key, tag int)")
        s.execute("create table d (tag int)")
        s.execute(
            "create table c (pid int, constraint f foreign key (pid) "
            "references p (id))"
        )
        s.execute("insert into p values (1, 5)")
        s.execute("insert into d values (5)")
        s.execute("insert into c values (1)")
        with pytest.raises(ValueError, match="restricts"):
            s.execute(
                "update p join d on p.tag = d.tag set p.id = 9"
            )

    def test_join_update_atomic_across_targets(self):
        import pytest

        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table a (id int primary key, v int)")
        s.execute("create table b (id int primary key, v int)")
        s.execute("insert into a values (1, 10)")
        s.execute("insert into b values (1, 20), (2, 30)")
        # target a updates fine; target b's SET collides on its PK ->
        # the WHOLE statement must roll back, including a
        with pytest.raises(Exception):
            s.execute(
                "update a join b on a.id = b.id "
                "set a.v = 99, b.id = 2"
            )
        assert s.execute("select v from a").rows == [(10,)]
        assert sorted(
            r[0] for r in s.execute("select id from b").rows
        ) == [1, 2]
