"""Online ADD INDEX: the F1 schema-state ladder with concurrent DML.

Reference: pkg/ddl/index.go:545 (None -> DeleteOnly -> WriteOnly ->
WriteReorg -> Public) and ddl_worker.go:1180. VERDICT round-2 item #5:
a test interleaving DML with a slow backfill (failpoint) must end with
a consistent index. DeleteOnly is vacuous here by design: indexes are
derived per-version sorted permutations, so deletes can never strand
index entries.
"""

import threading

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.utils import failpoint


@pytest.fixture()
def env():
    cat = Catalog()
    s = Session(cat, db="test")
    s.execute("create table t (a int, b int)")
    s.execute("insert into t values (1, 10), (2, 20), (3, 30)")
    yield cat, s
    failpoint.disable_all()


def test_states_progress_to_public(env):
    cat, s = env
    seen = []
    t = cat.table("test", "t")
    failpoint.enable(
        "ddl/index-write-only", lambda: seen.append(t.index_state("ia"))
    )
    failpoint.enable(
        "ddl/index-write-reorg", lambda: seen.append(t.index_state("ia"))
    )
    s.execute("create index ia on t (a)")
    assert seen == ["write_only", "write_reorg"]
    assert t.index_state("ia") == "public"


def test_planner_ignores_nonpublic_index(env):
    cat, s = env
    t = cat.table("test", "t")
    plans = []

    def check():
        # while the backfill is mid-reorg, point queries must still plan
        # (and not route through the half-built index)
        txt = "\n".join(
            r[0] for r in s.execute("explain select b from t where a = 2").rows
        )
        plans.append(("IndexRangeScan(a" in txt, t.index_state("ia")))

    failpoint.enable("ddl/index-write-reorg", check)
    s.execute("create index ia on t (a)")
    failpoint.disable("ddl/index-write-reorg")
    assert plans == [(False, "write_reorg")]
    txt = "\n".join(
        r[0] for r in s.execute("explain select b from t where a = 2").rows
    )
    assert "IndexRangeScan(a" in txt  # public now: planner uses it


def test_concurrent_dml_during_unique_backfill(env):
    """Writers that land DURING the reorg are checked against the
    half-built unique index (write_only enforcement); the end state is
    a consistent PUBLIC unique index."""
    cat, s = env
    writer = Session(cat, db="test")
    dup_err, ok_rows = [], []

    def dml():
        try:
            writer.execute("insert into t values (2, 99)")  # dup of a=2
        except Exception as e:
            dup_err.append(str(e))
        writer.execute("insert into t values (7, 70)")  # fine
        ok_rows.append(1)

    failpoint.enable("ddl/index-write-reorg", dml)
    s.execute("create unique index ua on t (a)")
    failpoint.disable("ddl/index-write-reorg")

    t = cat.table("test", "t")
    assert t.index_state("ua") == "public"
    assert dup_err and "uplicate" in dup_err[0].replace("D", "d"), dup_err
    assert ok_rows
    assert s.execute("select b from t where a = 7").rows == [(70,)]
    # and the finished index still rejects duplicates
    with pytest.raises(Exception, match="[Dd]uplicate"):
        s.execute("insert into t values (7, 71)")


def test_backfill_validation_failure_rolls_back(env):
    cat, s = env
    s.execute("insert into t values (2, 99)")  # pre-existing duplicate
    with pytest.raises(Exception, match="duplicate"):
        s.execute("create unique index ua on t (a)")
    t = cat.table("test", "t")
    assert "ua" not in t.indexes
    assert "ua" not in t.unique_indexes
    assert t.index_state("ua") == "public"  # unregistered = default
    # table remains fully writable
    s.execute("insert into t values (2, 100)")


def test_dense_join_ignores_unvalidated_unique(env):
    """The dense 1:1 join's uniqueness proof must not trust a unique
    index that has not reached PUBLIC (it may cover duplicates)."""
    cat, s = env
    s.execute("create table child (fk int, v int)")
    s.execute("insert into child values (2, 1), (2, 2)")
    results = []

    def probe():
        r = s.execute(
            "select count(*) from child, t where t.a = child.fk"
        )
        results.append(r.rows[0][0])

    failpoint.enable("ddl/index-write-reorg", probe)
    s.execute("create unique index ua on t (a)")
    failpoint.disable("ddl/index-write-reorg")
    assert results == [2]
    r = s.execute("select count(*) from child, t where t.a = child.fk")
    assert r.rows == [(2,)]


def test_stale_txn_shadow_conflicts_after_index_ddl(env):
    """A transaction whose shadow predates CREATE UNIQUE INDEX must not
    commit rows that skipped the new constraint: the PUBLIC flip bumps
    the table version (the 'Information schema is changed' abort)."""
    cat, s = env
    other = Session(cat, db="test")
    other.execute("begin")
    other.execute("insert into t values (2, 99)")  # dup of a=2, pre-DDL
    s.execute("create unique index ua on t (a)")
    with pytest.raises(Exception, match="conflict"):
        other.execute("commit")
    r = s.execute("select a, count(*) c from t group by a having c > 1")
    assert r.rows == []
