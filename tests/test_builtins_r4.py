"""Round-4 builtin breadth: misc/conversion/base64/inet/uuid/soundex/
period/json additions (reference: pkg/expression builtin_string.go,
builtin_miscellaneous.go, builtin_time.go, builtin_json.go families;
VERDICT round-3 item #10)."""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture(scope="module")
def s():
    s = Session(Catalog(), db="test")
    s.execute("create table t (a int, s varchar(40), j varchar(60))")
    s.execute(
        "insert into t values "
        "(5, 'Robert', '{\"a\": {\"b\": [1, 2]}}'), "
        "(255, '1.2.3.4', '[1, 2]'), "
        "(NULL, NULL, NULL)"
    )
    return s


def q1(s, sql):
    return s.execute(sql).rows[0][0]


class TestStringMisc:
    def test_soundex(self, s):
        assert q1(s, "select soundex(s) from t") == "R163"
        # Soundex equivalence: Robert ~ Rupert
        assert q1(s, "select soundex('Rupert')") == "R163"

    def test_base64_roundtrip(self, s):
        assert q1(s, "select to_base64('abc')") == "YWJj"
        assert q1(s, "select from_base64(to_base64(s)) from t") == "Robert"

    def test_weight_string_collation(self, s):
        assert q1(s, "select weight_string('abc')") == "abc"
        s.execute(
            "create table ws (c varchar(8) collate utf8mb4_general_ci)"
        )
        s.execute("insert into ws values ('MiXeD')")
        assert q1(s, "select weight_string(c) from ws") == "MIXED"

    def test_export_make_set(self, s):
        assert q1(s, "select export_set(6, '1', '0', '', 4)") == "0110"
        assert q1(s, "select make_set(5, 'a', 'b', 'c')") == "a,c"

    def test_format_inet_ntoa_const(self, s):
        assert q1(s, "select format(1234567.891, 2)") == "1,234,567.89"
        assert q1(s, "select inet_ntoa(16909060)") == "1.2.3.4"
        with pytest.raises(Exception, match="constant"):
            s.execute("select format(a, 2) from t")


class TestInetUuid:
    def test_inet_aton(self, s):
        assert q1(s, "select inet_aton('1.2.3.4')") == 16909060
        assert q1(s, "select inet_aton('127.0.0.1')") == 2130706433
        # MySQL short form: '1.2' = 1<<24 | 2
        assert q1(s, "select inet_aton('1.2')") == (1 << 24) | 2
        assert q1(s, "select inet_aton(s) from t where a = 255") == 16909060

    def test_uuid_shape_and_volatility(self, s):
        u = q1(s, "select uuid()")
        assert q1(s, f"select is_uuid('{u}')") is True
        assert q1(s, "select is_uuid('nope')") is False
        u2 = q1(s, "select uuid()")
        assert u != u2  # fresh per statement
        assert q1(s, "select uuid_short()") != q1(s, "select uuid_short()")


class TestTemporalMisc:
    def test_addtime_subtime(self, s):
        r = s.execute("select addtime('10:00:00', '01:30:00')").rows[0][0]
        assert "11:30:00" in str(r)
        r = s.execute(
            "select subtime('2024-01-01 10:00:00', '00:30:00')"
        ).rows[0][0]
        assert "09:30:00" in str(r)

    def test_period_math(self, s):
        assert q1(s, "select period_add(202411, 3)") == 202502
        assert q1(s, "select period_diff(202502, 202411)") == 3
        assert q1(s, "select period_add(202401, -2)") == 202311

    def test_datediff_string_literals(self, s):
        assert q1(s, "select datediff('2024-03-05', '2024-03-01')") == 4


class TestJsonMisc:
    def test_json_depth(self, s):
        assert q1(s, "select json_depth(j) from t") == 4
        assert q1(s, "select json_depth('[1, 2]')") == 2
        assert q1(s, "select json_depth('3')") == 1

    def test_json_quote_unquote(self, s):
        assert q1(s, 'select json_quote(\'a"b\')') == '"a\\"b"'
        assert q1(s, "select json_unquote('\"abc\"')") == "abc"


class TestConvertUsing:
    def test_convert_using_identity(self, s):
        assert q1(s, "select convert(s using utf8mb4) from t") == "Robert"
        # latin1's default here is BINARY (reference bootstrap): the
        # comparison after conversion is case-sensitive
        s.execute(
            "create table cu (c varchar(8) collate utf8mb4_general_ci)"
        )
        s.execute("insert into cu values ('A'), ('a')")
        assert q1(
            s, "select count(*) from cu where convert(c using utf8mb4) = 'a'"
        ) == 1


class TestMiscAdditions:
    def test_json_keys_contains(self, s):
        assert q1(s, "select json_keys(j) from t") == '["a"]'
        assert q1(s, "select json_contains('[1, 2, 3]', '2')") is True
        assert q1(s, "select json_contains(j, '1', '$.a') from t") is False

    def test_unhex(self, s):
        assert q1(s, "select unhex('414243')") == "ABC"

    def test_session_info_funcs(self, s):
        assert isinstance(q1(s, "select connection_id()"), int)
        assert "tidb" in q1(s, "select version()")

    def test_rand_sleep_benchmark(self, s):
        v = q1(s, "select rand()")
        assert 0.0 <= float(v) < 1.0
        assert q1(s, "select sleep(0)") == 0
        assert q1(s, "select benchmark(10, 1)") == 0

    def test_found_rows_row_count_wired(self, s):
        s.execute("create table fr (x int)")
        s.execute("insert into fr values (1), (2), (3)")
        assert q1(s, "select row_count()") == 3
        s.execute("select * from fr where x > 1")
        assert q1(s, "select found_rows()") == 2
        s.execute("update fr set x = 9 where x > 1")
        assert q1(s, "select row_count()") == 2

    def test_is_uuid_mysql_forms(self, s):
        u = "12345678-1234-1234-1234-123456789012"
        assert q1(s, f"select is_uuid('{u}')") is True
        assert q1(s, f"select is_uuid('{u.replace('-', '')}')") is True
        assert q1(s, "select is_uuid('12345678-123412341234123456789012')") is False
