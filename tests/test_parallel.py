"""Mesh runtime tests on the virtual 8-device CPU mesh (reference model:
unistore's in-proc MPP exchange tests — full shuffle without a cluster,
SURVEY.md §4 "multi-node without a cluster")."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tidb_tpu.chunk import Batch, DevCol, HostBlock, block_to_batch, column_from_values
from tidb_tpu.dtypes import INT64
from tidb_tpu.executor import AggDesc, group_aggregate
from tidb_tpu.parallel import (
    broadcast_join,
    distributed_group_aggregate,
    hash_repartition,
    make_mesh,
    partitioned_join,
    shard_batch,
)
from tidb_tpu.parallel.mesh import shard_map

N = 8


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N
    return make_mesh(N)


def make_global_batch(n_rows, n_keys, seed=0, cap_per_dev=256):
    rng = np.random.default_rng(seed)
    g = rng.integers(0, n_keys, n_rows).astype(np.int64)
    v = rng.integers(0, 100, n_rows).astype(np.int64)
    block = HostBlock.from_columns(
        {
            "g": column_from_values(g.tolist(), INT64),
            "v": column_from_values(v.tolist(), INT64),
        }
    )
    batch = block_to_batch(block, cap_per_dev * N)
    return batch, g, v


def colfn(n):
    return lambda b: b.cols[n]


class TestRepartition:
    def test_preserves_rows_and_colocates(self, mesh):
        batch, g, v = make_global_batch(1000, 16)
        sharded = shard_batch(batch, mesh)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("d"), out_specs=(P("d"), P())
        )
        def step(b):
            out, dropped, need = hash_repartition(b, colfn("g"), N, 512)
            return out, dropped

        out, dropped = step(sharded)
        assert int(dropped) == 0
        rv = np.asarray(out.row_valid)
        gd = np.asarray(out.cols["g"].data)
        vd = np.asarray(out.cols["v"].data)
        # all rows survive with their values
        got = sorted(zip(gd[rv].tolist(), vd[rv].tolist()))
        exp = sorted(zip(g.tolist(), v.tolist()))
        assert got == exp
        # equal keys land on one device
        per_dev = np.asarray(out.row_valid).reshape(N, -1)
        gd2 = gd.reshape(N, -1)
        seen = {}
        for d in range(N):
            for key in np.unique(gd2[d][per_dev[d]]):
                assert seen.setdefault(int(key), d) == d

    def test_overflow_detected(self, mesh):
        batch, g, v = make_global_batch(1000, 1)  # all rows to one device
        sharded = shard_batch(batch, mesh)

        @jax.jit
        @functools.partial(
            shard_map,
            mesh=mesh, in_specs=P("d"), out_specs=(P("d"), P(), P()),
        )
        def step(b):
            out, dropped, need = hash_repartition(b, colfn("g"), N, 64)
            return out, dropped, need

        _out, dropped, need = step(sharded)
        assert int(dropped) == 1000 - 64 * N or int(dropped) > 0
        # the region-balance analog: the exchange reports the TRUE
        # hot-bucket size so the host retries at the exact capacity
        assert int(need) == 1000


class TestDistributedAgg:
    def test_matches_single_device(self, mesh):
        batch, g, v = make_global_batch(2000, 23, seed=3)
        sharded = shard_batch(batch, mesh)
        aggs = [
            AggDesc("sum", colfn("v"), "s"),
            AggDesc("count", None, "c"),
            AggDesc("avg", colfn("v"), "m"),
            AggDesc("min", colfn("v"), "lo"),
            AggDesc("max", colfn("v"), "hi"),
        ]

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("d"), out_specs=(P("d"), P(), P())
        )
        def step(b):
            out, ng, dropped, _need = distributed_group_aggregate(
                b, [colfn("g")], aggs, 256, N, key_names=["g"]
            )
            return out, ng, dropped

        out, ng, dropped = step(sharded)
        assert int(dropped) == 0
        rv = np.asarray(out.row_valid)
        rows = {}
        for i in np.nonzero(rv)[0]:
            key = int(np.asarray(out.cols["g"].data)[i])
            assert key not in rows, "group split across devices!"
            rows[key] = (
                int(np.asarray(out.cols["s"].data)[i]),
                int(np.asarray(out.cols["c"].data)[i]),
                float(np.asarray(out.cols["m"].data)[i]),
                int(np.asarray(out.cols["lo"].data)[i]),
                int(np.asarray(out.cols["hi"].data)[i]),
            )
        # golden
        exp = {}
        for key in np.unique(g):
            m = g == key
            exp[int(key)] = (
                int(v[m].sum()), int(m.sum()), float(v[m].mean()),
                int(v[m].min()), int(v[m].max()),
            )
        assert rows == exp

    def test_scalar_agg(self, mesh):
        batch, g, v = make_global_batch(500, 5, seed=4)
        sharded = shard_batch(batch, mesh)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("d"), out_specs=(P("d"), P(), P())
        )
        def step(b):
            return distributed_group_aggregate(b, [], [AggDesc("sum", colfn("v"), "s")], 64, N)[:3]

        out, _ng, _dropped = step(sharded)
        # replicated result: read shard 0 row 0
        assert int(np.asarray(out.cols["s"].data)[0]) == int(v.sum())


class TestDistributedJoin:
    def _sides(self, seed=5):
        rng = np.random.default_rng(seed)
        bk = np.arange(64, dtype=np.int64)
        bv = rng.integers(0, 1000, 64).astype(np.int64)
        pk = rng.integers(0, 96, 800).astype(np.int64)
        pv = rng.integers(0, 1000, 800).astype(np.int64)
        build = block_to_batch(
            HostBlock.from_columns(
                {"bk": column_from_values(bk.tolist(), INT64),
                 "bv": column_from_values(bv.tolist(), INT64)}
            ),
            32 * N,
        )
        probe = block_to_batch(
            HostBlock.from_columns(
                {"pk": column_from_values(pk.tolist(), INT64),
                 "pv": column_from_values(pv.tolist(), INT64)}
            ),
            128 * N,
        )
        expected = sorted(
            (int(k), int(pv[i]), int(bv[k]))
            for i, k in enumerate(pk)
            if k < 64
        )
        return build, probe, expected

    def test_partitioned_join(self, mesh):
        build, probe, expected = self._sides()
        sb, sp = shard_batch(build, mesh), shard_batch(probe, mesh)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("d"), P("d")), out_specs=(P("d"), P(), P())
        )
        def step(b, p):
            return partitioned_join(
                p, b, colfn("pk"), colfn("bk"), N, 1024, 1024, "inner"
            )

        out, total, dropped = step(sb, sp)
        assert int(dropped) == 0
        assert int(total) == len(expected)
        rv = np.asarray(out.row_valid)
        got = sorted(
            zip(
                np.asarray(out.cols["pk"].data)[rv].tolist(),
                np.asarray(out.cols["pv"].data)[rv].tolist(),
                np.asarray(out.cols["bv"].data)[rv].tolist(),
            )
        )
        assert got == expected

    def test_broadcast_join(self, mesh):
        build, probe, expected = self._sides(seed=6)
        sb, sp = shard_batch(build, mesh), shard_batch(probe, mesh)

        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("d"), P("d")), out_specs=(P("d"), P())
        )
        def step(b, p):
            return broadcast_join(b, p, colfn("bk"), colfn("pk"), 1024, "inner")

        out, total = step(sb, sp)
        assert int(total) == len(expected)
        rv = np.asarray(out.row_valid)
        got = sorted(
            zip(
                np.asarray(out.cols["pk"].data)[rv].tolist(),
                np.asarray(out.cols["pv"].data)[rv].tolist(),
                np.asarray(out.cols["bv"].data)[rv].tolist(),
            )
        )
        assert got == expected


class TestDistributedSort:
    """Sample-sort ORDER BY over the mesh: range-partition by sampled
    splitters + local sort; per-device memory stays O(rows/n) instead of
    the round-1 whole-dataset gather (reference: sortexec partition
    merge; VERDICT round-1 weak #2)."""

    def _pair(self, rows):
        from tidb_tpu.session.session import Session

        sm, s1 = Session(mesh_devices=8), Session()
        for s in (sm, s1):
            s.execute("create table t (a int, w int, c varchar(8))")
            s.execute("insert into t values " + ",".join(rows))
        return sm, s1

    def test_parity_with_nulls_desc_strings(self):
        import random

        random.seed(5)
        rows = [
            f"({random.choice(['null'] + [str(random.randint(-500, 500))])},"
            f"{random.randint(0, 99)},'s{random.randint(0, 40)}')"
            for _ in range(2500)
        ]
        sm, s1 = self._pair(rows)
        for q in [
            "select a, w from t order by a, w",
            "select a, w from t order by a desc, w desc",
            "select c, a from t order by c, a",
            "select a, w, c from t order by w desc, a, c",
        ]:
            assert sm.execute(q).rows == s1.execute(q).rows, q

    def test_no_gather_in_sharded_sort_plan(self):
        """The mesh Sort on sharded input must range-exchange, not
        broadcast_gather (memory contract)."""
        from tidb_tpu.session.session import Session
        from tidb_tpu.utils import failpoint

        sm = Session(mesh_devices=8)
        sm.execute("create table t (a int)")
        sm.execute(
            "insert into t values " + ",".join(f"({i % 97})" for i in range(1000))
        )
        seen = []
        failpoint.enable("exchange/range-repartition", lambda: seen.append("range"))
        failpoint.enable("exchange/gather", lambda: seen.append("gather"))
        try:
            sm.execute("select a from t order by a")
        finally:
            failpoint.disable("exchange/range-repartition")
            failpoint.disable("exchange/gather")
        assert "range" in seen and "gather" not in seen

    def test_skewed_keys_converge(self):
        # every row shares one key: one bucket takes everything — the
        # drop-retry loop must converge, and ties must not reorder
        from tidb_tpu.session.session import Session

        sm, s1 = Session(mesh_devices=8), Session()
        for s in (sm, s1):
            s.execute("create table t (a int, b int)")
            s.execute(
                "insert into t values "
                + ",".join(f"(7,{i})" for i in range(900))
            )
        q = "select a, b from t order by a, b"
        assert sm.execute(q).rows == s1.execute(q).rows
