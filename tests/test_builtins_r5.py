"""Round-5 builtin batch: JSON mutation, JSON/variance aggregates,
encryption/compression, inet6/uuid, advisory locks, time additions.

Reference: pkg/expression/builtin_json.go (mutation family),
builtin_encryption.go (AES/COMPRESS), builtin_miscellaneous.go
(GET_LOCK, INET6, UUID), pkg/executor/aggfuncs (variance family,
JSON_ARRAYAGG/JSON_OBJECTAGG).
"""

import json

import pytest

from tidb_tpu.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create database b5")
    s.execute("use b5")
    s.execute("create table t (j varchar(200), s varchar(40), a int, grp int)")
    s.execute(
        "insert into t values"
        " ('{\"a\": 1, \"b\": [1, 2]}', 'hello', 5, 1),"
        " ('{\"a\": 2}', 'world', 7, 1),"
        " ('{\"x\": 9}', 'zap', 9, 2)"
    )
    return s


def one(sess, sql):
    return sess.execute(sql).rows[0][0]


class TestJsonMutation:
    def test_set_insert_replace(self, sess):
        assert json.loads(
            one(sess, "select json_set(j, '$.c', 5) from t where a = 5")
        ) == {"a": 1, "b": [1, 2], "c": 5}
        # INSERT never overwrites, REPLACE never creates
        assert json.loads(
            one(sess, "select json_insert(j, '$.a', 99) from t where a = 5")
        )["a"] == 1
        assert json.loads(
            one(sess, "select json_replace(j, '$.a', 42) from t where a = 5")
        )["a"] == 42
        assert "c" not in json.loads(
            one(sess, "select json_replace(j, '$.c', 1) from t where a = 5")
        )

    def test_remove_and_arrays(self, sess):
        assert json.loads(
            one(sess, "select json_remove(j, '$.b') from t where a = 5")
        ) == {"a": 1}
        assert json.loads(
            one(sess, "select json_array_append(j, '$.b', 3) from t where a = 5")
        )["b"] == [1, 2, 3]
        assert json.loads(
            one(sess, "select json_array_insert(j, '$.b[0]', 0) from t where a = 5")
        )["b"] == [0, 1, 2]

    def test_merge(self, sess):
        assert json.loads(
            one(sess, "select json_merge_patch(j, '{\"a\": null, \"z\": 1}') "
                      "from t where a = 5")
        ) == {"b": [1, 2], "z": 1}
        assert json.loads(
            one(sess, "select json_merge_preserve(j, '{\"a\": 7}') "
                      "from t where a = 5")
        )["a"] == [1, 7]

    def test_predicates(self, sess):
        assert one(
            sess, "select json_contains_path(j, 'one', '$.a') from t where a = 5"
        ) is True
        assert one(
            sess, "select json_contains_path(j, 'all', '$.a', '$.q') "
                  "from t where a = 5"
        ) is False
        assert one(
            sess, "select json_overlaps(j, '{\"a\": 1}') from t where a = 5"
        ) is True
        assert one(sess, "select json_storage_size(j) from t where a = 5") > 0

    def test_search_pretty_constructors(self, sess):
        sess.execute("create table js (d varchar(80))")
        sess.execute(
            "insert into js values ('{\"k\": \"hello\", \"l\": [\"hello\"]}')"
        )
        assert one(sess, "select json_search(d, 'one', 'hello') from js") == '"$.k"'
        assert "\n" in one(sess, "select json_pretty(d) from js")
        assert json.loads(one(sess, "select json_array(1, 'a', null)")) == [
            1, "a", None
        ]
        assert json.loads(
            one(sess, "select json_object('k', 1, 'm', 'v')")
        ) == {"k": 1, "m": "v"}


class TestCryptoCompress:
    def test_aes_roundtrip(self, sess):
        # AES lowers through the optional `cryptography` package —
        # stub-or-gate rule: environments without it skip instead of
        # failing on the import inside the kernel
        pytest.importorskip("cryptography")
        assert one(
            sess,
            "select aes_decrypt(aes_encrypt(s, 'key'), 'key') from t where a = 5",
        ) == "hello"
        # wrong key -> NULL (bad padding)
        assert one(
            sess,
            "select aes_decrypt(aes_encrypt(s, 'key'), 'nope') from t where a = 5",
        ) is None

    def test_compress_roundtrip(self, sess):
        assert one(
            sess, "select uncompress(compress(s)) from t where a = 7"
        ) == "world"
        assert one(
            sess, "select uncompressed_length(compress(s)) from t where a = 7"
        ) == 5


class TestInetUuid:
    def test_inet6(self, sess):
        assert one(sess, "select inet6_ntoa(inet6_aton('::1'))") == "::1"
        assert one(sess, "select inet6_ntoa(inet6_aton('1.2.3.4'))") == "1.2.3.4"

    def test_is_ip(self, sess):
        r = sess.execute(
            "select is_ipv4('1.2.3.4'), is_ipv4('::1'), is_ipv6('::1'), "
            "is_ipv6('x')"
        ).rows[0]
        assert r == (True, False, True, False)

    def test_uuid_bin(self, sess):
        u = "12345678-1234-5678-1234-567812345678"
        assert one(sess, f"select bin_to_uuid(uuid_to_bin('{u}'))") == u


class TestLocks:
    def test_lock_lifecycle(self, sess):
        assert one(sess, "select get_lock('l1', 0)") == 1
        assert one(sess, "select is_free_lock('l1')") == 0
        assert one(sess, "select is_used_lock('l1')") == sess.conn_id
        # re-entrant
        assert one(sess, "select get_lock('l1', 0)") == 1
        assert one(sess, "select release_lock('l1')") == 1
        assert one(sess, "select release_lock('l1')") == 1
        assert one(sess, "select release_lock('l1')") is None
        assert one(sess, "select is_free_lock('l1')") == 1

    def test_contention(self, sess):
        other = Session(
            getattr(sess.catalog, "_base", sess.catalog), db="b5"
        )
        assert one(sess, "select get_lock('c1', 0)") == 1
        assert one(other, "select get_lock('c1', 0)") == 0  # timeout
        assert one(other, "select release_lock('c1')") == 0  # not owner
        assert one(sess, "select release_all_locks()") == 1
        assert one(other, "select get_lock('c1', 0)") == 1
        other.execute("select release_all_locks()")


class TestVarianceAggs:
    def test_scalar(self, sess):
        r = sess.execute(
            "select var_pop(a), var_samp(a), stddev_pop(a), stddev_samp(a) "
            "from t"
        ).rows[0]
        # values 5,7,9: mean 7, var_pop 8/3, var_samp 4
        assert abs(r[0] - 8 / 3) < 1e-9
        assert abs(r[1] - 4.0) < 1e-9
        assert abs(r[2] - (8 / 3) ** 0.5) < 1e-9
        assert abs(r[3] - 2.0) < 1e-9

    def test_grouped_and_null_cases(self, sess):
        rows = sess.execute(
            "select grp, var_pop(a), var_samp(a) from t group by grp "
            "order by grp"
        ).rows
        assert rows[0][0] == 1 and abs(rows[0][1] - 1.0) < 1e-9
        # single-row group: var_pop 0, var_samp NULL (n-1 = 0)
        assert rows[1][1] == 0 and rows[1][2] is None

    def test_aliases(self, sess):
        a = one(sess, "select variance(a) from t")
        b = one(sess, "select var_pop(a) from t")
        c = one(sess, "select std(a) from t")
        assert abs(a - b) < 1e-12 and abs(c - b ** 0.5) < 1e-9


class TestJsonAggs:
    def test_arrayagg(self, sess):
        v = one(sess, "select json_arrayagg(a) from t")
        assert sorted(json.loads(v)) == [5, 7, 9]

    def test_objectagg(self, sess):
        rows = sess.execute(
            "select grp, json_objectagg(s, a) from t group by grp "
            "order by grp"
        ).rows
        assert json.loads(rows[0][1]) == {"hello": 5, "world": 7}
        assert json.loads(rows[1][1]) == {"zap": 9}

    def test_any_value(self, sess):
        rows = sess.execute(
            "select grp, any_value(s) from t group by grp order by grp"
        ).rows
        assert rows[0][1] in ("hello", "world") and rows[1][1] == "zap"
        assert one(sess, "select any_value(s) from t where a = 9") == "zap"


class TestTimeAndMisc:
    def test_time_constants(self, sess):
        assert len(one(sess, "select utc_date()")) == 10
        assert one(sess, "select maketime(10, 30, 45)") == "10:30:45"
        assert one(sess, "select get_format(date, 'usa')") == "%m.%d.%Y"
        assert one(sess, "select yearweek(date '1995-03-15')") == 199511
        assert one(
            sess, "select timestampadd(day, 3, date '1995-03-15')"
        ) == "1995-03-18"
        assert one(
            sess, "select to_seconds(date '1970-01-02')"
        ) == 62167305600

    def test_info_and_misc(self, sess):
        assert one(sess, "select current_role()") == "NONE"
        assert one(sess, "select name_const('x', 42)") == 42
        assert one(sess, "select charset('a')") == "utf8mb4"
        assert one(sess, "select collation('a')") == "utf8mb4_bin"
        assert one(sess, "select coercibility('a')") == 4
        assert len(one(sess, "select random_bytes(8)")) == 8
        assert "tidb-tpu" in one(sess, "select tidb_version()")
        assert one(sess, "select mid('hello', 2, 3)") == "ell"
        assert one(sess, "select sha('abc')") == (
            "a9993e364706816aba3e25717850c26c9cd0d89d"
        )
