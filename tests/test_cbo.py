"""Cost-based optimization: statistics drive join order, broadcast
exchange choice, and EXPLAIN estimates.

Reference: pkg/planner/cardinality/selectivity.go (histogram/NDV
selectivity), rule_join_reorder.go (cost-driven order),
exhaust_physical_plans.go (broadcast-vs-shuffle MPP join). VERDICT round
1 criterion: a Q5-shaped 6-way join picks the small side to broadcast
and EXPLAIN prints est-rows per node.
"""

import pytest

from tidb_tpu.bench import load_tpch
from tidb_tpu.planner.cardinality import est_rows, gather_stats, selectivity
from tidb_tpu.planner.logical import JoinPlan, Scan, build_query
from tidb_tpu.parser import parse
from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog

Q5 = (
    "select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue "
    "from customer, orders, lineitem, supplier, nation, region "
    "where c_custkey = o_custkey and l_orderkey = o_orderkey "
    "and l_suppkey = s_suppkey and c_nationkey = s_nationkey "
    "and s_nationkey = n_nationkey and n_regionkey = r_regionkey "
    "and r_name = 'ASIA' "
    "and o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01' "
    "group by n_name order by revenue desc"
)


@pytest.fixture(scope="module")
def sess():
    cat = Catalog()
    load_tpch(
        cat,
        sf=0.01,
        tables=["orders", "lineitem", "customer", "supplier", "nation", "region"],
        seed=3,
    )
    s = Session(cat, db="tpch")
    for t in ["lineitem", "orders", "customer", "supplier", "nation", "region"]:
        s.execute(f"analyze table {t}")
    return s


def _plan_of(sess, sql):
    st = parse(sql)
    st = st[0] if isinstance(st, list) else st
    return build_query(st, sess.catalog, "tpch", sess._scalar_subquery)


def _joins(plan, out=None):
    out = [] if out is None else out
    if isinstance(plan, JoinPlan):
        out.append(plan)
    for a in ("child", "left", "right"):
        c = getattr(plan, a, None)
        if c is not None:
            _joins(c, out)
    for c in getattr(plan, "children", []) or []:
        _joins(c, out)
    return out


def _scans_in_order(plan, out=None):
    out = [] if out is None else out
    if isinstance(plan, Scan):
        out.append(plan.table)
    for a in ("child", "left", "right"):
        c = getattr(plan, a, None)
        if c is not None:
            _scans_in_order(c, out)
    return out


def test_explain_prints_estimates(sess):
    r = sess.must_query("explain " + Q5)
    lines = [row[0] for row in r.rows]
    assert all("est=" in l for l in lines), lines
    # the filtered region scan estimates ~1 row; lineitem its full count
    li = sess.catalog.table("tpch", "lineitem")
    scan_lines = [l for l in lines if "Scan" in l and "lineitem" in l]
    assert scan_lines and f"est={li.nrows}" in scan_lines[0]


def test_q5_join_order_small_first(sess):
    """Cost-driven reorder starts from the filtered tiny relations and
    joins lineitem (largest) last — i.e. lineitem sits at depth 1 of the
    join spine, not at the bottom."""
    plan = _plan_of(sess, Q5)
    joins = _joins(plan)
    assert len(joins) == 5
    # top join's right side should be the biggest relation (lineitem);
    # the deepest subtree should contain region/nation (smallest)
    top = joins[0]
    right_tables = _scans_in_order(top.right)
    assert right_tables == ["lineitem"]
    deepest = _scans_in_order(joins[-1])
    assert set(deepest) <= {"region", "nation", "supplier"}


def test_q5_broadcast_choice(sess):
    """The small accumulated side is marked for broadcast against the
    large lineitem side."""
    plan = _plan_of(sess, Q5)
    top = _joins(plan)[0]
    assert top.broadcast == "left"


def test_selectivity_histogram_range(sess):
    """Date range selectivity comes from the histogram, not the 1/3
    pseudo rate: a one-year slice of a 6.5-year uniform range estimates
    ~15%, far from 33%."""
    plan = _plan_of(
        sess,
        "select count(*) from orders "
        "where o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01'",
    )
    smap = gather_stats(plan, sess.catalog)
    n = est_rows(plan, sess.catalog, smap)
    orders = sess.catalog.table("tpch", "orders")
    actual = None
    r = sess.must_query(
        "select count(*) from orders where o_orderdate >= date '1994-01-01' "
        "and o_orderdate < date '1995-01-01'"
    )
    actual = r.rows[0][0]
    # estimate within 2x of the true count and well under the pseudo 1/3
    assert actual / 2 <= _agg_input_est(plan) <= actual * 2
    assert _agg_input_est(plan) < orders.nrows / 4


def _agg_input_est(plan):
    # est of the Selection feeding the aggregate
    from tidb_tpu.planner.logical import Selection

    cur = plan
    while cur is not None:
        if isinstance(cur, Selection):
            return cur.est
        cur = getattr(cur, "child", None)
    raise AssertionError("no Selection in plan")


def test_eq_selectivity_uses_ndv(sess):
    plan = _plan_of(
        sess, "select count(*) from supplier where s_suppkey = 17"
    )
    est_rows(plan, sess.catalog)
    assert _agg_input_est(plan) <= 2  # 1/NDV of a unique key -> ~1 row


def test_broadcast_join_mesh_parity(sess):
    """The broadcast-join path produces identical results on the 8-device
    mesh (all_gather of the small side instead of all_to_all of both)."""
    mesh = Session(sess.catalog, db="tpch", mesh_devices=8)
    sql = (
        "select n_name, count(*) from supplier, nation "
        "where s_nationkey = n_nationkey group by n_name "
        "order by count(*) desc, n_name limit 5"
    )
    plan = _plan_of(sess, sql)
    assert any(j.broadcast for j in _joins(plan))
    a = sess.must_query(sql)
    b = mesh.must_query(sql)
    assert a.rows == b.rows
    c = sess.must_query(Q5)
    d = mesh.must_query(Q5)
    assert len(c.rows) == len(d.rows)
    for x, y in zip(c.rows, d.rows):
        assert x[0] == y[0]
        assert abs(x[1] - y[1]) < 0.02


class TestAutoAnalyze:
    """Auto-analyze: modify counters drive stats refresh (reference
    pkg/statistics/handle/autoanalyze/autoanalyze.go:264)."""

    def test_dml_triggers_analyze(self):
        from tidb_tpu.session.session import Session

        s = Session()
        s.execute("create table aa (a int)")
        t = s.catalog.table("test", "aa")
        assert getattr(t, "stats", None) is None
        s.execute(
            "insert into aa values " + ",".join(f"({i % 7})" for i in range(150))
        )
        assert t.stats is not None and t.stats["a"].ndv == 7

    def test_small_changes_do_not_churn(self):
        from tidb_tpu.session.session import Session

        s = Session()
        s.execute("create table aa (a int)")
        s.execute(
            "insert into aa values " + ",".join(f"({i})" for i in range(100))
        )
        t = s.catalog.table("test", "aa")
        ver = t.stats_version
        s.execute("insert into aa values (1)")
        assert t.stats_version == ver

    def test_disabled_by_sysvar_and_handle_tick(self):
        from tidb_tpu.session.session import Session
        from tidb_tpu.stats.handle import StatsHandle

        s = Session()
        s.execute("set global tidb_enable_auto_analyze = 0")
        s.execute("create table aa (a int)")
        s.execute(
            "insert into aa values " + ",".join(f"({i})" for i in range(100))
        )
        t = s.catalog.table("test", "aa")
        assert getattr(t, "stats", None) is None
        h = StatsHandle(s.catalog)
        assert h.tick() == 0  # daemon honors the disable sysvar
        s.execute("set global tidb_enable_auto_analyze = 1")
        assert h.tick() >= 1
        assert t.stats is not None

    def test_manual_analyze_resets_counter(self):
        from tidb_tpu.session.session import Session
        from tidb_tpu.stats.handle import needs_analyze

        s = Session()
        s.execute("set global tidb_enable_auto_analyze = 0")
        s.execute("create table aa (a int)")
        s.execute(
            "insert into aa values " + ",".join(f"({i})" for i in range(100))
        )
        t = s.catalog.table("test", "aa")
        assert needs_analyze(t, 0.5)
        s.execute("analyze table aa")
        assert not needs_analyze(t, 0.5)


def test_sampled_analyze_estimates(monkeypatch):
    """Above SAMPLE_CAP rows ANALYZE samples: row_count stays exact,
    NDV/bucket counts become scaled estimates in the right range
    (reference sampling regime: pkg/statistics row_sampler.go)."""
    import tidb_tpu.stats.collect as collect
    from tidb_tpu.session import Session

    monkeypatch.setattr(collect, "SAMPLE_CAP", 1000)
    s = Session()
    s.execute("create database sd")
    s.execute("use sd")
    s.execute("create table t (k int, v int)")
    import numpy as np

    rng = np.random.default_rng(3)
    n = 20_000
    ks = rng.integers(0, 50, n)  # 50 distinct, heavy hitters
    vs = np.arange(n)  # all distinct
    t = s.catalog.table("sd", "t")
    from tidb_tpu.chunk import HostBlock, column_from_values
    from tidb_tpu.dtypes import INT64

    t.replace_blocks([
        HostBlock.from_columns({
            "k": column_from_values(ks.tolist(), INT64),
            "v": column_from_values(vs.tolist(), INT64),
        })
    ])
    s.execute("analyze table t")
    st = t.stats
    assert st["k"].row_count == n and st["v"].row_count == n
    # low-cardinality column: sample sees every value, no blow-up
    assert 40 <= st["k"].ndv <= 70
    # all-distinct column: Haas-Stokes scales singletons back up
    assert st["v"].ndv > 5_000
    assert st["v"].ndv <= n
