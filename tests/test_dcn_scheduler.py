"""Cross-host DCN fragment scheduler: planning, dispatch, recovery.

Reference: MPP dispatch + probe + retry (pkg/store/copr/mpp.go:93,
mpp_probe.go:33, pkg/executor/internal/mpp/recovery_handler.go:26).
These tests run the coordinator against in-process EngineServers (the
unistore move: full protocol, no cluster); the true 2-process x
4-device dryrun lives in test_multihost.py.
"""

import pytest

from tidb_tpu.parallel.dcn import (
    DCNFragmentScheduler,
    FragmentLedger,
    HostHeartbeat,
)
from tidb_tpu.parser.sqlparse import parse
from tidb_tpu.planner import logical as L
from tidb_tpu.planner.fragmenter import split_plan
from tidb_tpu.planner.logical import build_query
from tidb_tpu.server.engine_pool import FailedEngineProber
from tidb_tpu.server.engine_rpc import DropConnection, EngineServer
from tidb_tpu.session.session import Session
from tidb_tpu.utils import failpoint


@pytest.fixture()
def sess():
    s = Session()
    s.execute(
        "create table t (a int, b varchar(8), c decimal(10,2), d date)"
    )
    s.execute(
        "insert into t values (1,'x',1.50,'1998-01-01'),"
        "(2,'y',2.25,'1998-02-01'),(3,'x',0.25,'1998-03-01'),"
        "(4,null,10.00,'1998-01-15'),(null,'z',3.00,null)"
    )
    s.execute("create table u (k int, v int)")
    s.execute("insert into u values (1,10),(2,20),(3,30),(4,40)")
    return s


def _plan(sess, q):
    return build_query(parse(q)[0], sess.catalog, "test", sess._scalar_subquery)


def _servers(sess, n=2):
    out = []
    for _ in range(n):
        srv = EngineServer(sess.catalog, port=0)
        srv.start_background()
        out.append(srv)
    return out


GROUPED = "select b, count(*), sum(a) from t group by b order by b"


class TestFragmentPlanning:
    def test_agg_cut_slices_largest_scan(self, sess):
        frag = split_plan(_plan(sess, GROUPED), sess.catalog)
        assert frag is not None
        assert frag.frag_scan.table == "t"
        # partial wire schema: group key + partial count + partial sum
        names = [c.internal for c in frag.partial_schema.cols]
        assert names[0] == "_g0" and len(names) == 3
        hp = frag.host_plan(1, 3)
        scans = []
        from tidb_tpu.planner.fragmenter import _candidate_scans

        _candidate_scans(hp.child, scans)
        assert [s.frag for s in scans] == [(1, 3)]
        # the template itself stays unsliced (reusable for any host)
        assert frag.frag_scan.frag is None

    def test_join_slices_probe_replicates_build(self, sess):
        q = (
            "select b, count(*) from t join u on a = k "
            "group by b order by b"
        )
        frag = split_plan(_plan(sess, q), sess.catalog)
        assert frag is not None
        assert frag.frag_scan.table == "t"  # larger side sliced

    def test_distinct_agg_falls_back(self, sess):
        # single-DISTINCT rewrites to stacked aggregates whose inner agg
        # pins the subtree: no safe slice -> whole-plan dispatch
        q = "select b, count(distinct a) from t group by b"
        assert split_plan(_plan(sess, q), sess.catalog) is None

    def test_no_agg_peels_sort_limit(self, sess):
        frag = split_plan(
            _plan(sess, "select a, b from t order by a desc limit 3"),
            sess.catalog,
        )
        assert frag is not None
        assert not isinstance(frag.template, (L.Sort, L.Limit))
        final = frag.final_builder(
            L.Staged(frag.partial_schema, batch=None, dicts={}, nonce=0)
        )
        # the peeled chain (projection/limit/sort) re-applies above the
        # staged union, in original order
        kinds = []
        node = final
        while not isinstance(node, L.Staged):
            kinds.append(type(node).__name__)
            node = node.child
        assert "Sort" in kinds and "Limit" in kinds
        assert kinds.index("Limit") < kinds.index("Sort")

    def test_frag_ir_roundtrip(self, sess):
        from tidb_tpu.planner.ir import deserialize_plan, serialize_plan

        frag = split_plan(_plan(sess, GROUPED), sess.catalog)
        hp = frag.host_plan(1, 2)
        rt = deserialize_plan(serialize_plan(hp))
        scans = []
        from tidb_tpu.planner.fragmenter import _candidate_scans

        _candidate_scans(rt.child, scans)
        assert [s.frag for s in scans] == [(1, 2)]


QUERIES = [
    "select count(*), sum(c), min(a), max(b) from t",
    "select b, count(*), sum(c), avg(c) from t group by b order by b",
    "select b, count(*) from t join u on a = k where v < 35 "
    "group by b order by count(*) desc, b limit 2",
    "select a, b from t order by a desc limit 3",
    "select b, count(distinct a) from t group by b order by b",
    "select avg(a) from t",
    "select d, count(*) from t group by d order by d",
]


class TestSchedulerParity:
    def test_two_host_parity(self, sess):
        srvs = _servers(sess, 2)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in srvs], catalog=sess.catalog
        )
        try:
            for q in QUERIES:
                exp = sess.must_query(q).rows
                _cols, got = sched.execute_plan(_plan(sess, q))
                assert got == exp, f"{q}\n got={got}\n exp={exp}"
        finally:
            sched.close()
            for s in srvs:
                s.shutdown()

    def test_partial_agg_crosses_the_wire(self, sess):
        """The DCN exchange carries PARTIAL rows: each host ships its
        group partials, not raw rows (partial-agg-before-DCN)."""
        executed = []
        failpoint.enable("dcn/fragment-execute", lambda: executed.append(1))
        srvs = _servers(sess, 2)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in srvs], catalog=sess.catalog
        )
        try:
            exp = sess.must_query(GROUPED).rows
            _cols, got = sched.execute_plan(_plan(sess, GROUPED))
            assert got == exp
            assert len(executed) == 2  # one fragment per host
        finally:
            failpoint.disable("dcn/fragment-execute")
            sched.close()
            for s in srvs:
                s.shutdown()


class TestLedger:
    def test_exactly_once_fences(self):
        led = FragmentLedger(2)
        tok = led.claim(0, "h0")
        assert led.complete(0, tok, [(1,)]) is True
        # duplicate redelivery of landed work: dropped
        assert led.complete(0, tok, [(1,)]) is False
        # transport loss -> release -> re-dispatch; the zombie original
        # attempt's late reply must lose to the fence
        tok1 = led.claim(1, "h0")
        led.release(1, tok1)
        tok1b = led.claim(1, "h1")
        assert led.complete(1, tok1, [(9,)]) is False
        assert led.complete(1, tok1b, [(2,)]) is True
        assert led.all_done()
        assert led.duplicates_dropped == 2
        assert led.rows() == [(1,), (2,)]

    def test_release_requires_token(self):
        led = FragmentLedger(1)
        tok = led.claim(0, "h0")
        led.release(0, "not-the-token")
        assert led.pending() == []  # still inflight
        led.release(0, tok)
        assert led.pending() == [0]


class TestFailureRecovery:
    def test_worker_death_after_work_before_reply(self, sess):
        """dcn/result-send death: the fragment's work happened but the
        reply was lost — re-dispatch onto the survivor must return
        correct results exactly once (no double counting)."""
        srvs = _servers(sess, 2)
        failpoint.enable(
            "dcn/result-send", failpoint.after_n(1, DropConnection)
        )
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in srvs],
            catalog=sess.catalog,
            prober=FailedEngineProber(initial_backoff_s=30),
        )
        try:
            exp = sess.must_query(GROUPED).rows
            _cols, got = sched.execute_plan(_plan(sess, GROUPED))
            assert got == exp
            assert len(sched.prober.failed_endpoints()) == 1
        finally:
            failpoint.disable("dcn/result-send")
            sched.close()
            for s in srvs:
                s.shutdown()

    def test_dispatch_lost_redispatches(self, sess):
        srvs = _servers(sess, 2)
        failpoint.enable(
            "dcn/dispatch-lost", failpoint.after_n(1, lambda: True)
        )
        redispatched = []
        failpoint.enable("dcn/redispatch", lambda: redispatched.append(1))
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in srvs],
            catalog=sess.catalog,
            prober=FailedEngineProber(initial_backoff_s=30),
        )
        try:
            exp = sess.must_query(GROUPED).rows
            _cols, got = sched.execute_plan(_plan(sess, GROUPED))
            assert got == exp
            assert len(redispatched) == 1
        finally:
            failpoint.disable("dcn/dispatch-lost")
            failpoint.disable("dcn/redispatch")
            sched.close()
            for s in srvs:
                s.shutdown()

    def test_duplicate_redelivery_failpoint(self, sess):
        """The in-vivo fence drill: every completion is immediately
        redelivered; the second landing must be dropped and results
        stay correct."""
        srvs = _servers(sess, 1)
        failpoint.enable("dcn/duplicate-redelivery", True)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", srvs[0].port)], catalog=sess.catalog
        )
        try:
            exp = sess.must_query(GROUPED).rows
            _cols, got = sched.execute_plan(_plan(sess, GROUPED))
            assert got == exp
        finally:
            failpoint.disable("dcn/duplicate-redelivery")
            sched.close()
            srvs[0].shutdown()

    def test_heartbeat_quarantines_after_misses(self, sess):
        srvs = _servers(sess, 2)
        port1 = srvs[1].port
        srvs[1].shutdown()
        prober = FailedEngineProber(initial_backoff_s=30)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", srvs[0].port), ("127.0.0.1", port1)],
            catalog=sess.catalog, prober=prober,
        )
        try:
            assert sched.heartbeat.beat_once() == []  # 1st miss: suspect
            lost = sched.heartbeat.beat_once()  # 2nd miss: quarantine
            assert [ep.port for ep in lost] == [port1]
            assert [ep.port for ep in prober.failed_endpoints()] == [port1]
            # the survivor still answers queries (fewer fragments)
            exp = sess.must_query(GROUPED).rows
            _cols, got = sched.execute_plan(_plan(sess, GROUPED))
            assert got == exp
        finally:
            sched.close()
            srvs[0].shutdown()

    def test_heartbeat_timeout_failpoint(self, sess):
        srvs = _servers(sess, 1)
        prober = FailedEngineProber(initial_backoff_s=30)
        hb = HostHeartbeat(
            sched_endpoints(srvs), prober, miss_threshold=2
        )
        failpoint.enable("dcn/heartbeat-timeout", True)
        try:
            assert hb.beat_once() == []
            lost = hb.beat_once()
            assert len(lost) == 1  # forced misses quarantine a live host
        finally:
            failpoint.disable("dcn/heartbeat-timeout")
            srvs[0].shutdown()

    def test_all_hosts_down_raises(self, sess):
        srvs = _servers(sess, 1)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", srvs[0].port)], catalog=sess.catalog,
            max_attempts=2,
            prober=FailedEngineProber(initial_backoff_s=30),
        )
        srvs[0].shutdown()
        try:
            with pytest.raises(ConnectionError):
                sched.execute_plan(_plan(sess, GROUPED))
        finally:
            sched.close()


def sched_endpoints(srvs):
    from tidb_tpu.server.engine_pool import EngineEndpoint

    return [EngineEndpoint("127.0.0.1", s.port) for s in srvs]


class TestTelemetry:
    """Trace-context propagation + fragment runtime stats over the
    engine-RPC seam (coordinator merge in parallel/dcn.py)."""

    def test_fragment_stats_and_spans_merge(self, sess):
        srvs = _servers(sess, 2)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in srvs], catalog=sess.catalog
        )
        sched.tracer.enabled = True
        sched.tracer.reset()
        try:
            exp = sess.must_query(GROUPED).rows
            _cols, got = sched.execute_plan(_plan(sess, GROUPED))
            assert got == exp
            frags = sched.last_query["fragments"]
            assert sorted(f["fid"] for f in frags) == [0, 1]
            for f in frags:
                assert f["exec_s"] > 0 and f["bytes"] > 0
                assert f["attempt"] == 1
                # the worker's spans carry the propagated trace context
                qid = sched.last_query["qid"]
                assert any(
                    f"q{qid}/f{f['fid']}/execute" in s[0]
                    for s in f["spans"]
                )
            # coordinator tracer: every remote span host-labeled, one
            # execute span per fragment
            ex = [
                s for s in sched.tracer.spans
                if s.name.endswith("/execute")
            ]
            assert len(ex) == 2
            assert all(":" in s.name for s in ex)
        finally:
            sched.close()
            for s in srvs:
                s.shutdown()

    def test_spans_survive_worker_retry_without_duplication(self, sess):
        """dcn/result-send death: the zombie attempt's reply is lost, the
        retry's reply lands — the merged telemetry must hold each
        fragment EXACTLY once (the ledger fence gates the span merge)."""
        from tidb_tpu.utils.metrics import REGISTRY

        srvs = _servers(sess, 2)
        failpoint.enable(
            "dcn/result-send", failpoint.after_n(1, DropConnection)
        )
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in srvs],
            catalog=sess.catalog,
            prober=FailedEngineProber(initial_backoff_s=30),
        )
        sched.tracer.enabled = True
        sched.tracer.reset()
        retries0 = REGISTRY.counter("tidbtpu_dcn_retries").value
        try:
            exp = sess.must_query(GROUPED).rows
            _cols, got = sched.execute_plan(_plan(sess, GROUPED))
            assert got == exp
            frags = sched.last_query["fragments"]
            # exactly once per fragment, even though one was re-dispatched
            assert sorted(f["fid"] for f in frags) == [0, 1]
            assert max(f["attempt"] for f in frags) == 2
            ex = [
                s for s in sched.tracer.spans
                if s.name.endswith("/execute")
            ]
            assert len(ex) == 2  # no duplicated spans from the retry
            assert REGISTRY.counter("tidbtpu_dcn_retries").value == retries0 + 1
        finally:
            failpoint.disable("dcn/result-send")
            sched.close()
            for s in srvs:
                s.shutdown()

    def test_status_and_dcn_endpoint(self, sess):
        import json
        import urllib.request

        from tidb_tpu.server.http_status import StatusServer

        srvs = _servers(sess, 2)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in srvs], catalog=sess.catalog
        )
        http = StatusServer(sess.catalog, port=0, dcn=sched)
        http.start_background()
        try:
            sched.execute_plan(_plan(sess, GROUPED))
            body = json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/dcn", timeout=10
                ).read().decode()
            )
            assert body["enabled"] is True
            assert body["alive"] == 2 and len(body["hosts"]) == 2
            lq = body["last_query"]
            assert [f["fid"] for f in lq["fragments"]] == [0, 1]
            assert all(
                "spans" not in f and f["bytes"] > 0
                for f in lq["fragments"]
            )
        finally:
            http.shutdown()
            sched.close()
            for s in srvs:
                s.shutdown()
