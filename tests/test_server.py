"""MySQL wire protocol tests with a minimal raw-socket client
(reference: pkg/server tests driving the protocol directly)."""

import socket
import struct

import pytest

from tidb_tpu.server import Server
from tidb_tpu.server import protocol as P


class MiniClient:
    """Just enough of the client side: handshake + COM_QUERY text results."""

    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        self.io = P.PacketIO(self.sock)
        greeting = self.io.read_packet()
        assert greeting[0] == 0x0A, "expected handshake v10"
        self.server_version = greeting[1:greeting.index(b"\x00", 1)].decode()
        # HandshakeResponse41: caps, max packet, charset, 23 zeros, user, auth
        caps = P.CLIENT_PROTOCOL_41 | P.CLIENT_SECURE_CONNECTION
        body = struct.pack("<I", caps) + struct.pack("<I", 1 << 24) + bytes([0xFF])
        body += b"\x00" * 23 + b"root\x00" + bytes([0])
        self.io.write_packet(body)
        ok = self.io.read_packet()
        assert ok[0] == 0x00, f"auth failed: {ok!r}"

    def _lenenc(self, data, pos):
        v = data[pos]
        if v < 251:
            return v, pos + 1
        if v == 0xFC:
            return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
        if v == 0xFD:
            return int.from_bytes(data[pos + 1:pos + 4], "little"), pos + 4
        return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9

    def query(self, sql):
        self.io.reset_seq()
        self.io.write_packet(b"\x03" + sql.encode())
        first = self.io.read_packet()
        if first[0] == 0xFF:
            errno = struct.unpack_from("<H", first, 1)[0]
            raise RuntimeError(f"server error {errno}: {first[9:].decode()}")
        if first[0] == 0x00:
            affected, pos = self._lenenc(first, 1)
            return {"affected": affected, "rows": None}
        ncols, _ = self._lenenc(first, 0)
        names = []
        for _ in range(ncols):
            colpkt = self.io.read_packet()
            pos = 0
            vals = []
            for _f in range(6):
                ln, pos = self._lenenc(colpkt, pos)
                vals.append(colpkt[pos:pos + ln])
                pos += ln
            names.append(vals[4].decode())
        eof = self.io.read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.io.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            row = []
            pos = 0
            while pos < len(pkt):
                if pkt[pos] == 0xFB:
                    row.append(None)
                    pos += 1
                else:
                    ln, pos = self._lenenc(pkt, pos)
                    row.append(pkt[pos:pos + ln].decode())
                    pos += ln
            rows.append(tuple(row))
        return {"columns": names, "rows": rows}

    def close(self):
        try:
            self.io.reset_seq()
            self.io.write_packet(b"\x01")
        except OSError:
            pass
        self.sock.close()


@pytest.fixture(scope="module")
def server():
    srv = Server(port=0)  # ephemeral port
    srv.start_background()
    yield srv
    srv.shutdown()


def test_handshake_and_ddl_dml(server):
    c = MiniClient(server.port)
    assert "tidb-tpu" in c.server_version
    r = c.query("create table w (a bigint, b varchar(10), d date)")
    assert r["rows"] is None
    r = c.query("insert into w values (1, 'x', '2024-01-15'), (2, null, null)")
    assert r["affected"] == 2
    r = c.query("select a, b, d from w order by a")
    assert r["columns"] == ["a", "b", "d"]
    assert r["rows"] == [("1", "x", "2024-01-15"), ("2", None, None)]
    c.close()


def test_error_keeps_connection(server):
    c = MiniClient(server.port)
    with pytest.raises(RuntimeError, match="server error"):
        c.query("select * from no_such_table")
    r = c.query("select 1 + 1")
    assert r["rows"] == [("2",)]
    c.close()


def test_aggregates_and_decimals(server):
    c = MiniClient(server.port)
    c.query("create table m (v decimal(10,2))")
    c.query("insert into m values (1.50), (2.25), (null)")
    r = c.query("select count(*), sum(v), avg(v) from m")
    assert r["rows"][0][0] == "3"
    assert r["rows"][0][1] == "3.75"
    c.close()


def test_two_connections_share_catalog(server):
    c1 = MiniClient(server.port)
    c2 = MiniClient(server.port)
    c1.query("create table shared (x bigint)")
    c1.query("insert into shared values (42)")
    r = c2.query("select x from shared")
    assert r["rows"] == [("42",)]
    c1.close()
    c2.close()


class PreparedClient(MiniClient):
    """Binary-protocol extension: COM_STMT_PREPARE / EXECUTE / CLOSE
    (reference: conn_stmt.go client side as exercised by real drivers)."""

    MYSQL_TYPE = {
        int: 8,      # LONGLONG
        float: 5,    # DOUBLE
        str: 253,    # VAR_STRING
        type(None): 6,
    }

    def prepare(self, sql):
        self.io.reset_seq()
        self.io.write_packet(b"\x16" + sql.encode())
        first = self.io.read_packet()
        assert first[0] == 0x00, first
        stmt_id = struct.unpack_from("<I", first, 1)[0]
        ncols = struct.unpack_from("<H", first, 5)[0]
        nparams = struct.unpack_from("<H", first, 7)[0]
        for _ in range(nparams):
            self.io.read_packet()
        if nparams:
            eof = self.io.read_packet()
            assert eof[0] == 0xFE
        for _ in range(ncols):
            self.io.read_packet()
        if ncols:
            self.io.read_packet()
        return stmt_id, nparams

    def execute(self, stmt_id, params, send_types=True):
        self.io.reset_seq()
        payload = b"\x17" + struct.pack("<I", stmt_id) + b"\x00" + struct.pack("<I", 1)
        n = len(params)
        if n:
            bitmap = bytearray((n + 7) // 8)
            for i, v in enumerate(params):
                if v is None:
                    bitmap[i // 8] |= 1 << (i % 8)
            payload += bytes(bitmap)
            payload += b"\x01" if send_types else b"\x00"
            if send_types:
                for v in params:
                    payload += struct.pack("<H", self.MYSQL_TYPE[type(v)])
            for v in params:
                if v is None:
                    continue
                if isinstance(v, int):
                    payload += struct.pack("<q", v)
                elif isinstance(v, float):
                    payload += struct.pack("<d", v)
                else:
                    b = str(v).encode()
                    payload += bytes([len(b)]) + b
        self.io.write_packet(payload)
        return self._read_binary_resultset()

    def _read_binary_resultset(self):
        first = self.io.read_packet()
        if first[0] == 0xFF:
            errno = struct.unpack_from("<H", first, 1)[0]
            raise RuntimeError(f"server error {errno}: {first[9:].decode()}")
        if first[0] == 0x00 and len(first) < 9:
            affected, _ = self._lenenc(first, 1)
            return {"affected": affected, "rows": None}
        ncols, _ = self._lenenc(first, 0)
        names, mtypes = [], []
        for _ in range(ncols):
            colpkt = self.io.read_packet()
            pos = 0
            vals = []
            for _f in range(6):
                ln, pos = self._lenenc(colpkt, pos)
                vals.append(colpkt[pos:pos + ln])
                pos += ln
            names.append(vals[4].decode())
            mtypes.append(colpkt[pos + 7])  # fixed-len part: type byte
        eof = self.io.read_packet()
        assert eof[0] == 0xFE
        rows = []
        while True:
            pkt = self.io.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                break
            rows.append(self._decode_binary_row(pkt, ncols, mtypes))
        return {"columns": names, "rows": rows}

    def _decode_binary_row(self, pkt, ncols, mtypes):
        nb = (ncols + 7 + 2) // 8
        bitmap = pkt[1:1 + nb]
        pos = 1 + nb
        row = []
        for i, mt in enumerate(mtypes):
            if bitmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
                row.append(None)
                continue
            if mt == 8:  # LONGLONG
                row.append(struct.unpack_from("<q", pkt, pos)[0])
                pos += 8
            elif mt == 5:  # DOUBLE
                row.append(struct.unpack_from("<d", pkt, pos)[0])
                pos += 8
            elif mt == 1:  # TINY (bool)
                row.append(struct.unpack_from("<b", pkt, pos)[0])
                pos += 1
            elif mt == 10:  # DATE
                ln = pkt[pos]
                pos += 1
                y, mo, d = struct.unpack_from("<HBB", pkt, pos)
                row.append(f"{y:04d}-{mo:02d}-{d:02d}")
                pos += ln
            else:  # VAR_STRING / NEWDECIMAL
                ln, pos = self._lenenc(pkt, pos)
                row.append(pkt[pos:pos + ln].decode())
                pos += ln
        return tuple(row)


def test_prepared_statements_binary_protocol(server):
    c = PreparedClient(server.port)
    c.query("create table ps (k bigint primary key, v double, nm varchar(16), d date)")
    sid, np_ = c.prepare("insert into ps values (?, ?, ?, ?)")
    assert np_ == 4
    c.execute(sid, [1, 1.5, "alpha", "2024-03-31"])
    c.execute(sid, [2, None, "beta's", None])
    r = c.query("select count(*) from ps")
    assert r["rows"] == [("2",)]

    sid2, np2 = c.prepare("select k, v, nm, d from ps where k = ?")
    assert np2 == 1
    r = c.execute(sid2, [1])
    assert r["rows"] == [(1, 1.5, "alpha", "2024-03-31")]
    r = c.execute(sid2, [2])
    assert r["rows"] == [(2, None, "beta's", None)]  # NULLs + quote escape
    # reuse with another parameter; placeholder inside a string literal
    sid3, np3 = c.prepare("select nm from ps where nm <> '?' and k = ?")
    assert np3 == 1
    r = c.execute(sid3, [1])
    assert r["rows"] == [("alpha",)]
    c.close()


def test_prepared_reexecute_without_types(server):
    """Real drivers send parameter types only on the first execute; the
    server must reuse them (new-params-bound flag = 0)."""
    c = PreparedClient(server.port)
    c.query("create table ps2 (k bigint primary key, v bigint)")
    c.query("insert into ps2 values (1, 10), (2, 20), (42, 420)")
    sid, _ = c.prepare("select v from ps2 where k = ?")
    r = c.execute(sid, [1])  # first execute: types sent
    assert r["rows"] == [(10,)]
    r = c.execute(sid, [42], send_types=False)  # re-execute: no types
    assert r["rows"] == [(420,)]
    c.close()


class CursorClient(PreparedClient):
    """COM_STMT_EXECUTE with CURSOR_TYPE_READ_ONLY + COM_STMT_FETCH
    (reference: conn_stmt.go:153-155 useCursor — forward-only read-only
    server-side cursors, the JDBC setFetchSize path)."""

    def execute_cursor(self, stmt_id, params=()):
        self.io.reset_seq()
        payload = (
            b"\x17" + struct.pack("<I", stmt_id) + b"\x01"  # READ_ONLY
            + struct.pack("<I", 1)
        )
        assert not params  # cursor tests use parameterless statements
        self.io.write_packet(payload)
        first = self.io.read_packet()
        assert first[0] not in (0xFF,), first
        ncols, _ = self._lenenc(first, 0)
        names, mtypes = [], []
        for _ in range(ncols):
            colpkt = self.io.read_packet()
            pos = 0
            vals = []
            for _f in range(6):
                ln, pos = self._lenenc(colpkt, pos)
                vals.append(colpkt[pos:pos + ln])
                pos += ln
            names.append(vals[4].decode())
            mtypes.append(colpkt[pos + 7])
        eof = self.io.read_packet()
        assert eof[0] == 0xFE
        status = struct.unpack_from("<H", eof, 3)[0]
        assert status & 0x0040, hex(status)  # SERVER_STATUS_CURSOR_EXISTS
        return names, mtypes

    def fetch(self, stmt_id, n, mtypes):
        self.io.reset_seq()
        self.io.write_packet(
            b"\x1c" + struct.pack("<I", stmt_id) + struct.pack("<I", n)
        )
        rows = []
        while True:
            pkt = self.io.read_packet()
            if pkt[0] == 0xFE and len(pkt) < 9:
                status = struct.unpack_from("<H", pkt, 3)[0]
                return rows, bool(status & 0x0080)  # LAST_ROW_SENT
            rows.append(self._decode_binary_row(pkt, len(mtypes), mtypes))


def test_cursor_fetch(server):
    c = CursorClient(server.port)
    try:
        c.query("create database if not exists curdb")
        c.query("use curdb")
        c.query("create table ct (a int)")
        c.query("insert into ct values (1), (2), (3), (4), (5)")
        sid, _np = c.prepare("select a from ct order by a")
        names, mtypes = c.execute_cursor(sid)
        assert names == ["a"]
        rows, last = c.fetch(sid, 2, mtypes)
        assert rows == [(1,), (2,)] and not last
        rows, last = c.fetch(sid, 2, mtypes)
        assert rows == [(3,), (4,)] and not last
        rows, last = c.fetch(sid, 2, mtypes)
        assert rows == [(5,)] and last
        # drained cursor: a further fetch errors cleanly
        c.io.reset_seq()
        c.io.write_packet(b"\x1c" + struct.pack("<I", sid) + struct.pack("<I", 1))
        pkt = c.io.read_packet()
        assert pkt[0] == 0xFF
        # plain execute on the same statement still works (no cursor)
        r = c.execute(sid, [])
        assert r["rows"] == [(1,), (2,), (3,), (4,), (5,)]
    finally:
        c.close()


def test_cursor_reset_discards(server):
    c = CursorClient(server.port)
    try:
        c.query("create database if not exists curdb2")
        c.query("use curdb2")
        c.query("create table ct (a int)")
        c.query("insert into ct values (1), (2)")
        sid, _np = c.prepare("select a from ct order by a")
        _names, mtypes = c.execute_cursor(sid)
        c.io.reset_seq()
        c.io.write_packet(b"\x1a" + struct.pack("<I", sid))  # STMT_RESET
        assert c.io.read_packet()[0] == 0x00
        c.io.reset_seq()
        c.io.write_packet(b"\x1c" + struct.pack("<I", sid) + struct.pack("<I", 1))
        assert c.io.read_packet()[0] == 0xFF  # cursor gone
    finally:
        c.close()
