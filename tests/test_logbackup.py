"""Log backup + PiTR + external storage abstraction.

Reference: br/pkg/storage (ExternalStorage backends), br/pkg/streamhelper
(log backup advancer + GC safepoint interaction), br/pkg/task/stream.go
(restore point). The columnar analogs live in storage/external.py and
storage/logbackup.py.
"""

import time

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.storage.external import (
    LocalStorage,
    MemStorage,
    open_storage,
)


class TestExternalStorage:
    def test_local_roundtrip(self, tmp_path):
        st = open_storage(str(tmp_path / "bk"))
        assert isinstance(st, LocalStorage)
        st.write_file("a/b.txt", b"hello")
        assert st.read_file("a/b.txt") == b"hello"
        assert st.exists("a/b.txt") and not st.exists("a/c.txt")
        assert st.list("a/") == ["a/b.txt"]
        st.delete("a/b.txt")
        assert not st.exists("a/b.txt")

    def test_memory_backend(self):
        st = open_storage("memory://bkt1")
        st.write_file("x", b"1")
        # the same bucket is visible through a second handle (object
        # stores are shared, not per-process-object)
        st2 = MemStorage("bkt1")
        assert st2.read_file("x") == b"1"
        assert open_storage("memory://other").exists("x") is False

    def test_path_escape_rejected(self, tmp_path):
        st = LocalStorage(str(tmp_path / "root"))
        with pytest.raises(ValueError):
            st.write_file("../evil", b"x")

    def test_unknown_scheme(self):
        with pytest.raises(ValueError):
            open_storage("s3://bucket/x")

    def test_npz_roundtrip(self):
        import numpy as np

        st = MemStorage("npzbkt")
        st.write_npz("f.npz", a=np.arange(5), b=np.ones(3, dtype=bool))
        data = st.read_npz("f.npz")
        assert data["a"].tolist() == [0, 1, 2, 3, 4]

    def test_backup_database_to_memory_uri(self):
        cat = Catalog()
        s = Session(cat)
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (a int, b varchar(8))")
        s.execute("insert into t values (1, 'x'), (2, null)")
        s.execute("backup database d to 'memory://brbkt'")
        cat2 = Catalog()
        s2 = Session(cat2)
        s2.execute("restore database d from 'memory://brbkt'")
        assert s2.execute("select a, b from d.t order by a").rows == [
            (1, "x"), (2, None)
        ]


@pytest.fixture()
def sess():
    cat = Catalog()
    s = Session(cat)
    s.execute("create database d")
    s.execute("use d")
    s.execute("create table t (id int primary key, v varchar(10))")
    s.execute("insert into t values (1, 'one')")
    return s


class TestLogBackup:
    def test_pitr_roundtrip(self, sess):
        uri = "memory://pitr1"
        sess.execute(f"backup log to '{uri}'")
        sess.execute("insert into t values (2, 'two')")
        time.sleep(0.01)
        ts_mid = time.time()
        time.sleep(0.01)
        sess.execute("insert into t values (3, 'three')")
        sess.execute("delete from t where id = 1")
        rows = sess.execute("backup log status").rows
        assert rows and rows[0][0] == "running"
        sess.execute("backup log stop")

        # restore to the mid point: rows 1,2 present, 3 absent
        cat2 = Catalog()
        s2 = Session(cat2)
        r = s2.execute(f"restore point from '{uri}' until {ts_mid}")
        assert r.rows == [(1,)]
        assert s2.execute("select id, v from d.t order by id").rows == [
            (1, "one"), (2, "two")
        ]

    def test_pitr_to_latest(self, sess):
        uri = "memory://pitr2"
        sess.execute(f"backup log to '{uri}'")
        sess.execute("insert into t values (2, 'two')")
        sess.execute("update t set v = 'uno' where id = 1")
        sess.execute("backup log stop")
        cat2 = Catalog()
        s2 = Session(cat2)
        s2.execute(f"restore point from '{uri}' until {time.time()}")
        assert s2.execute("select id, v from d.t order by id").rows == [
            (1, "uno"), (2, "two")
        ]

    def test_table_created_after_start_is_captured(self, sess):
        uri = "memory://pitr3"
        sess.execute(f"backup log to '{uri}'")
        sess.execute("create table t2 (x int)")
        sess.execute("insert into t2 values (42)")
        sess.execute("backup log status")  # advancer tick hooks new tables
        sess.execute("insert into t2 values (43)")
        sess.execute("backup log stop")
        cat2 = Catalog()
        s2 = Session(cat2)
        s2.execute(f"restore point from '{uri}' until {time.time()}")
        assert s2.execute("select x from d.t2 order by x").rows == [(42,), (43,)]

    def test_deltas_ship_only_new_blocks(self, sess):
        from tidb_tpu.storage.logbackup import LogBackupTask
        import json

        uri = "memory://pitr4"
        task = LogBackupTask(sess.catalog, uri)
        task.start()
        sess.execute("insert into t values (2, 'two')")
        task.advance()
        st = open_storage(uri)
        segs = st.list("log/")
        # find the delta segment for the insert: it must carry fewer
        # blocks than the table has in total (only the appended block)
        metas = []
        for fn in segs:
            d = st.read_npz(fn)
            metas.append(json.loads(d["_meta"].tobytes().decode()))
        kinds = [m["kind"] for m in metas if m["table"] == "t"]
        assert "full" in kinds and "delta" in kinds
        delta = [m for m in metas if m["kind"] == "delta"][0]
        assert len(delta["blocks"]) <= 1  # only the new block shipped
        task.stop()

    def test_gc_pin_held_until_advance(self, sess):
        # the queued version must survive GC between commit and advance
        from tidb_tpu.storage.logbackup import LogBackupTask

        task = LogBackupTask(sess.catalog, "memory://pitr5")
        task.start()
        t = sess.catalog.table("d", "t")
        v_before = t.version
        sess.execute("insert into t values (2, 'two')")
        sess.execute("insert into t values (3, 'three')")
        sess.execute("insert into t values (4, 'four')")
        # versions between v_before and now are pinned by the queue
        assert any(v > v_before for v in t._pins)
        task.advance()
        assert not any(v > v_before and v < t.version for v in t._pins)
        task.stop()

    def test_restart_into_same_storage_preserves_old_segments(self, sess):
        uri = "memory://pitr7"
        sess.execute(f"backup log to '{uri}'")
        sess.execute("insert into t values (2, 'two')")
        sess.execute("backup log stop")
        time.sleep(0.01)
        ts_between = time.time()
        time.sleep(0.01)
        sess.execute(f"backup log to '{uri}'")
        sess.execute("insert into t values (3, 'three')")
        sess.execute("backup log stop")
        # the first stream's window must still restore
        cat2 = Catalog()
        s2 = Session(cat2)
        s2.execute(f"restore point from '{uri}' until {ts_between}")
        assert s2.execute("select id from d.t order by id").rows == [(1,), (2,)]
        # and the full history too
        cat3 = Catalog()
        s3 = Session(cat3)
        s3.execute(f"restore point from '{uri}' until {time.time()}")
        assert s3.execute("select id from d.t order by id").rows == [
            (1,), (2,), (3,)
        ]

    def test_failed_write_requeues_and_keeps_pins(self, sess):
        from tidb_tpu.storage.logbackup import LogBackupTask

        task = LogBackupTask(sess.catalog, "memory://pitr8")
        task.start()
        sess.execute("insert into t values (2, 'two')")
        boom = RuntimeError("storage down")
        orig = task.storage.write_file
        task.storage.write_file = lambda *a, **k: (_ for _ in ()).throw(boom)
        with pytest.raises(RuntimeError):
            task.advance()
        assert task._queue  # requeued, not lost
        task.storage.write_file = orig
        task.advance()  # retries cleanly
        assert not task._queue
        task.stop()
        # restore sees the row captured on retry
        cat2 = Catalog()
        s2 = Session(cat2)
        s2.execute(f"restore point from 'memory://pitr8' until {time.time()}")
        assert s2.execute("select id from d.t order by id").rows == [(1,), (2,)]

    def test_failed_start_leaves_no_hooks(self, sess):
        from tidb_tpu.storage.logbackup import LogBackupTask

        task = LogBackupTask(sess.catalog, "memory://pitr9")
        task.storage.write_file = lambda *a, **k: (_ for _ in ()).throw(
            OSError("unwritable")
        )
        with pytest.raises(OSError):
            task.start()
        t = sess.catalog.table("d", "t")
        assert t.on_commit == []
        v0 = t.version
        sess.execute("insert into t values (9, 'nine')")
        sess.execute("insert into t values (10, 'ten')")
        sess.execute("insert into t values (11, 'eleven')")
        # no pins leaked: old versions get GC'd as usual
        assert all(v >= t.version - 1 for v in t._versions)
        assert v0 not in t._pins

    def test_local_storage_sibling_dir_escape_blocked(self, tmp_path):
        st = LocalStorage(str(tmp_path / "bk"))
        with pytest.raises(ValueError):
            st.write_file("../bk-evil/f", b"x")

    def test_stop_unhooks(self, sess):
        sess.execute("backup log to 'memory://pitr6'")
        sess.execute("backup log stop")
        t = sess.catalog.table("d", "t")
        assert t.on_commit == []
        with pytest.raises(ValueError):
            sess.execute("backup log stop")


class TestPiTRMetadata:
    """PiTR must reconstruct the full table state — unique indexes,
    AUTO_INCREMENT position, constraints — not just columns + PK
    (reference: BR restore rebuilds complete table info,
    br/pkg/restore/create_table; same contract for restore point)."""

    def test_restore_preserves_autoinc_and_unique_index(self):
        s = Session()
        s.execute("create database d")
        s.execute("use d")
        s.execute(
            "create table t (id int primary key auto_increment, v int)"
        )
        s.execute("create unique index uv on t (v)")
        s.execute("insert into t (v) values (10), (20)")
        uri = "memory://pitr-meta1"
        s.execute(f"backup log to '{uri}'")
        s.execute("insert into t (v) values (30)")
        s.execute("backup log stop")

        cat2 = Catalog()
        s2 = Session(cat2)
        s2.execute(f"restore point from '{uri}' until {time.time()}")
        s2.execute("use d")
        # AUTO_INCREMENT resumes past restored rows, not at 1
        s2.execute("insert into t (v) values (40)")
        ids = [r[0] for r in s2.execute("select id from t order by id").rows]
        assert len(ids) == len(set(ids)) and max(ids) >= 4
        # the unique index survived the restore and still enforces
        with pytest.raises(ValueError, match="duplicate"):
            s2.execute("insert into t (v) values (10)")

    def test_restore_over_diverged_schema_wins(self):
        s = Session()
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (id int primary key, v int)")
        s.execute("insert into t values (1, 10)")
        uri = "memory://pitr-meta2"
        s.execute(f"backup log to '{uri}'")
        s.execute("insert into t values (2, 20)")
        s.execute("backup log stop")

        # the live table diverges: DDL adds a column after the backup
        s.execute("alter table t add column extra int")
        s.execute(f"restore point from '{uri}' until {time.time()}")
        # the restored (pre-ALTER) schema wins wholesale; every column
        # of every row is readable (no stream-shaped blocks under a
        # diverged live schema)
        assert s.execute("select id, v from t order by id").rows == [
            (1, 10), (2, 20)
        ]
        cols = [r[0] for r in s.execute("show columns from t").rows]
        assert "extra" not in cols

    def test_dropped_and_recreated_table_rehooked(self):
        """A drop/create cycle under the same name must re-hook the new
        table object and restart its stream with a full capture —
        otherwise every post-recreate write silently vanishes."""
        s = Session()
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (id int primary key, v int)")
        s.execute("insert into t values (1, 10)")
        uri = "memory://pitr-recreate"
        s.execute(f"backup log to '{uri}'")
        s.execute("drop table t")
        s.execute("create table t (id int primary key, v int)")
        s.execute("insert into t values (7, 70)")
        s.execute("backup log stop")

        cat2 = Catalog()
        s2 = Session(cat2)
        s2.execute(f"restore point from '{uri}' until {time.time()}")
        assert s2.execute("select id, v from d.t order by id").rows == [
            (7, 70)
        ]
