"""Per-column collation: sort keys drive comparisons and ORDER BY.

Reference: pkg/util/collate/collate.go:66 (Collator interface — Compare
and Key per collation). The columnar analog builds dense collation-rank
LUTs over the dictionary at compile time: rank comparison IS the
collation comparison, one gather per row on device.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.utils import collate


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create database coll")
    s.execute("use coll")
    return s


class TestCollatorKeys:
    def test_general_ci_keys(self):
        kf = collate.key_fn("utf8mb4_general_ci")
        assert kf("abc") == kf("ABC") == kf("AbC")
        assert kf("a") != kf("b")
        assert kf("trail  ") == kf("trail")  # PAD SPACE
        assert kf("Ä") == kf("ä")

    def test_unicode_ci_keys(self):
        kf = collate.key_fn("utf8mb4_unicode_ci")
        assert kf("é") == kf("e") == kf("E")
        assert kf("Å") == kf("a")

    def test_binary_identity(self):
        assert collate.is_binary("utf8mb4_bin")
        assert collate.is_binary(None)
        assert not collate.is_binary("utf8mb4_general_ci")

    def test_unknown_collation_rejected(self):
        with pytest.raises(ValueError, match="Unknown collation"):
            collate.validate("klingon_ci")


class TestCIColumn:
    def setup_t(self, sess):
        sess.execute(
            "create table t (s varchar(16) collate utf8mb4_general_ci, "
            "k int)"
        )
        sess.execute(
            "insert into t values ('Apple', 1), ('apple', 2), "
            "('BANANA', 3), ('banana', 4), ('_under', 5), ('Zebra', 6)"
        )

    def test_ci_equality_literal(self, sess):
        self.setup_t(sess)
        assert sess.execute(
            "select k from t where s = 'APPLE' order by k"
        ).rows == [(1,), (2,)]
        assert sess.execute(
            "select count(*) from t where s <> 'banana'"
        ).rows == [(4,)]

    def test_ci_range_literal(self, sess):
        self.setup_t(sess)
        # general_ci compares by UPPER key: 'APPLE' < 'B' while
        # 'BANANA', 'ZEBRA', '_UNDER' ('_' = 0x5F > 'B') are not
        assert sess.execute(
            "select count(*) from t where s < 'b'"
        ).rows == [(2,)]  # Apple, apple

    def test_ci_order_by_rank(self, sess):
        self.setup_t(sess)
        rows = [r[0] for r in sess.execute(
            "select s from t order by s, k"
        ).rows]
        # collation order by UPPER key ('_UNDER' sorts LAST: 0x5F
        # follows 'Z'), case-variants adjacent with stored-order ties
        assert rows == [
            "Apple", "apple", "BANANA", "banana", "Zebra", "_under"
        ]
        # binary order would put 'Zebra' before '_under' ('Z' < '_')
        # and all lowercase after all uppercase — assert we did NOT
        binary_order = sorted(rows)
        assert rows != binary_order

    def test_ci_column_vs_column(self, sess):
        sess.execute(
            "create table a (x varchar(8) collate utf8mb4_general_ci)"
        )
        sess.execute("create table b (y varchar(8))")
        sess.execute("insert into a values ('HELLO'), ('world')")
        sess.execute("insert into b values ('hello'), ('WORLD'), ('zzz')")
        assert sess.execute(
            "select count(*) from a, b where x = y"
        ).rows == [(2,)]

    def test_charset_default_is_binary(self, sess):
        # the REFERENCE's default: utf8mb4 ships utf8mb4_bin (TiDB
        # new_collations off), so a bare charset clause stays binary
        sess.execute(
            "create table c (s varchar(8) character set utf8mb4)"
        )
        t = sess.catalog.table("coll", "c")
        assert t.schema.types["s"].collation is None

    def test_explicit_bin_collate_overrides_charset(self, sess):
        sess.execute(
            "create table cb (s varchar(8) character set utf8mb4 "
            "collate utf8mb4_bin)"
        )
        sess.execute("insert into cb values ('A'), ('a')")
        assert sess.execute(
            "select count(*) from cb where s = 'a'"
        ).rows == [(1,)]

    def test_expr_collate_bin_on_ci_column(self, sess):
        sess.execute(
            "create table eb (s varchar(8) collate utf8mb4_general_ci)"
        )
        sess.execute("insert into eb values ('A'), ('a')")
        assert sess.execute(
            "select count(*) from eb where s = 'a'"
        ).rows == [(2,)]
        assert sess.execute(
            "select count(*) from eb where s collate utf8mb4_bin = 'a'"
        ).rows == [(1,)]

    def test_tidb_snapshot_session_time_travel(self, sess):
        import time

        sess.execute("set global tidb_gc_life_time = 600")
        sess.execute("create table tt (a int)")
        sess.execute("insert into tt values (1)")
        time.sleep(0.02)
        ts = time.time()
        time.sleep(0.02)
        sess.execute("insert into tt values (2)")
        sess.execute(f"set tidb_snapshot = {ts}")
        try:
            assert sess.execute("select count(*) from tt").rows == [(1,)]
            with pytest.raises(ValueError, match="tidb_snapshot"):
                sess.execute("insert into tt values (3)")
        finally:
            sess.execute("set tidb_snapshot = ''")
        assert sess.execute("select count(*) from tt").rows == [(2,)]
        sess.execute("set global tidb_gc_life_time = 0")

    def test_unicode_ci_accents(self, sess):
        sess.execute(
            "create table u (s varchar(8) collate utf8mb4_unicode_ci)"
        )
        sess.execute("insert into u values ('café'), ('CAFE'), ('other')")
        assert sess.execute(
            "select count(*) from u where s = 'cafe'"
        ).rows == [(2,)]

    def test_binary_column_unaffected(self, sess):
        sess.execute("create table bz (s varchar(8))")
        sess.execute("insert into bz values ('A'), ('a')")
        assert sess.execute(
            "select count(*) from bz where s = 'a'"
        ).rows == [(1,)]


class TestShowStatements:
    def test_show_collation(self, sess):
        rows = sess.execute("show collation").rows
        names = [r[0] for r in rows]
        assert "utf8mb4_general_ci" in names and "utf8mb4_bin" in names
        rows2 = sess.execute("show collation like 'utf8mb4%'").rows
        assert all(r[0].startswith("utf8mb4") for r in rows2)

    def test_show_character_set(self, sess):
        rows = sess.execute("show character set").rows
        d = {r[0]: r[2] for r in rows}
        assert d["utf8mb4"] == "utf8mb4_bin"

    def test_show_engines(self, sess):
        rows = sess.execute("show engines").rows
        assert rows[0][0] == "InnoDB" and rows[0][1] == "DEFAULT"
