"""Per-column collation: sort keys drive comparisons and ORDER BY.

Reference: pkg/util/collate/collate.go:66 (Collator interface — Compare
and Key per collation). The columnar analog builds dense collation-rank
LUTs over the dictionary at compile time: rank comparison IS the
collation comparison, one gather per row on device.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.utils import collate


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create database coll")
    s.execute("use coll")
    return s


class TestCollatorKeys:
    def test_general_ci_keys(self):
        kf = collate.key_fn("utf8mb4_general_ci")
        assert kf("abc") == kf("ABC") == kf("AbC")
        assert kf("a") != kf("b")
        assert kf("trail  ") == kf("trail")  # PAD SPACE
        assert kf("Ä") == kf("ä")

    def test_unicode_ci_keys(self):
        kf = collate.key_fn("utf8mb4_unicode_ci")
        assert kf("é") == kf("e") == kf("E")
        assert kf("Å") == kf("a")

    def test_binary_identity(self):
        assert collate.is_binary("utf8mb4_bin")
        assert collate.is_binary(None)
        assert not collate.is_binary("utf8mb4_general_ci")

    def test_unknown_collation_rejected(self):
        with pytest.raises(ValueError, match="Unknown collation"):
            collate.validate("klingon_ci")


class TestCIColumn:
    def setup_t(self, sess):
        sess.execute(
            "create table t (s varchar(16) collate utf8mb4_general_ci, "
            "k int)"
        )
        sess.execute(
            "insert into t values ('Apple', 1), ('apple', 2), "
            "('BANANA', 3), ('banana', 4), ('_under', 5), ('Zebra', 6)"
        )

    def test_ci_equality_literal(self, sess):
        self.setup_t(sess)
        assert sess.execute(
            "select k from t where s = 'APPLE' order by k"
        ).rows == [(1,), (2,)]
        assert sess.execute(
            "select count(*) from t where s <> 'banana'"
        ).rows == [(4,)]

    def test_ci_range_literal(self, sess):
        self.setup_t(sess)
        # general_ci compares by UPPER key: 'APPLE' < 'B' while
        # 'BANANA', 'ZEBRA', '_UNDER' ('_' = 0x5F > 'B') are not
        assert sess.execute(
            "select count(*) from t where s < 'b'"
        ).rows == [(2,)]  # Apple, apple

    def test_ci_order_by_rank(self, sess):
        self.setup_t(sess)
        rows = [r[0] for r in sess.execute(
            "select s from t order by s, k"
        ).rows]
        # collation order by UPPER key ('_UNDER' sorts LAST: 0x5F
        # follows 'Z'), case-variants adjacent with stored-order ties
        assert rows == [
            "Apple", "apple", "BANANA", "banana", "Zebra", "_under"
        ]
        # binary order would put 'Zebra' before '_under' ('Z' < '_')
        # and all lowercase after all uppercase — assert we did NOT
        binary_order = sorted(rows)
        assert rows != binary_order

    def test_ci_column_vs_column(self, sess):
        sess.execute(
            "create table a (x varchar(8) collate utf8mb4_general_ci)"
        )
        sess.execute("create table b (y varchar(8))")
        sess.execute("insert into a values ('HELLO'), ('world')")
        sess.execute("insert into b values ('hello'), ('WORLD'), ('zzz')")
        assert sess.execute(
            "select count(*) from a, b where x = y"
        ).rows == [(2,)]

    def test_charset_default_is_binary(self, sess):
        # the REFERENCE's default: utf8mb4 ships utf8mb4_bin (TiDB
        # new_collations off), so a bare charset clause stays binary
        sess.execute(
            "create table c (s varchar(8) character set utf8mb4)"
        )
        t = sess.catalog.table("coll", "c")
        assert t.schema.types["s"].collation is None

    def test_explicit_bin_collate_overrides_charset(self, sess):
        sess.execute(
            "create table cb (s varchar(8) character set utf8mb4 "
            "collate utf8mb4_bin)"
        )
        sess.execute("insert into cb values ('A'), ('a')")
        assert sess.execute(
            "select count(*) from cb where s = 'a'"
        ).rows == [(1,)]

    def test_expr_collate_bin_on_ci_column(self, sess):
        sess.execute(
            "create table eb (s varchar(8) collate utf8mb4_general_ci)"
        )
        sess.execute("insert into eb values ('A'), ('a')")
        assert sess.execute(
            "select count(*) from eb where s = 'a'"
        ).rows == [(2,)]
        assert sess.execute(
            "select count(*) from eb where s collate utf8mb4_bin = 'a'"
        ).rows == [(1,)]

    def test_tidb_snapshot_session_time_travel(self, sess):
        import time

        sess.execute("set global tidb_gc_life_time = 600")
        sess.execute("create table tt (a int)")
        sess.execute("insert into tt values (1)")
        time.sleep(0.02)
        ts = time.time()
        time.sleep(0.02)
        sess.execute("insert into tt values (2)")
        sess.execute(f"set tidb_snapshot = {ts}")
        try:
            assert sess.execute("select count(*) from tt").rows == [(1,)]
            with pytest.raises(ValueError, match="tidb_snapshot"):
                sess.execute("insert into tt values (3)")
        finally:
            sess.execute("set tidb_snapshot = ''")
        assert sess.execute("select count(*) from tt").rows == [(2,)]
        sess.execute("set global tidb_gc_life_time = 0")

    def test_unicode_ci_accents(self, sess):
        sess.execute(
            "create table u (s varchar(8) collate utf8mb4_unicode_ci)"
        )
        sess.execute("insert into u values ('café'), ('CAFE'), ('other')")
        assert sess.execute(
            "select count(*) from u where s = 'cafe'"
        ).rows == [(2,)]

    def test_binary_column_unaffected(self, sess):
        sess.execute("create table bz (s varchar(8))")
        sess.execute("insert into bz values ('A'), ('a')")
        assert sess.execute(
            "select count(*) from bz where s = 'a'"
        ).rows == [(1,)]


class TestCIGrouping:
    """GROUP BY / DISTINCT / MIN / MAX over CI collations group and
    order by collation rank (reference collate.go Key() drives both
    compare and hash — round-4 verdict's documented divergence, closed)."""

    def setup_t(self, sess):
        sess.execute(
            "create table g (s varchar(16) collate utf8mb4_general_ci, "
            "k int)"
        )
        sess.execute(
            "insert into g values ('Ann', 1), ('ANN', 2), ('ann', 4), "
            "('Bob', 8), ('BOB', 16), ('carl', 32)"
        )

    def test_group_by_merges_case_variants(self, sess):
        self.setup_t(sess)
        rows = sess.execute(
            "select s, sum(k), count(*) from g group by s order by s"
        ).rows
        assert [(r[1], r[2]) for r in rows] == [(7, 3), (24, 2), (32, 1)]
        # representative values are group members, case-insensitively
        # equal to the class ('ANN' the binary-least of the Ann class)
        assert [r[0].upper() for r in rows] == ["ANN", "BOB", "CARL"]

    def test_distinct_merges_case_variants(self, sess):
        self.setup_t(sess)
        rows = sess.execute("select distinct s from g order by s").rows
        assert [r[0].upper() for r in rows] == ["ANN", "BOB", "CARL"]

    def test_count_distinct_ci(self, sess):
        self.setup_t(sess)
        assert sess.execute(
            "select count(distinct s) from g"
        ).rows == [(3,)]

    def test_min_max_ci_rank_order(self, sess):
        # under general_ci: min is the ANN class, max the CARL class —
        # binary code order would make '_' sort before letters wrongly
        self.setup_t(sess)
        sess.execute("insert into g values ('_z', 64)")
        (mn, mx), = sess.execute("select min(s), max(s) from g").rows
        assert mn.upper() == "ANN" and mx.upper() == "_Z"

    def test_group_by_binary_column_untouched(self, sess):
        sess.execute("create table gb (s varchar(8), k int)")
        sess.execute("insert into gb values ('A', 1), ('a', 2)")
        rows = sess.execute(
            "select s, sum(k) from gb group by s order by s"
        ).rows
        assert rows == [("A", 1), ("a", 2)]

    def test_group_by_ci_with_having(self, sess):
        self.setup_t(sess)
        rows = sess.execute(
            "select s, count(*) from g group by s "
            "having count(*) > 1 order by s"
        ).rows
        assert [(r[0].upper(), r[1]) for r in rows] == [
            ("ANN", 3), ("BOB", 2)
        ]

    def test_group_output_binary_compare(self, sess):
        # the rep dictionary must stay BINARY-sorted: a binary-collated
        # compare over the group output uses searchsorted on it
        sess.execute(
            "create table gc (s varchar(8) collate utf8mb4_general_ci, "
            "k int)"
        )
        sess.execute("insert into gc values ('B', 1), ('a', 2)")
        rows = sess.execute(
            "select * from (select s, sum(k) sk from gc group by s) t "
            "where s collate utf8mb4_bin = 'B'"
        ).rows
        assert rows == [("B", 1)]
        rows = sess.execute(
            "select s from (select s from gc group by s) t "
            "order by s collate utf8mb4_bin"
        ).rows
        assert [r[0] for r in rows] == ["B", "a"]

    def test_min_max_returns_real_member(self, sess):
        # MIN/MAX decode to actual dictionary codes: downstream binary
        # compares and joins on the result still work
        self.setup_t(sess)
        rows = sess.execute(
            "select * from (select max(s) m from g) t where m = 'carl'"
        ).rows
        assert rows == [("carl",)]

    def test_ci_group_minmax_streamed(self, sess):
        # the partial/final split must keep rank-composed values across
        # chunks and decode only at the final stage (fragment.py
        # _partial_descs post threading)
        self.setup_t(sess)
        full = sess.execute(
            "select s, min(s), max(s), sum(k) from g group by s order by s"
        ).rows
        sess.execute("set tidb_tpu_stream_rows = 2")
        try:
            streamed = sess.execute(
                "select s, min(s), max(s), sum(k) from g "
                "group by s order by s"
            ).rows
        finally:
            sess.execute("set tidb_tpu_stream_rows = 0")
        assert streamed == full
        assert [(r[0].upper(), r[3]) for r in full] == [
            ("ANN", 7), ("BOB", 24), ("CARL", 32)
        ]

    def test_ci_group_minmax_mesh(self):
        from tidb_tpu.storage import Catalog

        cat = Catalog()
        single = Session(cat)
        single.execute("create database collm")
        for s in (single,):
            s.execute("use collm")
        single.execute(
            "create table g (s varchar(16) collate utf8mb4_general_ci, "
            "k int)"
        )
        single.execute(
            "insert into g values ('Ann', 1), ('ANN', 2), ('ann', 4), "
            "('Bob', 8), ('BOB', 16), ('carl', 32)"
        )
        mesh = Session(cat, db="collm", mesh_devices=8)
        q = "select s, min(s), max(s), sum(k) from g group by s order by s"
        assert mesh.execute(q).rows == single.execute(q).rows

    def test_unicode_ci_group_accents(self, sess):
        sess.execute(
            "create table ua (s varchar(8) collate utf8mb4_unicode_ci, "
            "k int)"
        )
        sess.execute(
            "insert into ua values ('café', 1), ('CAFE', 2), ('tea', 4)"
        )
        rows = sess.execute(
            "select s, sum(k) from ua group by s order by s"
        ).rows
        assert [r[1] for r in rows] == [3, 4]


class TestShowStatements:
    def test_show_collation(self, sess):
        rows = sess.execute("show collation").rows
        names = [r[0] for r in rows]
        assert "utf8mb4_general_ci" in names and "utf8mb4_bin" in names
        rows2 = sess.execute("show collation like 'utf8mb4%'").rows
        assert all(r[0].startswith("utf8mb4") for r in rows2)

    def test_show_character_set(self, sess):
        rows = sess.execute("show character set").rows
        d = {r[0]: r[2] for r in rows}
        assert d["utf8mb4"] == "utf8mb4_bin"

    def test_show_engines(self, sess):
        rows = sess.execute("show engines").rows
        assert rows[0][0] == "InnoDB" and rows[0][1] == "DEFAULT"
