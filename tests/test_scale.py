"""Scale-tier (@slow) runs: TPC-H at SF0.1+ with quotas small enough
that the streamed (spill-analog) paths actually engage, parity-checked
against vectorized numpy oracles.

Reference: realtikvtest runs SF-sized workloads; VERDICT round-2 item #9
(scale-tier tests) and #3 (sort/join spill parity: Q18 under a memory
budget that forces staging).

Run with RUN_SLOW=1 python -m pytest tests/test_scale.py -q
(SF via TIDB_TPU_SCALE_SF, default 1.0 for the Q18 budget test).
"""

import os

import numpy as np
import pytest

from tidb_tpu.bench import load_tpch
from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.utils import failpoint

SF = float(os.environ.get("TIDB_TPU_SCALE_SF", "1"))

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def sess():
    cat = Catalog()
    load_tpch(cat, sf=SF, seed=7, tables=["orders", "lineitem"])
    s = Session(cat, db="tpch")
    for t in ("orders", "lineitem"):
        s.execute(f"analyze table {t}")
    yield s
    failpoint.disable_all()


def _li_cols(sess, *names):
    t = sess.catalog.table("tpch", "lineitem")
    out = {n: np.concatenate([b.columns[n].data for b in t.blocks()]) for n in names}
    return out


def test_q18_forced_staging_parity(sess):
    """Q18 (join + 1.5M-group agg + TopN) with a chunk budget that forces
    the big scan through the streamed join+agg path; results must match
    both the unpaged run and a numpy oracle."""
    q = (
        "select o_orderkey, sum(l_quantity) q from lineitem, orders "
        "where o_orderkey = l_orderkey "
        "group by o_orderkey having sum(l_quantity) > 250 "
        "order by q desc, o_orderkey limit 100"
    )
    sess.execute("set tidb_tpu_stream_rows = 0")
    full = sess.must_query(q).rows

    hits = []
    failpoint.enable("executor/stream-chunk", lambda: hits.append(1))
    try:
        # ~8 chunks at SF1
        sess.execute(f"set tidb_tpu_stream_rows = {max(int(SF * 750_000), 10_000)}")
        staged = sess.must_query(q).rows
    finally:
        failpoint.disable("executor/stream-chunk")
        sess.execute("set tidb_tpu_stream_rows = 0")
    assert len(hits) > 1, "expected the streamed path to chunk the scan"
    assert staged == full

    # numpy oracle
    li = _li_cols(sess, "l_orderkey", "l_quantity")
    ok = sess.catalog.table("tpch", "orders")
    okeys = np.concatenate([b.columns["o_orderkey"].data for b in ok.blocks()])
    sums = np.bincount(li["l_orderkey"], li["l_quantity"])
    present = np.zeros(max(len(sums), int(okeys.max()) + 1), dtype=bool)
    present[okeys] = True
    keys = np.nonzero((sums > 25000) & present[: len(sums)])[0]
    pairs = sorted(
        [(int(k), sums[k] / 100.0) for k in keys], key=lambda p: (-p[1], p[0])
    )[:100]
    got = [(int(a), float(b)) for a, b in staged]
    assert got == pairs


def test_q1_streamed_parity(sess):
    q = (
        "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
        "from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day "
        "group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"
    )
    sess.execute("set tidb_tpu_stream_rows = 0")
    full = sess.must_query(q).rows
    sess.execute(f"set tidb_tpu_stream_rows = {max(int(SF * 600_000), 10_000)}")
    staged = sess.must_query(q).rows
    sess.execute("set tidb_tpu_stream_rows = 0")
    assert staged == full


@pytest.mark.skipif(
    os.environ.get("RUN_SF10") != "1",
    reason="SF10 tier: RUN_SLOW=1 RUN_SF10=1 (needs ~10GB RAM, ~6 min)",
)
def test_q1_sf10_end_to_end():
    """SF10 readiness proof as a repeatable test (VERDICT r4 item #2):
    datagen, ANALYZE, capacity discovery and execution survive 60M
    rows; the result parity-checks against a numpy oracle on the
    grouped sums."""
    cat = Catalog()
    load_tpch(cat, sf=10.0, seed=1, tables=["lineitem"])
    s = Session(cat, db="tpch")
    s.execute(f"set tidb_mem_quota_query = {64 << 30}")
    s.execute("analyze table lineitem")
    rows = s.execute(
        "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
        "from lineitem where l_shipdate <= date '1998-09-02' "
        "group by l_returnflag, l_linestatus "
        "order by l_returnflag, l_linestatus"
    ).rows
    t = cat.table("tpch", "lineitem")
    sd = np.concatenate([b.columns["l_shipdate"].data for b in t.blocks()])
    qty = np.concatenate([b.columns["l_quantity"].data for b in t.blocks()])
    rf = np.concatenate([b.columns["l_returnflag"].data for b in t.blocks()])
    ls = np.concatenate([b.columns["l_linestatus"].data for b in t.blocks()])
    from tidb_tpu.dtypes import date_to_days

    m = sd <= date_to_days("1998-09-02")
    key = rf[m] * 16 + ls[m]
    want_cnt = {int(k): int(c) for k, c in zip(*np.unique(key, return_counts=True))}
    want_sum = {
        int(k): int(s_)
        for k, s_ in zip(
            np.unique(key),
            # l_quantity is DECIMAL(scale 2): raw storage is value*100
            np.bincount(key, weights=qty[m].astype(np.float64))[
                np.unique(key)
            ] / 100.0,
        )
    }
    got_cnt, got_sum = {}, {}
    rfd = t.dictionaries["l_returnflag"]
    lsd = t.dictionaries["l_linestatus"]
    for r in rows:
        k = int(np.searchsorted(rfd, r[0]) * 16 + np.searchsorted(lsd, r[1]))
        got_cnt[k] = int(r[3])
        got_sum[k] = int(round(float(r[2])))
    assert got_cnt == want_cnt
    assert got_sum == want_sum  # SUM parity on the 26-bit dense path
