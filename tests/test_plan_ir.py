"""Serializable plan IR + the frontend/engine RPC seam.

Reference: tipb.DAGRequest built by pkg/planner/core/plan_to_pb.go
shipped via kv.Request.Data (pkg/kv/kv.go:523); unistore's loopback
RPCClient.SendRequest (rpc.go:64) proves the whole stack runs against
the seam. Here: planner/ir.py serializes bound logical plans to JSON;
server/engine_rpc.py executes them across a socket.
"""

import pytest

from tidb_tpu.chunk import batch_to_block
from tidb_tpu.parser import parse
from tidb_tpu.planner import build_query
from tidb_tpu.planner.ir import (
    deserialize_plan,
    plan_to_ir,
    serialize_plan,
)
from tidb_tpu.server.engine_rpc import EngineClient, EngineServer
from tidb_tpu.session.session import Session

QUERIES = [
    "select a, b from t where a > 1 order by a",
    "select b, count(*), sum(dec) from t group by b order by b",
    "select t.a, u.v from t join u on t.a = u.a order by t.a",
    "select t.a from t left join u on t.a = u.a where u.v is null",
    "select a, row_number() over (partition by b order by a) from t order by a",
    "select a from t union select a from u order by a",
    "select a, case when a > 2 then 'big' else 'small' end from t order by a",
    "select year(d), count(distinct b) from t group by year(d)",
    "select a from t where b like 'x%'",
]


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create table t (a int, b varchar(8), d date, dec decimal(10,2))")
    s.execute(
        "insert into t values (1,'x','2024-01-01',1.50),"
        "(2,'y','2024-02-02',2.25),(3,'x','2024-03-03',0.75)"
    )
    s.execute("create table u (a int, v int)")
    s.execute("insert into u values (1,10),(3,30)")
    return s


def _rows(sess, plan):
    batch, dicts = sess.executor.run(plan)
    types = {c.internal: c.type for c in plan.schema}
    block = batch_to_block(batch, types, dicts)
    return sorted(
        repr(tuple(block.columns[c.internal].decode()[i] for c in plan.schema))
        for i in range(block.nrows)
    )


@pytest.mark.parametrize("q", QUERIES)
def test_roundtrip_executes_identically(sess, q):
    plan = build_query(parse(q)[0], sess.catalog, "test", sess._scalar_subquery)
    plan2 = deserialize_plan(serialize_plan(plan))
    assert _rows(sess, plan) == _rows(sess, plan2)


def test_ir_is_json_stable(sess):
    import json

    plan = build_query(
        parse(QUERIES[1])[0], sess.catalog, "test", sess._scalar_subquery
    )
    d = plan_to_ir(plan)
    assert json.loads(json.dumps(d)) == d


def test_staged_plans_refuse_serialization(sess):
    from tidb_tpu.planner import logical as L

    staged = L.Staged(L.Schema([]), batch=None, dicts=None, nonce=1)
    with pytest.raises(ValueError):
        plan_to_ir(staged)


class TestEngineRPC:
    """Frontend with no data executes plans on a remote engine."""

    @pytest.fixture()
    def engine(self, sess):
        srv = EngineServer(sess.catalog, port=0)
        srv.start_background()
        yield srv
        srv.shutdown()

    def test_remote_execution_matches_local(self, sess, engine):
        client = EngineClient("127.0.0.1", engine.port)
        try:
            for q in QUERIES[:5]:
                plan = build_query(
                    parse(q)[0], sess.catalog, "test", sess._scalar_subquery
                )
                cols, rows = client.execute_plan(plan)
                assert sorted(map(repr, rows)) == _rows(sess, plan), q
        finally:
            client.close()

    def test_engine_error_propagates(self, sess, engine):
        from tidb_tpu.planner import logical as L

        client = EngineClient("127.0.0.1", engine.port)
        try:
            bad = L.Scan(L.Schema([]), "test", "no_such_table", "x", [])
            with pytest.raises(RuntimeError):
                client.execute_plan(bad)
            # connection survives the error (reference: copr retry layer)
            plan = build_query(
                parse(QUERIES[0])[0], sess.catalog, "test",
                sess._scalar_subquery,
            )
            cols, rows = client.execute_plan(plan)
            assert len(rows) == 2
        finally:
            client.close()

    def test_frontend_without_data(self, sess, engine):
        """A second catalog holding only SCHEMAS plans the query; the
        engine executes it over the real data — the multi-host split."""
        from tidb_tpu.storage import Catalog

        front = Session(catalog=Catalog())
        front.execute(
            "create table t (a int, b varchar(8), d date, dec decimal(10,2))"
        )
        plan = build_query(
            parse("select a from t where a >= 2")[0],
            front.catalog, "test", front._scalar_subquery,
        )
        client = EngineClient("127.0.0.1", engine.port)
        try:
            cols, rows = client.execute_plan(plan)
            assert sorted(rows) == [(2,), (3,)]
        finally:
            client.close()


class TestRPCSafety:
    @pytest.fixture()
    def secured(self, sess):
        srv = EngineServer(sess.catalog, port=0, secret="s3cret")
        srv.start_background()
        yield srv
        srv.shutdown()

    def test_secret_required(self, sess, secured):
        with pytest.raises(PermissionError):
            EngineClient("127.0.0.1", secured.port, secret="wrong")
        client = EngineClient("127.0.0.1", secured.port, secret="s3cret")
        plan = build_query(
            parse(QUERIES[0])[0], sess.catalog, "test", sess._scalar_subquery
        )
        cols, rows = client.execute_plan(plan)
        assert len(rows) == 2
        client.close()

    def test_poisoned_connection_refuses_reuse(self, sess):
        srv = EngineServer(sess.catalog, port=0)
        srv.start_background()
        try:
            client = EngineClient("127.0.0.1", srv.port, timeout_s=1.0)
            client._dead = True  # simulate a timeout/desync poisoning
            plan = build_query(
                parse(QUERIES[0])[0], sess.catalog, "test",
                sess._scalar_subquery,
            )
            with pytest.raises(ConnectionError):
                client.execute_plan(plan)
        finally:
            srv.shutdown()

    def test_concurrent_clients(self, sess):
        import threading

        srv = EngineServer(sess.catalog, port=0)
        srv.start_background()
        errs = []

        def worker(q):
            try:
                c = EngineClient("127.0.0.1", srv.port)
                plan = build_query(
                    parse(q)[0], sess.catalog, "test", sess._scalar_subquery
                )
                for _ in range(3):
                    cols, rows = c.execute_plan(plan)
                    assert sorted(map(repr, rows)) == _rows(sess, plan)
                c.close()
            except Exception as e:
                errs.append(e)

        ths = [
            threading.Thread(target=worker, args=(q,)) for q in QUERIES[:4]
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        srv.shutdown()
        assert not errs


def test_secreted_client_works_with_open_server(sess):
    """Mismatched secret config must not brick the connection: a client
    carrying a secret interoperates with a server that requires none."""
    srv = EngineServer(sess.catalog, port=0)
    srv.start_background()
    try:
        client = EngineClient("127.0.0.1", srv.port, secret="anything")
        plan = build_query(
            parse(QUERIES[0])[0], sess.catalog, "test", sess._scalar_subquery
        )
        cols, rows = client.execute_plan(plan)
        assert len(rows) == 2
        client.close()
    finally:
        srv.shutdown()


class TestSchemaLease:
    """Schema-version validation on the RPC seam (reference: domain
    schema lease — 'Information schema is out of date')."""

    @pytest.fixture()
    def engine(self, sess):
        srv = EngineServer(sess.catalog, port=0)
        srv.start_background()
        yield srv
        srv.shutdown()

    def test_stale_schema_version_rejected(self, sess, engine):
        from tidb_tpu.server.engine_rpc import SchemaOutOfDateError

        client = EngineClient("127.0.0.1", engine.port)
        try:
            plan = build_query(
                parse("select count(*) from t")[0], sess.catalog, "test",
                sess._scalar_subquery,
            )
            v = sess.catalog.schema_version
            cols, rows = client.execute_plan(plan, schema_version=v)
            assert rows  # matching lease executes
            # DDL on the engine side moves the schema version: the old
            # lease must be rejected, the refreshed one accepted
            sess.execute("create table lease_probe (x int)")
            with pytest.raises(SchemaOutOfDateError, match="out of date"):
                client.execute_plan(plan, schema_version=v)
            cols, rows = client.execute_plan(
                plan, schema_version=sess.catalog.schema_version
            )
            assert rows
            # versionless requests keep working (lease check is opt-in)
            client.execute_plan(plan)
        finally:
            client.close()
