"""INSERT...SELECT, CREATE TABLE AS SELECT, INTERSECT/EXCEPT, REPLACE.

Reference: pkg/executor/insert.go (+SelectionExec source), replace.go,
and the MySQL 8.0.31 set operations (parser setOpr grammar). Set ops
ride the group-by kernel, so NULL rows compare equal (SQL set
semantics) without a special join path.
"""

import pytest

from tidb_tpu.session.session import Session


@pytest.fixture()
def s():
    s = Session()
    s.execute("create table a (x int, y varchar(4))")
    s.execute(
        "insert into a values (1,'p'),(2,'q'),(null,'n'),(11,'p'),(12,'q')"
    )
    s.execute("create table c (x int)")
    s.execute("insert into c values (1),(11),(null)")
    return s


class TestInsertSelect:
    def test_basic(self, s):
        s.execute("insert into a select x + 100, upper(y) from a where x < 10")
        assert s.execute(
            "select x, y from a where x > 100 order by x"
        ).rows == [(101, "P"), (102, "Q")]

    def test_column_subset(self, s):
        s.execute("insert into a (x) select x + 200 from c where x is not null")
        assert s.execute(
            "select x, y from a where x > 200 order by x"
        ).rows == [(201, None), (211, None)]

    def test_arity_mismatch(self, s):
        with pytest.raises(ValueError):
            s.execute("insert into a select x from c")

    def test_autoinc_filled(self):
        s = Session()
        s.execute("create table t (id int auto_increment, v int)")
        s.execute("create table src (v int)")
        s.execute("insert into src values (7),(8)")
        s.execute("insert into t (v) select v from src")
        assert s.execute("select id, v from t order by id").rows == [
            (1, 7), (2, 8),
        ]


class TestCreateTableAsSelect:
    def test_schema_derived(self, s):
        s.execute("create table b as select x, upper(y) as yy from a where x > 10")
        assert s.execute("select * from b order by x").rows == [
            (11, "P"), (12, "Q"),
        ]
        t = s.catalog.table("test", "b")
        assert t.schema.names == ["x", "yy"]

    def test_exists_guard(self, s):
        s.execute("create table b as select x from a")
        with pytest.raises(ValueError):
            s.execute("create table b as select x from a")
        s.execute("create table if not exists b as select y from a")  # no-op
        assert s.catalog.table("test", "b").schema.names == ["x"]


class TestSetOps:
    def test_intersect_with_nulls(self, s):
        # NULL = NULL under set semantics (both sides contain a NULL row)
        assert s.execute(
            "select x from a intersect select x from c order by x"
        ).rows == [(None,), (1,), (11,)]

    def test_except(self, s):
        assert s.execute(
            "select x from a except select x from c order by x"
        ).rows == [(2,), (12,)]

    def test_chained_and_tail(self, s):
        assert s.execute(
            "select x from a except select x from c except select 2 order by x"
        ).rows == [(12,)]
        assert s.execute(
            "select x from a intersect select x from c order by x desc limit 1"
        ).rows == [(11,)]

    def test_multi_column(self, s):
        assert s.execute(
            "select x, y from a intersect select x, y from a where x > 1 "
            "order by x"
        ).rows == [(2, "q"), (11, "p"), (12, "q")]

    def test_distinct_semantics(self, s):
        s.execute("insert into a values (1,'p'),(1,'p')")  # duplicates
        assert s.execute(
            "select x from a intersect select x from c order by x"
        ).rows == [(None,), (1,), (11,)]

    def test_all_rejected(self, s):
        with pytest.raises(Exception):
            s.execute("select x from a intersect all select x from c")

    def test_mesh_parity(self):
        sm, s1 = Session(mesh_devices=8), Session()
        for ss in (sm, s1):
            ss.execute("create table a (x int)")
            ss.execute("create table b (x int)")
            ss.execute(
                "insert into a values "
                + ",".join(f"({i % 40})" for i in range(400))
            )
            ss.execute(
                "insert into b values "
                + ",".join(f"({i % 25})" for i in range(100))
            )
        for q in [
            "select x from a intersect select x from b order by x",
            "select x from a except select x from b order by x",
        ]:
            assert sm.execute(q).rows == s1.execute(q).rows, q


class TestReplace:
    def test_replace_by_pk(self, s):
        s.execute("create table r (k int primary key, v varchar(4))")
        s.execute("insert into r values (1,'a'),(2,'b')")
        s.execute("replace into r values (1,'z'),(3,'c')")
        assert s.execute("select * from r order by k").rows == [
            (1, "z"), (2, "b"), (3, "c"),
        ]

    def test_replace_by_unique_string_key(self, s):
        s.execute("create table u2 (k varchar(4), v int)")
        s.execute("create unique index uk on u2 (k)")
        s.execute("insert into u2 values ('a',1)")
        s.execute("replace into u2 values ('a',9),('b',2)")
        assert s.execute("select * from u2 order by k").rows == [
            ("a", 9), ("b", 2),
        ]

    def test_replace_without_keys_is_plain_insert(self, s):
        s.execute("create table nk (v int)")
        s.execute("insert into nk values (1)")
        s.execute("replace into nk values (1)")
        assert s.execute("select count(*) from nk").rows == [(2,)]


class TestReviewRegressions:
    def test_ctas_requires_select_privilege(self):
        s = Session()
        s.execute("create table a (x int)")
        s.execute("insert into a values (1)")
        s.execute("create user bob")
        s.execute("grant create on test.* to bob")
        bob = Session(catalog=s.catalog, user="bob")
        with pytest.raises(PermissionError):
            bob.execute("create table leak as select x from a")

    def test_tableless_ctas(self):
        s = Session()
        s.execute("create table t1 as select 1 as a, 'x' as b")
        assert s.execute("select * from t1").rows == [(1, "x")]
        assert s.catalog.table("test", "t1").schema.names == ["a", "b"]

    def test_replace_composite_pk_replaces(self):
        # formerly NotImplementedError; composite conflict keys are now
        # first-class across REPLACE/IGNORE/ON DUP (round-3)
        s = Session()
        s.execute("create table cp (a int, b int, v int, primary key (a, b))")
        s.execute("insert into cp values (1,1,1), (1,2,2)")
        s.execute("replace into cp values (1,1,9)")
        assert s.execute("select a,b,v from cp order by a,b").rows == [
            (1, 1, 9), (1, 2, 2)
        ]

    def test_replace_intra_statement_keeps_last(self):
        s = Session()
        s.execute("create table r (k int primary key, v varchar(4))")
        s.execute("replace into r values (1,'a'),(1,'b')")
        assert s.execute("select * from r").rows == [(1, "b")]


class TestCompatSurface:
    """Round-5 compat batch: CREATE TABLE LIKE, ALTER TABLE ADD
    INDEX/KEY/UNIQUE, INSERT ... SET, SHOW TABLE STATUS,
    information_schema.partitions."""

    @pytest.fixture()
    def s(self):
        sess = Session()
        sess.execute("create database cs")
        sess.execute("use cs")
        return sess

    def test_create_table_like(self, s):
        s.execute("create table parent (pk int primary key)")
        s.execute(
            "create table src (id int primary key auto_increment, "
            "v varchar(8) not null, z int default 7, "
            "constraint fz foreign key (z) references parent (pk))"
        )
        s.execute("create index iv on src (v)")
        s.execute("insert into parent values (7)")
        s.execute("insert into src (v) values ('a')")
        s.execute("create table dst like src")
        ddl = s.execute("show create table dst").rows[0][1].lower()
        assert "auto_increment" in ddl and "not null" in ddl
        assert "default 7" in ddl and "index iv" in ddl
        assert "foreign key" not in ddl  # MySQL: LIKE drops FKs
        assert s.execute("select count(*) from dst").rows == [(0,)]
        s.execute("insert into dst (v) values ('x')")
        assert s.execute("select id, z from dst").rows == [(1, 7)]
        with pytest.raises(Exception, match="[Nn]ull|NULL"):
            s.execute("insert into dst (v) values (NULL)")

    def test_create_table_like_partitioned(self, s):
        s.execute(
            "create table ps (k int, d int) partition by list (d) ("
            "partition a values in (1), partition b values in (2, null))"
        )
        s.execute("create table pd like ps")
        s.execute("insert into pd values (1, 2), (2, NULL)")
        r = s.execute(
            "select partition_name, table_rows from "
            "information_schema.partitions where table_name = 'pd' "
            "order by partition_ordinal_position"
        ).rows
        assert r == [("a", 0), ("b", 2)]

    def test_alter_add_index_forms(self, s):
        s.execute("create table t (a int, b int, c int)")
        s.execute("insert into t values (1, 2, 3), (1, 5, 6)")
        s.execute("alter table t add index ia (a)")
        s.execute("alter table t add key kb (b)")
        s.execute("alter table t add unique uc (c)")
        s.execute("alter table t add unique index ubc (b, c)")
        idx = {
            v for r in s.execute("show index from t").rows for v in r
            if isinstance(v, str)
        }
        assert {"ia", "kb", "uc", "ubc"} <= idx
        with pytest.raises(Exception, match="[Dd]uplicate"):
            s.execute("alter table t add unique ua (a)")

    def test_insert_set(self, s):
        s.execute("create table t (a int, b varchar(4) default 'dd')")
        s.execute("insert into t set a = 5")
        s.execute("insert ignore into t set a = 6, b = 'x'")
        assert s.execute("select a, b from t order by a").rows == [
            (5, "dd"), (6, "x")
        ]

    def test_show_table_status(self, s):
        s.execute("create table t (a int)")
        s.execute("insert into t values (1), (2)")
        s.execute("create view vw as select a from t")
        rows = s.execute("show table status").rows
        names = {r[0]: r for r in rows}
        assert names["t"][4] == 2  # Rows
        assert names["vw"][9] == "VIEW"  # Comment
        only = s.execute("show table status like 't'").rows
        assert len(only) == 1 and only[0][0] == "t"

    def test_review_fixes(self, s):
        s.execute("create table u (k int primary key, v int)")
        s.execute("insert into u set k = 1, v = 2")
        # SET form composes with ON DUPLICATE (MySQL)
        s.execute(
            "insert into u set k = 1, v = 9 on duplicate key update v = 3"
        )
        assert s.execute("select v from u").rows == [(3,)]
        # anonymous index names auto-generate
        s.execute("alter table u add unique (v)")
        s.execute("alter table u add index (k, v)")
        with pytest.raises(Exception, match="[Dd]uplicate"):
            s.execute("insert into u values (2, 3)")
        # SHOW TABLE STATUS: uppercase + ci LIKE
        rows = s.execute("SHOW TABLE STATUS LIKE 'U'").rows
        assert len(rows) == 1 and rows[0][0] == "u"
        # backslash-bearing string default survives the DDL round-trip
        s.execute(r"create table bs (a int, b varchar(8) default 'a\\b')")
        s.execute("create table bs2 like bs")
        s.execute("insert into bs2 (a) values (1)")
        assert s.execute("select b from bs2").rows == [("a\\b",)]

    def test_connector_statements(self, s, tmp_path):
        s.execute("create table t (a int primary key, b varchar(8))")
        s.execute("insert into t values (1, 'x'), (2, 'y')")
        s.execute("set names utf8mb4 collate utf8mb4_general_ci")
        assert s.execute(
            "select @@character_set_client"
        ).rows == [("utf8mb4",)]
        s.execute("set session transaction isolation level read committed")
        assert s.execute(
            "select @@transaction_isolation"
        ).rows == [("READ-COMMITTED",)]
        for noop in (
            "flush privileges", "flush tables", "lock tables t read",
            "unlock tables",
        ):
            assert s.execute(noop).rows == []
        s.execute("do 1 + 1, sleep(0)")
        assert s.execute("select a from t order by a for share").rows == [
            (1,), (2,)
        ]
        assert s.execute("show open tables").rows == []
        st = dict(s.execute("show status like 'Threads%'").rows)
        assert st["Threads_connected"] == "1"
        assert len(s.execute("show full processlist").rows) >= 1
        # DESC <select> = EXPLAIN
        plan = "\n".join(
            r[0] for r in s.execute("desc select a from t").rows
        )
        assert "Scan" in plan
        # CHECKSUM TABLE rides the ADMIN CHECKSUM machinery
        ck = s.execute("checksum table t").rows
        assert len(ck) == 1 and ck[0][1]
        opt = s.execute("optimize table t").rows
        assert opt[-1][3] == "OK"

    def test_into_outfile_and_serial(self, s, tmp_path):
        s.execute("create table t (a serial, b varchar(4))")
        s.execute("insert into t (b) values ('x'), (NULL)")
        out = str(tmp_path / "o.tsv")
        r = s.execute(f"select a, b from t order by a into outfile '{out}'")
        assert r.affected == 2
        assert open(out).read() == "1\tx\n2\t\\N\n"
        with pytest.raises(Exception, match="exists"):
            s.execute(f"select a from t into outfile '{out}'")
        # SERIAL implies AUTO_INCREMENT: NULL generates the next id
        s.execute("insert into t values (NULL, 'q')")
        assert s.execute("select max(a) from t").rows == [(3,)]

    def test_show_warnings_lifecycle(self, s):
        s.execute("create table w (k int primary key)")
        s.execute("insert ignore into w values (NULL)")
        assert s.execute("show warnings").rows == [
            ("Warning", 1048, "Column 'k' cannot be null")
        ]
        # diagnostics survive repeated SHOW WARNINGS, clear on the next
        # ordinary statement
        assert len(s.execute("show warnings").rows) == 1
        s.execute("select 1")
        assert s.execute("show warnings").rows == []

    def test_review_fixes_2(self, s, tmp_path):
        s.execute("create table t (a int)")
        s.execute("insert into t values (1), (2)")
        # UNION writes the outfile too
        out = str(tmp_path / "u.tsv")
        r = s.execute(
            f"select a from t union select 9 into outfile '{out}'"
        )
        assert r.affected == 3 and len(open(out).read().splitlines()) == 3
        # SET NAMES resets collation_connection to the charset default
        s.execute("set names utf8mb4 collate utf8mb4_general_ci")
        s.execute("set names latin1")
        cc = s.execute("select @@collation_connection").rows[0][0]
        assert "latin1" in cc or cc != "utf8mb4_general_ci"
        with pytest.raises(Exception, match="[Uu]nknown character set"):
            s.execute("set names klingon")
        with pytest.raises(Exception, match="ONLY or WRITE"):
            s.execute("set session transaction read foo")
        # outfile existence check fires BEFORE running the query
        with pytest.raises(Exception, match="exists"):
            s.execute(f"select a from t into outfile '{out}'")

    def test_multi_spec_alter(self, s):
        s.execute("create table t (a int, b int)")
        s.execute("insert into t values (1, 2)")
        s.execute(
            "alter table t add column c int default 9, add index ic (c), "
            "alter column b set default 5, drop index ic, "
            "add index ic2 (a, c)"
        )
        s.execute("insert into t (a) values (3)")
        assert s.execute("select a, b, c from t order by a").rows == [
            (1, 2, 9), (3, 5, 9),
        ]
        # whole statement rolls back when a later spec fails
        with pytest.raises(Exception):
            s.execute(
                "alter table t add column d int, add column d int"
            )
        assert "d" not in [
            r[0] for r in s.execute("show columns from t").rows
        ]
        s.execute("alter table t alter column b drop default")
        ddl = s.execute("show create table t").rows[0][1].lower()
        assert "b` bigint" in ddl and "default 5" not in ddl

    def test_alter_drop_index(self, s):
        s.execute("create table t (a int)")
        s.execute("alter table t add index ia (a)")
        s.execute("alter table t drop index ia")
        assert all(
            "ia" not in r for r in s.execute("show index from t").rows
        )

    def test_multi_alter_guards(self, s):
        s.execute("create table t (a int)")
        with pytest.raises(Exception, match="combined"):
            s.execute("alter table t rename to t9, add column b int")
        with pytest.raises(Exception, match="Invalid default"):
            s.execute("alter table t add column c int, "
                      "alter column c set default 'abc'")
        # negative defaults parse in every DEFAULT position
        s.execute("alter table t add column d int default -1")
        s.execute("insert into t (a) values (1)")
        assert s.execute("select d from t").rows == [(-1,)]

    def test_add_column_invalid_default_atomic(self, s):
        s.execute("create table t (a int)")
        s.execute("insert into t values (1)")
        with pytest.raises(Exception, match="Invalid default"):
            s.execute("alter table t add column c int default 'abc'")
        assert [r[0] for r in s.execute("show columns from t").rows] == [
            "a"
        ]

    def test_drop_partition_then_spec_reports_combination(self, s):
        s.execute(
            "create table pt (a int, d int) partition by range (d) ("
            "partition p0 values less than (10), "
            "partition p1 values less than (20))"
        )
        with pytest.raises(Exception, match="combined"):
            s.execute("alter table pt drop partition p0, add column b int")
        assert s.execute(
            "select count(*) from information_schema.partitions "
            "where table_name = 'pt'"
        ).rows == [(2,)]

    def test_check_table_and_aliases(self, s):
        s.execute("create table t (a int primary key, v int)")
        s.execute("create index iv on t (v)")
        s.execute("insert into t values (1, 5)")
        assert s.execute("check table t").rows == [
            ("cs.t", "check", "status", "OK")
        ]
        assert s.execute("show indexes from t").rows == s.execute(
            "show index from t"
        ).rows
        assert s.execute("show keys from t").rows
        assert "CREATE DATABASE `cs`" in s.execute(
            "show create database cs"
        ).rows[0][1]

    def test_invisible_index(self, s):
        s.execute("create table t (a int primary key, v int)")
        s.execute("create index iv on t (v)")
        s.execute("insert into t values " + ", ".join(
            f"({i}, {i % 50})" for i in range(1, 2001)))
        plan = lambda: "\n".join(
            r[0] for r in s.execute(
                "explain select a from t where v = 7"
            ).rows
        )
        assert "Index" in plan()
        s.execute("alter table t alter index iv invisible")
        assert "Index" not in plan()
        # still maintained: results identical, and visibility restores
        assert len(s.execute("select a from t where v = 7").rows) == 40
        s.execute("alter table t alter index iv visible")
        assert "Index" in plan()

    def test_read_only_transaction(self, s):
        s.execute("create table t (a int primary key)")
        s.execute("insert into t values (1)")
        s.execute("start transaction read only, with consistent snapshot")
        assert s.execute("select count(*) from t").rows == [(1,)]
        with pytest.raises(Exception, match="READ ONLY"):
            s.execute("insert into t values (2)")
        s.execute("commit")
        s.execute("insert into t values (2)")
        assert s.execute("select count(*) from t").rows == [(2,)]
        # plain START TRANSACTION is read-write
        s.execute("start transaction")
        s.execute("insert into t values (3)")
        s.execute("commit")

    def test_review_fixes_3(self, s, tmp_path):
        s.execute("create table u (a int primary key, v int)")
        s.execute("create index iv on u (v)")
        s.execute("insert into u values " + ", ".join(
            f"({i}, {i % 40})" for i in range(1, 2001)))
        # drop clears visibility state; a recreated index is usable
        s.execute("alter table u alter index iv invisible")
        s.execute("drop index iv on u")
        s.execute("create index iv on u (v)")
        assert "Index" in "\n".join(
            r[0] for r in s.execute(
                "explain select a from u where v = 7"
            ).rows
        )
        # invisibility survives BACKUP/RESTORE
        s.execute("alter table u alter index iv invisible")
        s.execute(f"backup database cs to '{tmp_path}/b'")
        from tidb_tpu.session import Session as S2
        from tidb_tpu.storage import Catalog as C2

        c2 = C2()
        s2 = S2(c2, db="cs")
        s2.execute(f"restore database cs from '{tmp_path}/b'")
        assert "iv" in c2.table("cs", "u").invisible_indexes
        # missing table is an Error row, never Corrupt
        rows = s.execute("check table nope").rows
        assert rows == [
            ("cs.nope", "check", "Error", "Table 'cs.nope' doesn't exist")
        ]
        # RO txn blocks locking reads too
        s.execute("start transaction read only")
        with pytest.raises(Exception, match="READ ONLY"):
            s.execute("select a from u where a = 1 for update")
        s.execute("rollback")
