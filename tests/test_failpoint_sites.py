"""Tier-1 gate for scripts/check_failpoints.py: the declared failpoint
site set (utils/failpoint.py SITES) stays in lockstep with the actual
inject() call sites, and enable() rejects names that would arm nothing."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "check_failpoints.py")


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, LINT, REPO], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        f"failpoint-site violations:\n{proc.stdout}{proc.stderr}"
    )


def test_lint_catches_violations(tmp_path):
    util = tmp_path / "tidb_tpu" / "utils"
    util.mkdir(parents=True)
    (util / "failpoint.py").write_text(
        'SITES = frozenset({"good/site", "dead/site"})\n'
    )
    (tmp_path / "tidb_tpu" / "engine.py").write_text(
        'from tidb_tpu.utils.failpoint import inject\n'
        'inject("good/site")\n'
        'inject("undeclared/site")\n'   # rule 1
    )
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_x.py").write_text(
        'failpoint.enable("good/site", True)\n'
        'failpoint.enable("typod/site", True)\n'  # rule 3
    )
    proc = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "undeclared/site" in proc.stdout          # injected, undeclared
    assert "dead/site" in proc.stdout                # declared, never injected
    assert "typod/site" in proc.stdout               # enabled, undeclared
    assert "3 failpoint violation(s)" in proc.stdout  # and nothing else


def test_enable_rejects_unknown_site():
    from tidb_tpu.utils import failpoint

    with pytest.raises(ValueError, match="unknown failpoint site"):
        failpoint.enable("definitely/not-a-site", True)


def test_declare_admits_test_local_site():
    from tidb_tpu.utils import failpoint

    failpoint.declare("testonly/site")
    try:
        failpoint.enable("testonly/site", 7)
        assert failpoint.inject("testonly/site") == 7
    finally:
        failpoint.disable("testonly/site")
