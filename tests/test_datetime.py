"""DATETIME / TIME types and temporal builtins.

Reference: pkg/types/time.go (coreTime, AddDate), pkg/types Duration, and
the builtin time family (pkg/expression/builtin_time_vec.go). Device
layout: DATETIME = int64 micros since epoch, TIME = signed int64 micros;
comparisons/sorts/interval arithmetic are plain int64 ops, calendar math
uses the branchless civil-date kernels.
"""

import pytest

from tidb_tpu.dtypes import Kind, micros_to_datetime, micros_to_time
from tidb_tpu.session.session import Session


def _fmt(r):
    rows = []
    for row in r.rows:
        out = []
        for v, t in zip(row, r.types or [None] * len(row)):
            if t is not None and t.kind == Kind.DATETIME and isinstance(v, int):
                out.append(micros_to_datetime(v))
            elif t is not None and t.kind == Kind.TIME and isinstance(v, int):
                out.append(micros_to_time(v))
            else:
                out.append(v)
        rows.append(tuple(out))
    return rows


@pytest.fixture()
def s():
    s = Session()
    s.execute("create table e (id int, ts datetime, d date, t time)")
    s.execute(
        "insert into e values "
        "(1,'2024-02-29 13:45:30','2024-02-29','13:45:30'),"
        "(2,'2024-03-01 00:00:00','2024-03-01','00:00:01'),"
        "(3,'1969-12-31 23:59:59','1969-12-31','23:59:59'),"
        "(4,null,null,null)"
    )
    return s


def test_extract_parts(s):
    r = s.execute(
        "select id, year(ts), month(ts), day(ts), hour(ts), minute(ts), "
        "second(ts) from e where id=1"
    )
    assert r.rows == [(1, 2024, 2, 29, 13, 45, 30)]


def test_pre_epoch_time_parts(s):
    # negative micros: floor-div/mod keep calendar semantics
    r = s.execute("select year(ts), hour(ts), second(ts) from e where id=3")
    assert r.rows == [(1969, 23, 59)]


def test_string_literal_coercion(s):
    assert s.execute(
        "select id from e where ts >= '2024-03-01' order by id"
    ).rows == [(2,)]
    assert s.execute(
        "select id from e where ts > '2024-02-29 13:00:00' order by id"
    ).rows == [(1,), (2,)]


def test_date_vs_datetime_comparison(s):
    # DATE promotes to midnight: true whenever ts has a time-of-day
    assert s.execute("select id from e where d < ts order by id").rows == [
        (1,),
        (3,),
    ]
    assert s.execute(
        "select id, date(ts) = d from e where id in (1,2) order by id"
    ).rows == [(1, True), (2, True)]


def test_time_column_parts(s):
    assert s.execute(
        "select id, hour(t), minute(t), second(t) from e where id=3"
    ).rows == [(3, 23, 59, 59)]


def test_interval_arithmetic(s):
    assert _fmt(s.execute("select ts + interval 1 day from e where id=1")) == [
        ("2024-03-01 13:45:30",)
    ]
    assert _fmt(s.execute("select ts + interval 2 hour from e where id=3")) == [
        ("1970-01-01 01:59:59",)
    ]
    assert _fmt(
        s.execute("select date_add(ts, interval 1 month) from e where id=1")
    ) == [("2024-03-29 13:45:30",)]
    assert _fmt(
        s.execute("select date_sub(ts, interval 90 minute) from e where id=2")
    ) == [("2024-02-29 22:30:00",)]


def test_casts(s):
    assert _fmt(
        s.execute("select cast('2021-05-06 07:08:09' as datetime) from e where id=1")
    ) == [("2021-05-06 07:08:09",)]
    assert _fmt(s.execute("select cast(d as datetime) from e where id=2")) == [
        ("2024-03-01 00:00:00",)
    ]
    assert s.execute(
        "select cast(ts as date) = d from e where id=1"
    ).rows == [(True,)]


def test_aggregates_and_order(s):
    assert _fmt(s.execute("select max(ts), min(ts) from e")) == [
        ("2024-03-01 00:00:00", "1969-12-31 23:59:59")
    ]
    assert s.execute(
        "select id from e where ts is not null order by ts desc limit 1"
    ).rows == [(2,)]
    assert s.execute("select count(*) from e where ts is null").rows == [(1,)]


def test_group_by_datetime(s):
    s.execute("insert into e values (5,'2024-02-29 13:45:30','2024-02-29','13:45:30')")
    r = s.execute(
        "select ts, count(*) from e where ts is not null "
        "group by ts order by ts limit 1"
    )
    assert r.rows[0][1] == 1  # 1969 row is unique


def test_datediff_mixed(s):
    assert s.execute("select datediff(ts, d) from e where id=1").rows == [(0,)]


def test_now_is_datetime():
    s = Session()
    r = s.execute("select now() >= '2026-01-01 00:00:00'")
    assert r.rows == [(True,)]


def test_mesh_parity():
    q = (
        "select d, count(*), max(ts) from e where ts is not null "
        "group by d order by d"
    )
    rows = []
    for mesh in (None, 8):
        s = Session(mesh_devices=mesh)
        s.execute("create table e (id int, ts datetime, d date)")
        s.execute(
            "insert into e values "
            "(1,'2024-02-29 13:45:30','2024-02-29'),"
            "(2,'2024-02-29 15:00:00','2024-02-29'),"
            "(3,'2024-03-01 00:00:00','2024-03-01')"
        )
        rows.append(s.execute(q).rows)
    assert rows[0] == rows[1]
