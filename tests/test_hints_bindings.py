"""Optimizer hints (/*+ ... */) and SQL plan bindings.

Reference: pkg/parser/hintparser.y (hint grammar), planner hint
handling (BROADCAST_JOIN et al), and pkg/bindinfo (digest-matched hint
sets applied to unhinted statements).
"""

import pytest

from tidb_tpu.parser import parse
from tidb_tpu.planner import build_query
from tidb_tpu.planner import logical as L
from tidb_tpu.session.session import Session


@pytest.fixture()
def s():
    s = Session()
    s.execute("create table big (k int, v int)")
    s.execute("create table small (k int, name varchar(8))")
    s.execute(
        "insert into big values " + ",".join(f"({i % 50},{i})" for i in range(5000))
    )
    s.execute(
        "insert into small values " + ",".join(f"({i},'n{i}')" for i in range(50))
    )
    s.execute("analyze table big")
    s.execute("analyze table small")
    return s


def _bcasts(s, sql):
    plan = build_query(parse(sql)[0], s.catalog, "test", s._scalar_subquery)
    out = []

    def walk(p):
        if isinstance(p, L.JoinPlan):
            out.append(p.broadcast)
        for a in ("child", "left", "right"):
            c = getattr(p, a, None)
            if c is not None:
                walk(c)
        for c in getattr(p, "children", []) or []:
            walk(c)

    walk(plan)
    return out


JOIN = "select * from big join small on big.k = small.k"


class TestHints:
    def test_cost_based_default(self, s):
        assert _bcasts(s, JOIN) == ["right"]  # small side replicates

    def test_no_broadcast_hint(self, s):
        assert _bcasts(s, f"select /*+ NO_BROADCAST_JOIN() */ {JOIN[7:]}") == [None]

    def test_force_side(self, s):
        assert _bcasts(s, f"select /*+ BROADCAST_JOIN(big) */ {JOIN[7:]}") == [
            "left"
        ]

    def test_unknown_hint_ignored(self, s):
        assert _bcasts(s, f"select /*+ NOT_A_HINT(x) */ {JOIN[7:]}") == ["right"]

    def test_hinted_results_identical(self, s):
        plain = s.execute(JOIN + " order by big.v limit 5").rows
        hinted = s.execute(
            f"select /*+ NO_BROADCAST_JOIN() */ {JOIN[7:]} order by big.v limit 5"
        ).rows
        assert plain == hinted

    def test_max_execution_time_hint(self, s):
        import time

        from tidb_tpu.utils import failpoint
        from tidb_tpu.utils.sqlkiller import QueryKilled

        # deterministic: slow the scan past the 1ms deadline; the next
        # executor kill-safepoint must abort the statement
        failpoint.enable("storage/scan", lambda: time.sleep(0.05))
        try:
            with pytest.raises(QueryKilled):
                s.execute(
                    "select /*+ MAX_EXECUTION_TIME(1) */ count(*), sum(v) "
                    "from big where v > 1"
                )
        finally:
            failpoint.disable("storage/scan")
        s.execute("select count(*) from big")  # deadline was per-statement


class TestBindings:
    def test_binding_injects_hints(self, s):
        s.execute(
            f"create binding for {JOIN} using "
            f"select /*+ NO_BROADCAST_JOIN() */ {JOIN[7:]}"
        )
        assert len(s.execute("show bindings").rows) == 1
        # matched statement executes correctly with injected hints
        r = s.execute(JOIN + " order by big.v limit 3")
        assert len(r.rows) == 3
        # literal-normalized digest: different constants still match
        s.execute(JOIN + " order by big.v limit 5")
        s.execute(f"drop binding for {JOIN}")
        assert s.execute("show bindings").rows == []

    def test_binding_requires_hints(self, s):
        with pytest.raises(ValueError):
            s.execute(f"create binding for {JOIN} using {JOIN}")

    def test_binding_requires_super(self, s):
        s.execute("create user pleb")
        pleb = Session(catalog=s.catalog, user="pleb")
        with pytest.raises(PermissionError):
            pleb.execute(
                f"create binding for {JOIN} using "
                f"select /*+ NO_BROADCAST_JOIN() */ {JOIN[7:]}"
            )

    def test_mesh_executes_hinted_plan(self):
        sm = Session(mesh_devices=8)
        sm.execute("create table a (k int, v int)")
        sm.execute("create table b (k int, n int)")
        sm.execute(
            "insert into a values " + ",".join(f"({i % 9},{i})" for i in range(300))
        )
        sm.execute("insert into b values " + ",".join(f"({i},{i})" for i in range(9)))
        plain = sm.execute(
            "select a.k, sum(a.v), max(b.n) from a join b on a.k = b.k "
            "group by a.k order by a.k"
        ).rows
        hinted = sm.execute(
            "select /*+ NO_BROADCAST_JOIN() */ a.k, sum(a.v), max(b.n) "
            "from a join b on a.k = b.k group by a.k order by a.k"
        ).rows
        assert plain == hinted
