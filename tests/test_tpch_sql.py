"""TPC-H benchmark ladder (BASELINE.json configs) through the SQL surface,
golden-checked against plain-Python computation over the decoded data."""

import math
from collections import defaultdict

import pytest

from tidb_tpu.bench import load_tpch
from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture(scope="module")
def sess():
    cat = Catalog()
    load_tpch(cat, sf=0.002, seed=11)
    s = Session(cat, db="tpch")
    return s


def decode_table(sess, name):
    t = sess.catalog.table("tpch", name)
    rows = []
    blocks = t.blocks()
    cols = t.schema.names
    data = {c: [] for c in cols}
    for b in blocks:
        for c in cols:
            data[c].extend(b.columns[c].decode().tolist())
    n = sum(b.nrows for b in blocks)
    return data, n


def days(s):
    from tidb_tpu.dtypes import date_to_days

    return int(date_to_days(s))


def test_q1(sess):
    r = sess.must_query(
        "select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, "
        "sum(l_extendedprice) as sum_base_price, "
        "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
        "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
        "avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price, "
        "avg(l_discount) as avg_disc, count(*) as count_order "
        "from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day "
        "group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"
    )
    li, n = decode_table(sess, "lineitem")
    cutoff = days("1998-12-01") - 90  # DATE decodes to int days
    agg = defaultdict(lambda: [0.0, 0.0, 0.0, 0.0, 0])
    for i in range(n):
        sd = li["l_shipdate"][i]
        if sd > cutoff:
            continue
        key = (li["l_returnflag"][i], li["l_linestatus"][i])
        a = agg[key]
        q, p, d, t = (
            li["l_quantity"][i],
            li["l_extendedprice"][i],
            li["l_discount"][i],
            li["l_tax"][i],
        )
        a[0] += q
        a[1] += p
        a[2] += p * (1 - d)
        a[3] += p * (1 - d) * (1 + t)
        a[4] += 1
    expected = []
    for key in sorted(agg):
        a = agg[key]
        expected.append(
            (key[0], key[1], round(a[0], 2), round(a[1], 2), round(a[2], 4),
             round(a[3], 6), a[0] / a[4], a[1] / a[4], None, a[4])
        )
    assert len(r.rows) == len(expected)
    for got, exp in zip(r.rows, expected):
        assert got[0] == exp[0] and got[1] == exp[1]
        assert math.isclose(got[2], exp[2], abs_tol=0.01)
        assert math.isclose(got[3], exp[3], abs_tol=0.01)
        assert math.isclose(got[4], exp[4], rel_tol=1e-12, abs_tol=1e-4)
        assert math.isclose(got[5], exp[5], rel_tol=1e-12, abs_tol=1e-6)
        assert math.isclose(got[6], exp[6], rel_tol=1e-9)
        assert math.isclose(got[7], exp[7], rel_tol=1e-9)
        assert got[9] == exp[9]


def test_q6(sess):
    r = sess.must_query(
        "select sum(l_extendedprice * l_discount) as revenue from lineitem "
        "where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01' "
        "and l_discount between 0.05 and 0.07 and l_quantity < 24"
    )
    li, n = decode_table(sess, "lineitem")
    d0, d1 = days("1994-01-01"), days("1995-01-01")
    exp = 0.0
    for i in range(n):
        if (
            d0 <= li["l_shipdate"][i] < d1
            and 0.05 <= li["l_discount"][i] <= 0.07
            and li["l_quantity"][i] < 24
        ):
            exp += li["l_extendedprice"][i] * li["l_discount"][i]
    assert math.isclose(r.rows[0][0], round(exp, 4), abs_tol=0.01)


def test_q3(sess):
    r = sess.must_query(
        "select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue, "
        "o_orderdate, o_shippriority "
        "from customer, orders, lineitem "
        "where c_mktsegment = 'BUILDING' and c_custkey = o_custkey "
        "and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15' "
        "and l_shipdate > date '1995-03-15' "
        "group by l_orderkey, o_orderdate, o_shippriority "
        "order by revenue desc, o_orderdate limit 10"
    )
    cust, nc = decode_table(sess, "customer")
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    building = {
        cust["c_custkey"][i] for i in range(nc) if cust["c_mktsegment"][i] == "BUILDING"
    }
    cut = days("1995-03-15")
    okeys = {}
    for i in range(no):
        if orders["o_custkey"][i] in building and orders["o_orderdate"][i] < cut:
            okeys[orders["o_orderkey"][i]] = (
                orders["o_orderdate"][i],
                orders["o_shippriority"][i],
            )
    agg = defaultdict(float)
    for i in range(nl):
        ok = li["l_orderkey"][i]
        if ok in okeys and li["l_shipdate"][i] > cut:
            agg[(ok, okeys[ok][0], okeys[ok][1])] += li["l_extendedprice"][i] * (
                1 - li["l_discount"][i]
            )
    expected = sorted(
        ((k[0], round(v, 4), k[1], k[2]) for k, v in agg.items()),
        key=lambda t: (-t[1], t[2]),
    )[:10]
    got = [(a, round(b, 4), c, d) for a, b, c, d in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0] and g[2] == e[2] and g[3] == e[3]
        assert math.isclose(g[1], e[1], abs_tol=0.01)


def test_q5(sess):
    r = sess.must_query(
        "select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue "
        "from customer, orders, lineitem, supplier, nation, region "
        "where c_custkey = o_custkey and l_orderkey = o_orderkey "
        "and l_suppkey = s_suppkey and c_nationkey = s_nationkey "
        "and s_nationkey = n_nationkey and n_regionkey = r_regionkey "
        "and r_name = 'ASIA' "
        "and o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01' "
        "group by n_name order by revenue desc"
    )
    cust, nc = decode_table(sess, "customer")
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    supp, ns = decode_table(sess, "supplier")
    nat, nn = decode_table(sess, "nation")
    reg, nr = decode_table(sess, "region")
    asia = {reg["r_regionkey"][i] for i in range(nr) if reg["r_name"][i] == "ASIA"}
    nkey_name = {
        nat["n_nationkey"][i]: nat["n_name"][i]
        for i in range(nn)
        if nat["n_regionkey"][i] in asia
    }
    cust_nation = {cust["c_custkey"][i]: cust["c_nationkey"][i] for i in range(nc)}
    supp_nation = {supp["s_suppkey"][i]: supp["s_nationkey"][i] for i in range(ns)}
    d0, d1 = days("1994-01-01"), days("1995-01-01")
    order_cust = {}
    for i in range(no):
        if d0 <= orders["o_orderdate"][i] < d1:
            order_cust[orders["o_orderkey"][i]] = orders["o_custkey"][i]
    agg = defaultdict(float)
    for i in range(nl):
        ok = li["l_orderkey"][i]
        if ok not in order_cust:
            continue
        ck = order_cust[ok]
        sk = li["l_suppkey"][i]
        cn = cust_nation.get(ck)
        sn = supp_nation.get(sk)
        if cn is None or sn is None or cn != sn or sn not in nkey_name:
            continue
        agg[nkey_name[sn]] += li["l_extendedprice"][i] * (1 - li["l_discount"][i])
    expected = sorted(
        ((k, round(v, 4)) for k, v in agg.items()), key=lambda t: -t[1]
    )
    got = [(a, round(b, 4)) for a, b in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0]
        assert math.isclose(g[1], e[1], abs_tol=0.01)


def test_q18(sess):
    thresh = 120
    r = sess.must_query(
        "select c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity) "
        "from customer, orders, lineitem "
        "where o_orderkey in (select l_orderkey from lineitem group by l_orderkey "
        f"having sum(l_quantity) > {thresh}) "
        "and c_custkey = o_custkey and o_orderkey = l_orderkey "
        "group by c_custkey, o_orderkey, o_orderdate, o_totalprice "
        "order by o_totalprice desc, o_orderdate limit 100"
    )
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    qty = defaultdict(float)
    for i in range(nl):
        qty[li["l_orderkey"][i]] += li["l_quantity"][i]
    big = {k for k, v in qty.items() if v > thresh}
    order_info = {
        orders["o_orderkey"][i]: (
            orders["o_custkey"][i],
            orders["o_orderdate"][i],
            orders["o_totalprice"][i],
        )
        for i in range(no)
    }
    expected = []
    for ok in big:
        if ok in order_info:
            ck, od, tp = order_info[ok]
            expected.append((ck, ok, od, tp, round(qty[ok], 2)))
    expected.sort(key=lambda t: (-t[3], t[2]))
    expected = expected[:100]
    got = [(a, b, c, d, round(e, 2)) for a, b, c, d, e in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0] and g[1] == e[1] and g[3] == e[3]
        assert math.isclose(g[4], e[4], abs_tol=0.01)


def test_q10_left_style(sess):
    """Q10-shaped: join + group by over customer returns."""
    r = sess.must_query(
        "select c_custkey, sum(l_extendedprice * (1 - l_discount)) as revenue "
        "from customer, orders, lineitem "
        "where c_custkey = o_custkey and l_orderkey = o_orderkey "
        "and l_returnflag = 'R' "
        "group by c_custkey order by revenue desc limit 20"
    )
    assert len(r.rows) <= 20
    assert all(row[1] is None or row[1] >= 0 for row in r.rows)


def test_q4(sess):
    """Q4: EXISTS correlated subquery (reference: TPC-H Q4; planner
    rewrite mirrors expression_rewriter.go semi-join conversion)."""
    r = sess.must_query(
        "select o_orderpriority, count(*) as order_count from orders "
        "where o_orderdate >= date '1993-07-01' "
        "and o_orderdate < date '1993-10-01' "
        "and exists (select * from lineitem where l_orderkey = o_orderkey "
        "and l_commitdate < l_receiptdate) "
        "group by o_orderpriority order by o_orderpriority"
    )
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    ok_set = {
        li["l_orderkey"][i]
        for i in range(nl)
        if li["l_commitdate"][i] < li["l_receiptdate"][i]
    }
    d0, d1 = days("1993-07-01"), days("1993-10-01")
    cnt = defaultdict(int)
    for i in range(no):
        od = orders["o_orderdate"][i]
        if d0 <= od < d1 and orders["o_orderkey"][i] in ok_set:
            cnt[orders["o_orderpriority"][i]] += 1
    expected = sorted(cnt.items())
    assert [(p, c) for p, c in r.rows] == expected


def test_q17(sess):
    """Q17: correlated scalar aggregate subquery (decorrelated to a
    left join on l_partkey group aggregates)."""
    r = sess.must_query(
        "select sum(l_extendedprice) / 7.0 as avg_yearly "
        "from lineitem, part "
        "where p_partkey = l_partkey and p_brand = 'Brand#23' "
        "and p_container = 'MED BAG' "
        "and l_quantity < (select 0.2 * avg(l_quantity) from lineitem "
        "where l_partkey = p_partkey)"
    )
    li, nl = decode_table(sess, "lineitem")
    part, np_ = decode_table(sess, "part")
    part_ok = {
        part["p_partkey"][i]
        for i in range(np_)
        if part["p_brand"][i] == "Brand#23"
        and part["p_container"][i] == "MED BAG"
    }
    sums = defaultdict(float)
    counts = defaultdict(int)
    for i in range(nl):
        pk = li["l_partkey"][i]
        sums[pk] += li["l_quantity"][i]
        counts[pk] += 1
    total = 0.0
    for i in range(nl):
        pk = li["l_partkey"][i]
        if pk in part_ok and li["l_quantity"][i] < 0.2 * sums[pk] / counts[pk]:
            total += li["l_extendedprice"][i]
    expected = total / 7.0
    got = r.rows[0][0]
    if expected == 0:
        assert got is None or got == 0
    else:
        assert math.isclose(got, expected, rel_tol=1e-6)


def test_q21(sess):
    """Q21: EXISTS + NOT EXISTS with non-equality residual correlation
    (the hardest subquery shape in TPC-H; grouped by s_suppkey since the
    toy generator carries no s_name)."""
    r = sess.must_query(
        "select s_suppkey, count(*) as numwait "
        "from supplier, lineitem l1, orders, nation "
        "where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey "
        "and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate "
        "and s_nationkey = n_nationkey and n_name = 'SAUDI ARABIA' "
        "and exists (select * from lineitem l2 where "
        "l2.l_orderkey = l1.l_orderkey and l2.l_suppkey <> l1.l_suppkey) "
        "and not exists (select * from lineitem l3 where "
        "l3.l_orderkey = l1.l_orderkey and l3.l_suppkey <> l1.l_suppkey "
        "and l3.l_receiptdate > l3.l_commitdate) "
        "group by s_suppkey order by numwait desc, s_suppkey limit 100"
    )
    li, nl = decode_table(sess, "lineitem")
    orders, no = decode_table(sess, "orders")
    supp, ns = decode_table(sess, "supplier")
    nation, nn = decode_table(sess, "nation")
    saudi = {
        nation["n_nationkey"][i]
        for i in range(nn)
        if nation["n_name"][i] == "SAUDI ARABIA"
    }
    s_nat = {supp["s_suppkey"][i]: supp["s_nationkey"][i] for i in range(ns)}
    status_f = {
        orders["o_orderkey"][i]
        for i in range(no)
        if orders["o_orderstatus"][i] == "F"
    }
    by_order = defaultdict(list)
    for i in range(nl):
        by_order[li["l_orderkey"][i]].append(i)
    cnt = defaultdict(int)
    for i in range(nl):
        sk = li["l_suppkey"][i]
        okey = li["l_orderkey"][i]
        if s_nat.get(sk) not in saudi or okey not in status_f:
            continue
        if not (li["l_receiptdate"][i] > li["l_commitdate"][i]):
            continue
        others = [j for j in by_order[okey] if li["l_suppkey"][j] != sk]
        if not others:
            continue
        if any(li["l_receiptdate"][j] > li["l_commitdate"][j] for j in others):
            continue
        cnt[sk] += 1
    expected = sorted(cnt.items(), key=lambda t: (-t[1], t[0]))[:100]
    assert [(a, b) for a, b in r.rows] == expected
