"""TPC-H benchmark ladder (BASELINE.json configs) through the SQL surface,
golden-checked against plain-Python computation over the decoded data.

Scale tier: TIDB_TPU_TPCH_SF overrides the scale factor (default 0.002)
and TIDB_TPU_TPCH_QUOTA sets a per-query memory quota in bytes — a quota
small enough that the streamed (spill-analog) aggregation and host-merged
sort paths engage turns this same 22-query module into the SF0.1+ parity
suite (driven by tests/test_scale_tpch22.py under the slow marker)."""

import math
import os
from collections import defaultdict

import pytest

from tidb_tpu.bench import load_tpch
from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog

_SF = float(os.environ.get("TIDB_TPU_TPCH_SF", "0.002"))
_QUOTA = os.environ.get("TIDB_TPU_TPCH_QUOTA")


@pytest.fixture(scope="module")
def sess():
    cat = Catalog()
    load_tpch(cat, sf=_SF, seed=11)
    s = Session(cat, db="tpch")
    if _QUOTA:
        s.execute(f"set tidb_mem_quota_query = {int(_QUOTA)}")
    return s


def decode_table(sess, name):
    t = sess.catalog.table("tpch", name)
    rows = []
    blocks = t.blocks()
    cols = t.schema.names
    data = {c: [] for c in cols}
    for b in blocks:
        for c in cols:
            data[c].extend(b.columns[c].decode().tolist())
    n = sum(b.nrows for b in blocks)
    return data, n


def _d2s(day_int):
    from tidb_tpu.dtypes import days_to_date

    return days_to_date(int(day_int))


def days(s):
    from tidb_tpu.dtypes import date_to_days

    return int(date_to_days(s))


def test_q1(sess):
    r = sess.must_query(
        "select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, "
        "sum(l_extendedprice) as sum_base_price, "
        "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
        "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
        "avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price, "
        "avg(l_discount) as avg_disc, count(*) as count_order "
        "from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day "
        "group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"
    )
    li, n = decode_table(sess, "lineitem")
    cutoff = days("1998-12-01") - 90  # DATE decodes to int days
    agg = defaultdict(lambda: [0.0, 0.0, 0.0, 0.0, 0])
    for i in range(n):
        sd = li["l_shipdate"][i]
        if sd > cutoff:
            continue
        key = (li["l_returnflag"][i], li["l_linestatus"][i])
        a = agg[key]
        q, p, d, t = (
            li["l_quantity"][i],
            li["l_extendedprice"][i],
            li["l_discount"][i],
            li["l_tax"][i],
        )
        a[0] += q
        a[1] += p
        a[2] += p * (1 - d)
        a[3] += p * (1 - d) * (1 + t)
        a[4] += 1
    expected = []
    for key in sorted(agg):
        a = agg[key]
        expected.append(
            (key[0], key[1], round(a[0], 2), round(a[1], 2), round(a[2], 4),
             round(a[3], 6), a[0] / a[4], a[1] / a[4], None, a[4])
        )
    assert len(r.rows) == len(expected)
    for got, exp in zip(r.rows, expected):
        assert got[0] == exp[0] and got[1] == exp[1]
        assert math.isclose(got[2], exp[2], abs_tol=0.01)
        assert math.isclose(got[3], exp[3], abs_tol=0.01)
        assert math.isclose(got[4], exp[4], rel_tol=1e-12, abs_tol=1e-4)
        assert math.isclose(got[5], exp[5], rel_tol=1e-12, abs_tol=1e-6)
        assert math.isclose(got[6], exp[6], rel_tol=1e-9)
        assert math.isclose(got[7], exp[7], rel_tol=1e-9)
        assert got[9] == exp[9]


def test_q6(sess):
    r = sess.must_query(
        "select sum(l_extendedprice * l_discount) as revenue from lineitem "
        "where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01' "
        "and l_discount between 0.05 and 0.07 and l_quantity < 24"
    )
    li, n = decode_table(sess, "lineitem")
    d0, d1 = days("1994-01-01"), days("1995-01-01")
    exp = 0.0
    for i in range(n):
        if (
            d0 <= li["l_shipdate"][i] < d1
            and 0.05 <= li["l_discount"][i] <= 0.07
            and li["l_quantity"][i] < 24
        ):
            exp += li["l_extendedprice"][i] * li["l_discount"][i]
    assert math.isclose(r.rows[0][0], round(exp, 4), abs_tol=0.01)


def test_q3(sess):
    r = sess.must_query(
        "select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue, "
        "o_orderdate, o_shippriority "
        "from customer, orders, lineitem "
        "where c_mktsegment = 'BUILDING' and c_custkey = o_custkey "
        "and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15' "
        "and l_shipdate > date '1995-03-15' "
        "group by l_orderkey, o_orderdate, o_shippriority "
        "order by revenue desc, o_orderdate limit 10"
    )
    cust, nc = decode_table(sess, "customer")
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    building = {
        cust["c_custkey"][i] for i in range(nc) if cust["c_mktsegment"][i] == "BUILDING"
    }
    cut = days("1995-03-15")
    okeys = {}
    for i in range(no):
        if orders["o_custkey"][i] in building and orders["o_orderdate"][i] < cut:
            okeys[orders["o_orderkey"][i]] = (
                # engine results present DATE as 'YYYY-MM-DD'
                _d2s(orders["o_orderdate"][i]),
                orders["o_shippriority"][i],
            )
    agg = defaultdict(float)
    for i in range(nl):
        ok = li["l_orderkey"][i]
        if ok in okeys and li["l_shipdate"][i] > cut:
            agg[(ok, okeys[ok][0], okeys[ok][1])] += li["l_extendedprice"][i] * (
                1 - li["l_discount"][i]
            )
    expected = sorted(
        ((k[0], round(v, 4), k[1], k[2]) for k, v in agg.items()),
        key=lambda t: (-t[1], t[2]),
    )[:10]
    got = [(a, round(b, 4), c, d) for a, b, c, d in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0] and g[2] == e[2] and g[3] == e[3]
        assert math.isclose(g[1], e[1], abs_tol=0.01)


def test_q5(sess):
    r = sess.must_query(
        "select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue "
        "from customer, orders, lineitem, supplier, nation, region "
        "where c_custkey = o_custkey and l_orderkey = o_orderkey "
        "and l_suppkey = s_suppkey and c_nationkey = s_nationkey "
        "and s_nationkey = n_nationkey and n_regionkey = r_regionkey "
        "and r_name = 'ASIA' "
        "and o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01' "
        "group by n_name order by revenue desc"
    )
    cust, nc = decode_table(sess, "customer")
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    supp, ns = decode_table(sess, "supplier")
    nat, nn = decode_table(sess, "nation")
    reg, nr = decode_table(sess, "region")
    asia = {reg["r_regionkey"][i] for i in range(nr) if reg["r_name"][i] == "ASIA"}
    nkey_name = {
        nat["n_nationkey"][i]: nat["n_name"][i]
        for i in range(nn)
        if nat["n_regionkey"][i] in asia
    }
    cust_nation = {cust["c_custkey"][i]: cust["c_nationkey"][i] for i in range(nc)}
    supp_nation = {supp["s_suppkey"][i]: supp["s_nationkey"][i] for i in range(ns)}
    d0, d1 = days("1994-01-01"), days("1995-01-01")
    order_cust = {}
    for i in range(no):
        if d0 <= orders["o_orderdate"][i] < d1:
            order_cust[orders["o_orderkey"][i]] = orders["o_custkey"][i]
    agg = defaultdict(float)
    for i in range(nl):
        ok = li["l_orderkey"][i]
        if ok not in order_cust:
            continue
        ck = order_cust[ok]
        sk = li["l_suppkey"][i]
        cn = cust_nation.get(ck)
        sn = supp_nation.get(sk)
        if cn is None or sn is None or cn != sn or sn not in nkey_name:
            continue
        agg[nkey_name[sn]] += li["l_extendedprice"][i] * (1 - li["l_discount"][i])
    expected = sorted(
        ((k, round(v, 4)) for k, v in agg.items()), key=lambda t: -t[1]
    )
    got = [(a, round(b, 4)) for a, b in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0]
        assert math.isclose(g[1], e[1], abs_tol=0.01)


def test_q18(sess):
    thresh = 120
    r = sess.must_query(
        "select c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity) "
        "from customer, orders, lineitem "
        "where o_orderkey in (select l_orderkey from lineitem group by l_orderkey "
        f"having sum(l_quantity) > {thresh}) "
        "and c_custkey = o_custkey and o_orderkey = l_orderkey "
        "group by c_custkey, o_orderkey, o_orderdate, o_totalprice "
        "order by o_totalprice desc, o_orderdate limit 100"
    )
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    qty = defaultdict(float)
    for i in range(nl):
        qty[li["l_orderkey"][i]] += li["l_quantity"][i]
    big = {k for k, v in qty.items() if v > thresh}
    order_info = {
        orders["o_orderkey"][i]: (
            orders["o_custkey"][i],
            orders["o_orderdate"][i],
            orders["o_totalprice"][i],
        )
        for i in range(no)
    }
    expected = []
    for ok in big:
        if ok in order_info:
            ck, od, tp = order_info[ok]
            expected.append((ck, ok, od, tp, round(qty[ok], 2)))
    expected.sort(key=lambda t: (-t[3], t[2]))
    expected = expected[:100]
    got = [(a, b, c, d, round(e, 2)) for a, b, c, d, e in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0] and g[1] == e[1] and g[3] == e[3]
        assert math.isclose(g[4], e[4], abs_tol=0.01)


def test_q10_left_style(sess):
    """Q10-shaped: join + group by over customer returns."""
    r = sess.must_query(
        "select c_custkey, sum(l_extendedprice * (1 - l_discount)) as revenue "
        "from customer, orders, lineitem "
        "where c_custkey = o_custkey and l_orderkey = o_orderkey "
        "and l_returnflag = 'R' "
        "group by c_custkey order by revenue desc limit 20"
    )
    assert len(r.rows) <= 20
    assert all(row[1] is None or row[1] >= 0 for row in r.rows)


def test_q4(sess):
    """Q4: EXISTS correlated subquery (reference: TPC-H Q4; planner
    rewrite mirrors expression_rewriter.go semi-join conversion)."""
    r = sess.must_query(
        "select o_orderpriority, count(*) as order_count from orders "
        "where o_orderdate >= date '1993-07-01' "
        "and o_orderdate < date '1993-10-01' "
        "and exists (select * from lineitem where l_orderkey = o_orderkey "
        "and l_commitdate < l_receiptdate) "
        "group by o_orderpriority order by o_orderpriority"
    )
    orders, no = decode_table(sess, "orders")
    li, nl = decode_table(sess, "lineitem")
    ok_set = {
        li["l_orderkey"][i]
        for i in range(nl)
        if li["l_commitdate"][i] < li["l_receiptdate"][i]
    }
    d0, d1 = days("1993-07-01"), days("1993-10-01")
    cnt = defaultdict(int)
    for i in range(no):
        od = orders["o_orderdate"][i]
        if d0 <= od < d1 and orders["o_orderkey"][i] in ok_set:
            cnt[orders["o_orderpriority"][i]] += 1
    expected = sorted(cnt.items())
    assert [(p, c) for p, c in r.rows] == expected


def test_q17(sess):
    """Q17: correlated scalar aggregate subquery (decorrelated to a
    left join on l_partkey group aggregates)."""
    r = sess.must_query(
        "select sum(l_extendedprice) / 7.0 as avg_yearly "
        "from lineitem, part "
        "where p_partkey = l_partkey and p_brand = 'Brand#23' "
        "and p_container = 'MED BAG' "
        "and l_quantity < (select 0.2 * avg(l_quantity) from lineitem "
        "where l_partkey = p_partkey)"
    )
    li, nl = decode_table(sess, "lineitem")
    part, np_ = decode_table(sess, "part")
    part_ok = {
        part["p_partkey"][i]
        for i in range(np_)
        if part["p_brand"][i] == "Brand#23"
        and part["p_container"][i] == "MED BAG"
    }
    sums = defaultdict(float)
    counts = defaultdict(int)
    for i in range(nl):
        pk = li["l_partkey"][i]
        sums[pk] += li["l_quantity"][i]
        counts[pk] += 1
    total = 0.0
    for i in range(nl):
        pk = li["l_partkey"][i]
        if pk in part_ok and li["l_quantity"][i] < 0.2 * sums[pk] / counts[pk]:
            total += li["l_extendedprice"][i]
    expected = total / 7.0
    got = r.rows[0][0]
    if expected == 0:
        assert got is None or got == 0
    else:
        assert math.isclose(got, expected, rel_tol=1e-6)


def test_q21(sess):
    """Q21: EXISTS + NOT EXISTS with non-equality residual correlation
    (the hardest subquery shape in TPC-H; grouped by s_suppkey since the
    toy generator carries no s_name)."""
    r = sess.must_query(
        "select s_suppkey, count(*) as numwait "
        "from supplier, lineitem l1, orders, nation "
        "where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey "
        "and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate "
        "and s_nationkey = n_nationkey and n_name = 'SAUDI ARABIA' "
        "and exists (select * from lineitem l2 where "
        "l2.l_orderkey = l1.l_orderkey and l2.l_suppkey <> l1.l_suppkey) "
        "and not exists (select * from lineitem l3 where "
        "l3.l_orderkey = l1.l_orderkey and l3.l_suppkey <> l1.l_suppkey "
        "and l3.l_receiptdate > l3.l_commitdate) "
        "group by s_suppkey order by numwait desc, s_suppkey limit 100"
    )
    li, nl = decode_table(sess, "lineitem")
    orders, no = decode_table(sess, "orders")
    supp, ns = decode_table(sess, "supplier")
    nation, nn = decode_table(sess, "nation")
    saudi = {
        nation["n_nationkey"][i]
        for i in range(nn)
        if nation["n_name"][i] == "SAUDI ARABIA"
    }
    s_nat = {supp["s_suppkey"][i]: supp["s_nationkey"][i] for i in range(ns)}
    status_f = {
        orders["o_orderkey"][i]
        for i in range(no)
        if orders["o_orderstatus"][i] == "F"
    }
    by_order = defaultdict(list)
    for i in range(nl):
        by_order[li["l_orderkey"][i]].append(i)
    cnt = defaultdict(int)
    for i in range(nl):
        sk = li["l_suppkey"][i]
        okey = li["l_orderkey"][i]
        if s_nat.get(sk) not in saudi or okey not in status_f:
            continue
        if not (li["l_receiptdate"][i] > li["l_commitdate"][i]):
            continue
        others = [j for j in by_order[okey] if li["l_suppkey"][j] != sk]
        if not others:
            continue
        if any(li["l_receiptdate"][j] > li["l_commitdate"][j] for j in others):
            continue
        cnt[sk] += 1
    expected = sorted(cnt.items(), key=lambda t: (-t[1], t[0]))[:100]
    assert [(a, b) for a, b in r.rows] == expected


def test_q2(sess):
    """Q2: correlated scalar MIN subquery over partsupp (decorrelated to a
    grouped-min left join; reference shape: expression_rewriter.go)."""
    r = sess.must_query(
        "select s_acctbal, s_name, n_name, p_partkey, p_mfgr "
        "from part, supplier, partsupp, nation, region "
        "where p_partkey = ps_partkey and s_suppkey = ps_suppkey "
        "and p_size = 15 and p_type like '%BRASS' "
        "and s_nationkey = n_nationkey and n_regionkey = r_regionkey "
        "and r_name = 'EUROPE' "
        "and ps_supplycost = (select min(ps_supplycost) from partsupp, supplier, "
        "nation, region where p_partkey = ps_partkey and s_suppkey = ps_suppkey "
        "and s_nationkey = n_nationkey and n_regionkey = r_regionkey "
        "and r_name = 'EUROPE') "
        "order by s_acctbal desc, n_name, s_name, p_partkey limit 100"
    )
    part, np_ = decode_table(sess, "part")
    supp, ns = decode_table(sess, "supplier")
    ps, nps = decode_table(sess, "partsupp")
    nat, nn = decode_table(sess, "nation")
    reg, nr = decode_table(sess, "region")
    europe = {reg["r_regionkey"][i] for i in range(nr) if reg["r_name"][i] == "EUROPE"}
    nat_info = {
        nat["n_nationkey"][i]: nat["n_name"][i]
        for i in range(nn)
        if nat["n_regionkey"][i] in europe
    }
    s_info = {
        supp["s_suppkey"][i]: (
            supp["s_acctbal"][i],
            supp["s_name"][i],
            supp["s_nationkey"][i],
        )
        for i in range(ns)
    }
    # min supplycost per part over european suppliers
    min_cost = {}
    for i in range(nps):
        sk = ps["ps_suppkey"][i]
        if sk not in s_info or s_info[sk][2] not in nat_info:
            continue
        pk = ps["ps_partkey"][i]
        c = ps["ps_supplycost"][i]
        if pk not in min_cost or c < min_cost[pk]:
            min_cost[pk] = c
    p_ok = {
        part["p_partkey"][i]: part["p_mfgr"][i]
        for i in range(np_)
        if part["p_size"][i] == 15 and part["p_type"][i].endswith("BRASS")
    }
    expected = []
    for i in range(nps):
        pk, sk = ps["ps_partkey"][i], ps["ps_suppkey"][i]
        if pk not in p_ok or sk not in s_info:
            continue
        bal, sname, snat = s_info[sk]
        if snat not in nat_info:
            continue
        if ps["ps_supplycost"][i] != min_cost.get(pk):
            continue
        expected.append((bal, sname, nat_info[snat], pk, p_ok[pk]))
    expected.sort(key=lambda t: (-t[0], t[2], t[1], t[3]))
    expected = expected[:100]
    got = [(round(a, 2), b, c, d, e) for a, b, c, d, e in r.rows]
    expected = [(round(a, 2), b, c, d, e) for a, b, c, d, e in expected]
    assert got == expected


def test_q7(sess):
    """Q7: two nation aliases, OR of name pairs, EXTRACT(YEAR), derived
    table with aliased expression columns."""
    r = sess.must_query(
        "select supp_nation, cust_nation, l_year, sum(volume) as revenue "
        "from (select n1.n_name as supp_nation, n2.n_name as cust_nation, "
        "extract(year from l_shipdate) as l_year, "
        "l_extendedprice * (1 - l_discount) as volume "
        "from supplier, lineitem, orders, customer, nation n1, nation n2 "
        "where s_suppkey = l_suppkey and o_orderkey = l_orderkey "
        "and c_custkey = o_custkey and s_nationkey = n1.n_nationkey "
        "and c_nationkey = n2.n_nationkey "
        "and ((n1.n_name = 'FRANCE' and n2.n_name = 'GERMANY') "
        "or (n1.n_name = 'GERMANY' and n2.n_name = 'FRANCE')) "
        "and l_shipdate between date '1995-01-01' and date '1996-12-31'"
        ") as shipping "
        "group by supp_nation, cust_nation, l_year "
        "order by supp_nation, cust_nation, l_year"
    )
    li, nl = decode_table(sess, "lineitem")
    orders, no = decode_table(sess, "orders")
    cust, nc = decode_table(sess, "customer")
    supp, ns = decode_table(sess, "supplier")
    nat, nn = decode_table(sess, "nation")
    import datetime

    nname = {nat["n_nationkey"][i]: nat["n_name"][i] for i in range(nn)}
    s_nat = {supp["s_suppkey"][i]: supp["s_nationkey"][i] for i in range(ns)}
    c_nat = {cust["c_custkey"][i]: cust["c_nationkey"][i] for i in range(nc)}
    o_cust = {orders["o_orderkey"][i]: orders["o_custkey"][i] for i in range(no)}
    d0, d1 = days("1995-01-01"), days("1996-12-31")
    epoch = datetime.date(1970, 1, 1)
    agg = defaultdict(float)
    for i in range(nl):
        if not (d0 <= li["l_shipdate"][i] <= d1):
            continue
        sn = nname.get(s_nat.get(li["l_suppkey"][i]))
        ck = o_cust.get(li["l_orderkey"][i])
        cn = nname.get(c_nat.get(ck)) if ck is not None else None
        pair = (sn, cn)
        if pair not in (("FRANCE", "GERMANY"), ("GERMANY", "FRANCE")):
            continue
        y = (epoch + datetime.timedelta(days=li["l_shipdate"][i])).year
        agg[(sn, cn, y)] += li["l_extendedprice"][i] * (1 - li["l_discount"][i])
    expected = sorted((k[0], k[1], k[2], round(v, 4)) for k, v in agg.items())
    got = [(a, b, c, round(d, 4)) for a, b, c, d in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[:3] == e[:3]
        assert math.isclose(g[3], e[3], abs_tol=0.02)


def test_q8(sess):
    """Q8: market-share CASE aggregation over a two-level derived table."""
    r = sess.must_query(
        "select o_year, sum(case when nation = 'BRAZIL' then volume else 0 end) "
        "/ sum(volume) as mkt_share "
        "from (select extract(year from o_orderdate) as o_year, "
        "l_extendedprice * (1 - l_discount) as volume, n2.n_name as nation "
        "from part, supplier, lineitem, orders, customer, nation n1, nation n2, region "
        "where p_partkey = l_partkey and s_suppkey = l_suppkey "
        "and l_orderkey = o_orderkey and o_custkey = c_custkey "
        "and c_nationkey = n1.n_nationkey and n1.n_regionkey = r_regionkey "
        "and r_name = 'AMERICA' and s_nationkey = n2.n_nationkey "
        "and o_orderdate between date '1995-01-01' and date '1996-12-31' "
        "and p_type = 'ECONOMY ANODIZED STEEL') as all_nations "
        "group by o_year order by o_year"
    )
    li, nl = decode_table(sess, "lineitem")
    orders, no = decode_table(sess, "orders")
    cust, nc = decode_table(sess, "customer")
    supp, ns = decode_table(sess, "supplier")
    nat, nn = decode_table(sess, "nation")
    reg, nr = decode_table(sess, "region")
    part, np_ = decode_table(sess, "part")
    import datetime

    america = {reg["r_regionkey"][i] for i in range(nr) if reg["r_name"][i] == "AMERICA"}
    nat_region = {nat["n_nationkey"][i]: nat["n_regionkey"][i] for i in range(nn)}
    nname = {nat["n_nationkey"][i]: nat["n_name"][i] for i in range(nn)}
    c_nat = {cust["c_custkey"][i]: cust["c_nationkey"][i] for i in range(nc)}
    s_nat = {supp["s_suppkey"][i]: supp["s_nationkey"][i] for i in range(ns)}
    p_ok = {
        part["p_partkey"][i]
        for i in range(np_)
        if part["p_type"][i] == "ECONOMY ANODIZED STEEL"
    }
    d0, d1 = days("1995-01-01"), days("1996-12-31")
    o_info = {}
    for i in range(no):
        if d0 <= orders["o_orderdate"][i] <= d1:
            o_info[orders["o_orderkey"][i]] = (
                orders["o_custkey"][i],
                orders["o_orderdate"][i],
            )
    epoch = datetime.date(1970, 1, 1)
    num = defaultdict(float)
    den = defaultdict(float)
    for i in range(nl):
        if li["l_partkey"][i] not in p_ok:
            continue
        oi = o_info.get(li["l_orderkey"][i])
        if oi is None:
            continue
        ck, od = oi
        cn = c_nat.get(ck)
        if cn is None or nat_region.get(cn) not in america:
            continue
        sn = s_nat.get(li["l_suppkey"][i])
        if sn is None:
            continue
        y = (epoch + datetime.timedelta(days=od)).year
        vol = li["l_extendedprice"][i] * (1 - li["l_discount"][i])
        den[y] += vol
        if nname.get(sn) == "BRAZIL":
            num[y] += vol
    expected = [(y, num[y] / den[y]) for y in sorted(den)]
    got = r.rows
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0]
        assert math.isclose(g[1], e[1], rel_tol=1e-9, abs_tol=1e-12)


def test_q9(sess):
    """Q9: profit by nation and year; LIKE '%green%' on p_name, partsupp
    double-key join."""
    r = sess.must_query(
        "select nation, o_year, sum(amount) as sum_profit "
        "from (select n_name as nation, "
        "extract(year from o_orderdate) as o_year, "
        "l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount "
        "from part, supplier, lineitem, partsupp, orders, nation "
        "where s_suppkey = l_suppkey and ps_suppkey = l_suppkey "
        "and ps_partkey = l_partkey and p_partkey = l_partkey "
        "and o_orderkey = l_orderkey and s_nationkey = n_nationkey "
        "and p_name like '%green%') as profit "
        "group by nation, o_year order by nation, o_year desc"
    )
    li, nl = decode_table(sess, "lineitem")
    orders, no = decode_table(sess, "orders")
    supp, ns = decode_table(sess, "supplier")
    nat, nn = decode_table(sess, "nation")
    part, np_ = decode_table(sess, "part")
    ps, nps = decode_table(sess, "partsupp")
    import datetime

    nname = {nat["n_nationkey"][i]: nat["n_name"][i] for i in range(nn)}
    s_nat = {supp["s_suppkey"][i]: supp["s_nationkey"][i] for i in range(ns)}
    p_ok = {part["p_partkey"][i] for i in range(np_) if "green" in part["p_name"][i]}
    ps_cost = {
        (ps["ps_partkey"][i], ps["ps_suppkey"][i]): ps["ps_supplycost"][i]
        for i in range(nps)
    }
    o_date = {orders["o_orderkey"][i]: orders["o_orderdate"][i] for i in range(no)}
    epoch = datetime.date(1970, 1, 1)
    agg = defaultdict(float)
    for i in range(nl):
        pk = li["l_partkey"][i]
        if pk not in p_ok:
            continue
        sk = li["l_suppkey"][i]
        cost = ps_cost.get((pk, sk))
        od = o_date.get(li["l_orderkey"][i])
        sn = s_nat.get(sk)
        if cost is None or od is None or sn is None or sn not in nname:
            continue
        y = (epoch + datetime.timedelta(days=od)).year
        amount = li["l_extendedprice"][i] * (1 - li["l_discount"][i]) - cost * li["l_quantity"][i]
        agg[(nname[sn], y)] += amount
    expected = sorted(
        ((k[0], k[1], round(v, 4)) for k, v in agg.items()),
        key=lambda t: (t[0], -t[1]),
    )
    got = [(a, b, round(c, 4)) for a, b, c in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[:2] == e[:2]
        assert math.isclose(g[2], e[2], abs_tol=0.05)


def test_q10(sess):
    """Q10 (full form): returned-item reporting with customer details."""
    r = sess.must_query(
        "select c_custkey, c_name, "
        "sum(l_extendedprice * (1 - l_discount)) as revenue, c_acctbal, "
        "n_name, c_address, c_phone, c_comment "
        "from customer, orders, lineitem, nation "
        "where c_custkey = o_custkey and l_orderkey = o_orderkey "
        "and o_orderdate >= date '1993-10-01' "
        "and o_orderdate < date '1994-01-01' "
        "and l_returnflag = 'R' and c_nationkey = n_nationkey "
        "group by c_custkey, c_name, c_acctbal, c_phone, n_name, c_address, c_comment "
        "order by revenue desc, c_custkey limit 20"
    )
    li, nl = decode_table(sess, "lineitem")
    orders, no = decode_table(sess, "orders")
    cust, nc = decode_table(sess, "customer")
    nat, nn = decode_table(sess, "nation")
    nname = {nat["n_nationkey"][i]: nat["n_name"][i] for i in range(nn)}
    c_info = {
        cust["c_custkey"][i]: (
            cust["c_name"][i],
            cust["c_acctbal"][i],
            nname[cust["c_nationkey"][i]],
            cust["c_address"][i],
            cust["c_phone"][i],
            cust["c_comment"][i],
        )
        for i in range(nc)
    }
    d0, d1 = days("1993-10-01"), days("1994-01-01")
    o_cust = {
        orders["o_orderkey"][i]: orders["o_custkey"][i]
        for i in range(no)
        if d0 <= orders["o_orderdate"][i] < d1
    }
    agg = defaultdict(float)
    for i in range(nl):
        if li["l_returnflag"][i] != "R":
            continue
        ck = o_cust.get(li["l_orderkey"][i])
        if ck is None:
            continue
        agg[ck] += li["l_extendedprice"][i] * (1 - li["l_discount"][i])
    expected = []
    for ck, rev in agg.items():
        nm, bal, nnm, addr, ph, cm = c_info[ck]
        expected.append((ck, nm, round(rev, 4), bal, nnm, addr, ph, cm))
    expected.sort(key=lambda t: (-t[2], t[0]))
    expected = expected[:20]
    got = [(a, b, round(c, 4), d, e, f, g, h) for a, b, c, d, e, f, g, h in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0] and g[1] == e[1] and g[4:] == e[4:]
        assert math.isclose(g[2], e[2], abs_tol=0.02)
        assert math.isclose(g[3], e[3], abs_tol=0.01)


def test_q11(sess):
    """Q11: HAVING against an uncorrelated scalar subquery."""
    r = sess.must_query(
        "select ps_partkey, sum(ps_supplycost * ps_availqty) as value "
        "from partsupp, supplier, nation "
        "where ps_suppkey = s_suppkey and s_nationkey = n_nationkey "
        "and n_name = 'GERMANY' "
        "group by ps_partkey having "
        "sum(ps_supplycost * ps_availqty) > ("
        "select sum(ps_supplycost * ps_availqty) * 0.005 "
        "from partsupp, supplier, nation "
        "where ps_suppkey = s_suppkey and s_nationkey = n_nationkey "
        "and n_name = 'GERMANY') "
        "order by value desc, ps_partkey"
    )
    ps, nps = decode_table(sess, "partsupp")
    supp, ns = decode_table(sess, "supplier")
    nat, nn = decode_table(sess, "nation")
    germany = {
        nat["n_nationkey"][i] for i in range(nn) if nat["n_name"][i] == "GERMANY"
    }
    s_ok = {supp["s_suppkey"][i] for i in range(ns) if supp["s_nationkey"][i] in germany}
    agg = defaultdict(float)
    total = 0.0
    for i in range(nps):
        if ps["ps_suppkey"][i] in s_ok:
            v = ps["ps_supplycost"][i] * ps["ps_availqty"][i]
            agg[ps["ps_partkey"][i]] += v
            total += v
    thresh = total * 0.005
    expected = sorted(
        ((k, round(v, 4)) for k, v in agg.items() if v > thresh),
        key=lambda t: (-t[1], t[0]),
    )
    got = [(a, round(b, 4)) for a, b in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0]
        assert math.isclose(g[1], e[1], abs_tol=0.02)


def test_q12(sess):
    """Q12: CASE-sum by ship mode over an IN list."""
    r = sess.must_query(
        "select l_shipmode, "
        "sum(case when o_orderpriority = '1-URGENT' or o_orderpriority = '2-HIGH' "
        "then 1 else 0 end) as high_line_count, "
        "sum(case when o_orderpriority <> '1-URGENT' and o_orderpriority <> '2-HIGH' "
        "then 1 else 0 end) as low_line_count "
        "from orders, lineitem where o_orderkey = l_orderkey "
        "and l_shipmode in ('MAIL', 'SHIP') "
        "and l_commitdate < l_receiptdate and l_shipdate < l_commitdate "
        "and l_receiptdate >= date '1994-01-01' and l_receiptdate < date '1995-01-01' "
        "group by l_shipmode order by l_shipmode"
    )
    li, nl = decode_table(sess, "lineitem")
    orders, no = decode_table(sess, "orders")
    o_pri = {orders["o_orderkey"][i]: orders["o_orderpriority"][i] for i in range(no)}
    d0, d1 = days("1994-01-01"), days("1995-01-01")
    hi = defaultdict(int)
    lo = defaultdict(int)
    for i in range(nl):
        if li["l_shipmode"][i] not in ("MAIL", "SHIP"):
            continue
        if not (li["l_commitdate"][i] < li["l_receiptdate"][i]):
            continue
        if not (li["l_shipdate"][i] < li["l_commitdate"][i]):
            continue
        if not (d0 <= li["l_receiptdate"][i] < d1):
            continue
        pri = o_pri.get(li["l_orderkey"][i])
        if pri is None:
            continue
        if pri in ("1-URGENT", "2-HIGH"):
            hi[li["l_shipmode"][i]] += 1
        else:
            lo[li["l_shipmode"][i]] += 1
        hi.setdefault(li["l_shipmode"][i], 0)
        lo.setdefault(li["l_shipmode"][i], 0)
    expected = sorted((m, hi[m], lo[m]) for m in set(hi) | set(lo))
    assert [(a, b, c) for a, b, c in r.rows] == expected


def test_q13(sess):
    """Q13: LEFT OUTER JOIN with a NOT LIKE filter on the inner side,
    then a second aggregation over the per-customer counts."""
    r = sess.must_query(
        "select c_count, count(*) as custdist from "
        "(select c_custkey, count(o_orderkey) as c_count "
        "from customer left outer join orders on "
        "c_custkey = o_custkey and o_comment not like '%special%requests%' "
        "group by c_custkey) as c_orders "
        "group by c_count order by custdist desc, c_count desc"
    )
    orders, no = decode_table(sess, "orders")
    cust, nc = decode_table(sess, "customer")
    import re

    pat = re.compile(r"special.*requests")
    cnt = {cust["c_custkey"][i]: 0 for i in range(nc)}
    for i in range(no):
        if pat.search(orders["o_comment"][i]):
            continue
        ck = orders["o_custkey"][i]
        if ck in cnt:
            cnt[ck] += 1
    dist = defaultdict(int)
    for v in cnt.values():
        dist[v] += 1
    expected = sorted(((c, d) for c, d in dist.items()), key=lambda t: (-t[1], -t[0]))
    assert [(a, b) for a, b in r.rows] == expected


def test_q14(sess):
    """Q14: promo revenue ratio (CASE + LIKE prefix inside SUM)."""
    r = sess.must_query(
        "select 100.00 * sum(case when p_type like 'PROMO%' "
        "then l_extendedprice * (1 - l_discount) else 0 end) "
        "/ sum(l_extendedprice * (1 - l_discount)) as promo_revenue "
        "from lineitem, part where l_partkey = p_partkey "
        "and l_shipdate >= date '1995-09-01' and l_shipdate < date '1995-10-01'"
    )
    li, nl = decode_table(sess, "lineitem")
    part, np_ = decode_table(sess, "part")
    p_type = {part["p_partkey"][i]: part["p_type"][i] for i in range(np_)}
    d0, d1 = days("1995-09-01"), days("1995-10-01")
    num = den = 0.0
    for i in range(nl):
        if not (d0 <= li["l_shipdate"][i] < d1):
            continue
        t = p_type.get(li["l_partkey"][i])
        if t is None:
            continue
        v = li["l_extendedprice"][i] * (1 - li["l_discount"][i])
        den += v
        if t.startswith("PROMO"):
            num += v
    expected = 100.0 * num / den
    assert math.isclose(r.rows[0][0], expected, rel_tol=1e-9)


def test_q15(sess):
    """Q15: CTE view + equality with a scalar MAX over the view."""
    r = sess.must_query(
        "with revenue as (select l_suppkey as supplier_no, "
        "sum(l_extendedprice * (1 - l_discount)) as total_revenue "
        "from lineitem where l_shipdate >= date '1996-01-01' "
        "and l_shipdate < date '1996-04-01' group by l_suppkey) "
        "select s_suppkey, s_name, total_revenue "
        "from supplier, revenue where s_suppkey = supplier_no "
        "and total_revenue = (select max(total_revenue) from revenue) "
        "order by s_suppkey"
    )
    li, nl = decode_table(sess, "lineitem")
    supp, ns = decode_table(sess, "supplier")
    d0, d1 = days("1996-01-01"), days("1996-04-01")
    rev = defaultdict(float)
    for i in range(nl):
        if d0 <= li["l_shipdate"][i] < d1:
            rev[li["l_suppkey"][i]] += li["l_extendedprice"][i] * (
                1 - li["l_discount"][i]
            )
    mx = max(rev.values())
    s_name = {supp["s_suppkey"][i]: supp["s_name"][i] for i in range(ns)}
    expected = sorted(
        (sk, s_name[sk], round(v, 4))
        for sk, v in rev.items()
        if math.isclose(v, mx, rel_tol=0, abs_tol=1e-9) and sk in s_name
    )
    got = [(a, b, round(c, 4)) for a, b, c in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[:2] == e[:2]
        assert math.isclose(g[2], e[2], abs_tol=0.02)


def test_q16(sess):
    """Q16: COUNT(DISTINCT), NOT LIKE, and NOT IN subquery."""
    r = sess.must_query(
        "select p_brand, p_type, p_size, count(distinct ps_suppkey) as supplier_cnt "
        "from partsupp, part where p_partkey = ps_partkey "
        "and p_brand <> 'Brand#45' and p_type not like 'MEDIUM POLISHED%' "
        "and p_size in (49, 14, 23, 45, 19, 3, 36, 9) "
        "and ps_suppkey not in (select s_suppkey from supplier where "
        "s_comment like '%Customer%Complaints%') "
        "group by p_brand, p_type, p_size "
        "order by supplier_cnt desc, p_brand, p_type, p_size"
    )
    ps, nps = decode_table(sess, "partsupp")
    part, np_ = decode_table(sess, "part")
    supp, ns = decode_table(sess, "supplier")
    import re

    pat = re.compile(r"Customer.*Complaints")
    bad_supp = {
        supp["s_suppkey"][i] for i in range(ns) if pat.search(supp["s_comment"][i])
    }
    sizes = {49, 14, 23, 45, 19, 3, 36, 9}
    p_info = {}
    for i in range(np_):
        if (
            part["p_brand"][i] != "Brand#45"
            and not part["p_type"][i].startswith("MEDIUM POLISHED")
            and part["p_size"][i] in sizes
        ):
            p_info[part["p_partkey"][i]] = (
                part["p_brand"][i],
                part["p_type"][i],
                part["p_size"][i],
            )
    groups = defaultdict(set)
    for i in range(nps):
        pk = ps["ps_partkey"][i]
        sk = ps["ps_suppkey"][i]
        if pk in p_info and sk not in bad_supp:
            groups[p_info[pk]].add(sk)
    expected = sorted(
        ((k[0], k[1], k[2], len(v)) for k, v in groups.items()),
        key=lambda t: (-t[3], t[0], t[1], t[2]),
    )
    assert [(a, b, c, d) for a, b, c, d in r.rows] == expected


def test_q19(sess):
    """Q19: disjunction of three conjunctive predicate groups."""
    r = sess.must_query(
        "select sum(l_extendedprice * (1 - l_discount)) as revenue "
        "from lineitem, part where "
        "(p_partkey = l_partkey and p_brand = 'Brand#12' "
        "and p_container in ('SM CASE', 'SM BOX', 'SM PACK') "
        "and l_quantity >= 1 and l_quantity <= 11 "
        "and p_size between 1 and 5 "
        "and l_shipmode in ('AIR', 'REG AIR') "
        "and l_shipinstruct = 'DELIVER IN PERSON') "
        "or (p_partkey = l_partkey and p_brand = 'Brand#23' "
        "and p_container in ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK') "
        "and l_quantity >= 10 and l_quantity <= 20 "
        "and p_size between 1 and 10 "
        "and l_shipmode in ('AIR', 'REG AIR') "
        "and l_shipinstruct = 'DELIVER IN PERSON') "
        "or (p_partkey = l_partkey and p_brand = 'Brand#34' "
        "and p_container in ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG') "
        "and l_quantity >= 20 and l_quantity <= 30 "
        "and p_size between 1 and 15 "
        "and l_shipmode in ('AIR', 'REG AIR') "
        "and l_shipinstruct = 'DELIVER IN PERSON')"
    )
    li, nl = decode_table(sess, "lineitem")
    part, np_ = decode_table(sess, "part")
    p_info = {
        part["p_partkey"][i]: (
            part["p_brand"][i],
            part["p_container"][i],
            part["p_size"][i],
        )
        for i in range(np_)
    }
    total = 0.0
    hit = 0
    for i in range(nl):
        pi = p_info.get(li["l_partkey"][i])
        if pi is None:
            continue
        brand, cont, size = pi
        q = li["l_quantity"][i]
        if li["l_shipmode"][i] not in ("AIR", "REG AIR"):
            continue
        if li["l_shipinstruct"][i] != "DELIVER IN PERSON":
            continue
        ok = (
            (brand == "Brand#12" and cont in ("SM CASE", "SM BOX", "SM PACK")
             and 1 <= q <= 11 and 1 <= size <= 5)
            or (brand == "Brand#23" and cont in ("MED BAG", "MED BOX", "MED PKG", "MED PACK")
                and 10 <= q <= 20 and 1 <= size <= 10)
            or (brand == "Brand#34" and cont in ("LG CASE", "LG BOX", "LG PACK", "LG PKG")
                and 20 <= q <= 30 and 1 <= size <= 15)
        )
        if ok:
            total += li["l_extendedprice"][i] * (1 - li["l_discount"][i])
            hit += 1
    got = r.rows[0][0]
    if hit == 0:
        assert got is None or got == 0
    else:
        assert math.isclose(got, total, rel_tol=1e-9)


def test_q20(sess):
    """Q20: nested IN subqueries with a correlated scalar (0.5 * SUM)."""
    r = sess.must_query(
        "select s_name, s_address from supplier, nation "
        "where s_suppkey in (select ps_suppkey from partsupp where "
        "ps_partkey in (select p_partkey from part where p_name like 'forest%') "
        "and ps_availqty > (select 0.5 * sum(l_quantity) from lineitem "
        "where l_partkey = ps_partkey and l_suppkey = ps_suppkey "
        "and l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01')) "
        "and s_nationkey = n_nationkey and n_name = 'CANADA' "
        "order by s_name"
    )
    li, nl = decode_table(sess, "lineitem")
    supp, ns = decode_table(sess, "supplier")
    nat, nn = decode_table(sess, "nation")
    part, np_ = decode_table(sess, "part")
    ps, nps = decode_table(sess, "partsupp")
    forest = {part["p_partkey"][i] for i in range(np_) if part["p_name"][i].startswith("forest")}
    d0, d1 = days("1994-01-01"), days("1995-01-01")
    shipped = defaultdict(float)
    for i in range(nl):
        if d0 <= li["l_shipdate"][i] < d1:
            shipped[(li["l_partkey"][i], li["l_suppkey"][i])] += li["l_quantity"][i]
    good_supp = set()
    for i in range(nps):
        pk, sk = ps["ps_partkey"][i], ps["ps_suppkey"][i]
        if pk not in forest:
            continue
        key = (pk, sk)
        half = 0.5 * shipped[key] if key in shipped else None
        # NULL comparison semantics: no lineitem rows -> SUM is NULL ->
        # ps_availqty > NULL is unknown -> row filtered out
        if half is not None and ps["ps_availqty"][i] > half:
            good_supp.add(sk)
    canada = {nat["n_nationkey"][i] for i in range(nn) if nat["n_name"][i] == "CANADA"}
    expected = sorted(
        (supp["s_name"][i], supp["s_address"][i])
        for i in range(ns)
        if supp["s_suppkey"][i] in good_supp and supp["s_nationkey"][i] in canada
    )
    assert [(a, b) for a, b in r.rows] == expected


def test_q22(sess):
    """Q22: SUBSTRING country codes, uncorrelated AVG subquery, NOT EXISTS."""
    r = sess.must_query(
        "select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal "
        "from (select substring(c_phone, 1, 2) as cntrycode, c_acctbal "
        "from customer where substring(c_phone, 1, 2) in "
        "('13', '31', '23', '29', '30', '18', '17') "
        "and c_acctbal > (select avg(c_acctbal) from customer "
        "where c_acctbal > 0.00 and substring(c_phone, 1, 2) in "
        "('13', '31', '23', '29', '30', '18', '17')) "
        "and not exists (select * from orders where o_custkey = c_custkey)"
        ") as custsale group by cntrycode order by cntrycode"
    )
    orders, no = decode_table(sess, "orders")
    cust, nc = decode_table(sess, "customer")
    codes = {"13", "31", "23", "29", "30", "18", "17"}
    in_code = [cust["c_phone"][i][:2] in codes for i in range(nc)]
    pos = [
        cust["c_acctbal"][i]
        for i in range(nc)
        if in_code[i] and cust["c_acctbal"][i] > 0
    ]
    avg_bal = sum(pos) / len(pos)
    has_orders = {orders["o_custkey"][i] for i in range(no)}
    cnt = defaultdict(int)
    tot = defaultdict(float)
    for i in range(nc):
        if not in_code[i] or cust["c_acctbal"][i] <= avg_bal:
            continue
        if cust["c_custkey"][i] in has_orders:
            continue
        cc = cust["c_phone"][i][:2]
        cnt[cc] += 1
        tot[cc] += cust["c_acctbal"][i]
    expected = sorted((cc, cnt[cc], round(tot[cc], 2)) for cc in cnt)
    got = [(a, b, round(c, 2)) for a, b, c in r.rows]
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[:2] == e[:2]
        assert math.isclose(g[2], e[2], abs_tol=0.02)
