"""Index-merge scans: OR-of-indexed-ranges as a union of sorted-index
row-id sets.

Reference: pkg/executor/index_merge_reader.go:88 (IndexMergeReaderExec,
union mode). The columnar analog unions searchsorted row-id slices of
the derived per-version indexes (dedup via np.unique — a row matching
several disjuncts gathers once); the original predicate still filters
the fetched batch, so extraction over-approximation is always safe.
"""

import numpy as np
import pytest

from tidb_tpu.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create database im")
    s.execute("use im")
    s.execute(
        "create table t (id int primary key, a int, b int, v int)"
    )
    s.execute("create index ia on t (a)")
    s.execute("create index ib on t (b)")
    rows = ", ".join(
        f"({i}, {i % 97}, {(i * 7) % 89}, {i})" for i in range(2000)
    )
    s.execute(f"insert into t values {rows}")
    return s


def _plan(sess, sql):
    return "\n".join(r[0] for r in sess.execute("explain " + sql).rows)


class TestIndexMerge:
    def test_or_two_indexes_union(self, sess):
        sql = "select v from t where a = 5 or b = 7 order by v"
        assert "IndexMerge(union" in _plan(sess, sql)
        got = sess.execute(sql).rows
        expect = sorted(
            (i,) for i in range(2000) if i % 97 == 5 or (i * 7) % 89 == 7
        )
        assert got == expect

    def test_overlap_rows_counted_once(self, sess):
        # rows matching BOTH disjuncts must appear exactly once
        sql = "select count(*) from t where a = 5 or id < 100"
        assert "IndexMerge(union" in _plan(sess, sql)
        expect = sum(
            1 for i in range(2000) if i % 97 == 5 or i < 100
        )
        assert sess.execute(sql).rows == [(expect,)]

    def test_three_way_or(self, sess):
        sql = (
            "select count(*) from t "
            "where a = 3 or b = 11 or id between 1500 and 1600"
        )
        assert "IndexMerge(union" in _plan(sess, sql)
        expect = sum(
            1 for i in range(2000)
            if i % 97 == 3 or (i * 7) % 89 == 11 or 1500 <= i <= 1600
        )
        assert sess.execute(sql).rows == [(expect,)]

    def test_unindexed_disjunct_falls_back(self, sess):
        # v has no index: the union cannot cover "v = 9" -> no merge
        sql = "select count(*) from t where a = 5 or v = 9"
        assert "IndexMerge" not in _plan(sess, sql)
        expect = sum(1 for i in range(2000) if i % 97 == 5 or i == 9)
        assert sess.execute(sql).rows == [(expect,)]

    def test_extra_conjunct_still_filters(self, sess):
        # (a=5 OR b=7) AND v >= 1000: merge on the OR, filter the rest
        sql = (
            "select count(*) from t "
            "where (a = 5 or b = 7) and v >= 1000"
        )
        assert "IndexMerge(union" in _plan(sess, sql)
        expect = sum(
            1 for i in range(2000)
            if (i % 97 == 5 or (i * 7) % 89 == 7) and i >= 1000
        )
        assert sess.execute(sql).rows == [(expect,)]

    def test_dml_sees_merge_rows_correctly(self, sess):
        # UPDATE through an OR predicate (uses handle scans -> the
        # merge path must NOT engage on _tidb_rowid scans)
        sess.execute("update t set v = -1 where a = 5 or b = 7")
        expect = sum(
            1 for i in range(2000) if i % 97 == 5 or (i * 7) % 89 == 7
        )
        assert sess.execute(
            "select count(*) from t where v = -1"
        ).rows == [(expect,)]

    def test_merge_after_dml_fresh_rows(self, sess):
        sess.execute("insert into t values (9001, 5, 0, 9001)")
        sql = "select count(*) from t where a = 5 or b = 7"
        base = sum(
            1 for i in range(2000) if i % 97 == 5 or (i * 7) % 89 == 7
        )
        assert sess.execute(sql).rows == [(base + 1,)]
