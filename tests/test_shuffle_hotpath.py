"""Tier-1 gate for scripts/check_shuffle_hotpath.py: the shuffle data
plane (producer partition/encode/send, tunnel sender, receiver store,
push handlers, consumer staging) must not grow new json.dumps/json.loads
call sites — exchange data rides the binary columnar codec
(parallel/wire.py); JSON survives only at the marked fallback sites."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "check_shuffle_hotpath.py")


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, LINT, REPO], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        f"shuffle hot-path violations:\n{proc.stdout}{proc.stderr}"
    )


def test_lint_catches_unmarked_json_on_hotpath(tmp_path):
    pkg = tmp_path / "tidb_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (pkg / "shuffle.py").write_text(
        "import json\n"
        "class ShuffleStore:\n"
        "    def push(self, payload):\n"
        "        return json.loads(payload)\n"  # data plane: violation
        "class PeerTunnel:\n"
        "    def send(self, packet):\n"
        "        # shuffle-json-fallback: declared escape hatch\n"
        "        return json.dumps(packet)\n"  # marked: allowed
        "def off_hotpath():\n"
        "    return json.dumps({})\n"  # not a data-plane function
    )
    proc = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout
    assert "ShuffleStore.push" in proc.stdout
    assert "PeerTunnel.send" not in proc.stdout
    assert "off_hotpath" not in proc.stdout


def test_lint_rejects_barrier_shape_regressions(tmp_path):
    """The pipeline guard: whole-stage row materialization on the
    binary produce path and post-wait bulk decode / concat double
    copies fail the lint even without any json call."""
    pkg = tmp_path / "tidb_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (pkg / "shuffle.py").write_text(
        "import numpy as np\n"
        "def stage_payloads_incremental(schema, payloads, nonce,\n"
        "                               vocab=None, key=None):\n"
        "    return np.concatenate([p.data for p in payloads])\n"
        "class ShuffleWorker:\n"
        "    def _ship_side_stream(self, block):\n"
        "        return materialize_rows(block)\n"
        "    def run_task(self, spec):\n"
        "        return decode_frame(spec)\n"
        "    def _harmless(self, block):\n"
        "        return materialize_rows(block)\n"  # not a guarded fn
    )
    proc = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout
    assert "concatenate" in proc.stdout
    assert "materialize_rows" in proc.stdout
    assert "decode_frame" in proc.stdout
    assert "_harmless" not in proc.stdout


def test_lint_flags_unparseable_hotpath_file(tmp_path):
    pkg = tmp_path / "tidb_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (pkg / "wire.py").write_text("def broken(:\n")
    proc = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "unparseable" in proc.stdout
