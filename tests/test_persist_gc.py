"""Durability (save/load snapshot) and MVCC version GC.

Reference: BR full backup (br/pkg/task/backup.go) for persistence; the
GC worker safepoint contract (pkg/store/gcworker/gc_worker.go:194,371)
for version pruning. VERDICT round-1 criteria: a restart test reloads
the catalog; a long UPDATE loop holds steady memory.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog, load_catalog, save_catalog


def test_save_load_roundtrip(tmp_path):
    cat = Catalog()
    s = Session(cat)
    s.execute("create database shop")
    s.execute("use shop")
    s.execute(
        "create table items (id bigint, name varchar(32), price decimal(10,2), "
        "added date, score double)"
    )
    s.execute(
        "insert into items values (1,'apple',1.25,'2024-01-31',0.5),"
        "(2,'pear',null,'2023-06-01',null),(3,null,3.5,null,2.25)"
    )
    q = "select id, name, price, added, score from items order by id"
    before = s.must_query(q).rows

    save_catalog(cat, str(tmp_path / "snap"))

    cat2 = load_catalog(str(tmp_path / "snap"))
    s2 = Session(cat2, db="shop")
    after = s2.must_query(q).rows
    assert after == before
    # the restored store is writable and queryable
    s2.execute("insert into items values (4,'fig',9.99,'2025-05-05',1.0)")
    assert s2.must_query("select count(*) from items").rows == [(4,)]


def test_update_loop_holds_versions_steady():
    s = Session(Catalog())
    s.execute("create table t (k bigint, v bigint)")
    s.execute("insert into t values (1, 0), (2, 0)")
    t = s.catalog.table("test", "t")
    for i in range(300):
        s.execute(f"update t set v = {i} where k = 1")
    # GC keeps only current + previous (no pins active)
    assert len(t._versions) <= 2, len(t._versions)
    assert s.must_query("select v from t where k = 1").rows == [(299,)]


def test_pinned_snapshot_survives_gc():
    s = Session(Catalog())
    s.execute("create table t (k bigint, v bigint)")
    s.execute("insert into t values (1, 10)")
    writer = Session(s.catalog)
    s.execute("begin")
    assert s.must_query("select v from t").rows == [(10,)]  # pins snapshot
    for i in range(20):
        writer.execute(f"update t set v = {100 + i} where k = 1")
    # the reader's snapshot version is pinned through the writer churn
    assert s.must_query("select v from t").rows == [(10,)]
    s.execute("rollback")
    assert s.must_query("select v from t").rows == [(119,)]
    t = s.catalog.table("test", "t")
    writer.execute("update t set v = 1 where k = 1")
    assert len(t._versions) <= 2


# ---- point/range access (reference: point_get.go:132 + ranger) ------------


def test_point_and_range_pk_access():
    s = Session(Catalog())
    s.execute("create table p (k bigint primary key, v bigint)")
    s.execute(
        "insert into p values " + ",".join(f"({i},{i * 3})" for i in range(500))
    )
    assert s.must_query("select v from p where k = 42").rows == [(126,)]
    assert s.must_query(
        "select count(*), sum(v) from p where k between 10 and 14"
    ).rows == [(5, 180)]
    assert s.must_query("select v from p where k = 9999").rows == []
    # compiled plan carries the range: scan site fetches a tiny batch
    from tidb_tpu.parser import parse
    from tidb_tpu.planner import build_query
    from tidb_tpu.planner.physical import PlanCompiler

    st = parse("select v from p where k = 42")
    st = st[0] if isinstance(st, list) else st
    plan = build_query(st, s.catalog, "test", s._scalar_subquery)
    comp = PlanCompiler(s.catalog)
    cq = comp.compile(plan)
    assert comp.scans[0].pk_range == ("k", 42, 42)


def test_pk_update_touches_only_matching_rows():
    s = Session(Catalog())
    s.execute("create table u (k bigint primary key, v bigint, d decimal(8,2))")
    s.execute(
        "insert into u values " + ",".join(f"({i},{i},{i}.5)" for i in range(100))
    )
    s.execute("update u set v = v * 10, d = 0.25 where k = 7")
    assert s.must_query("select v, d from u where k = 7").rows == [(70, 0.25)]
    assert s.must_query("select v, d from u where k = 8").rows == [(8, 8.5)]
    assert s.must_query("select count(*), sum(v) from u").rows[0][0] == 100
    # NULL assignment through the columnar path
    s.execute("update u set v = null where k = 3")
    assert s.must_query("select v from u where k = 3").rows == [(None,)]


def test_snapshot_restores_without_pickle(tmp_path):
    """Snapshots store string dictionaries as fixed-width unicode and
    load with allow_pickle OFF: a crafted npz can never execute code on
    RESTORE (ADVICE round-2 #2; reference BR format is data-only)."""
    import numpy as np

    from tidb_tpu.storage.persist import load_catalog, save_catalog

    cat = Catalog()
    s = Session(cat, db="test")
    s.execute("create table t (a int, s varchar(20))")
    s.execute("insert into t values (1, 'alpha'), (2, NULL), (3, 'beta')")
    save_catalog(cat, str(tmp_path))
    # every stored array is pickle-free
    for fn in tmp_path.glob("*.npz"):
        data = np.load(fn)  # allow_pickle defaults to False: must not raise
        for k in data.files:
            assert data[k].dtype != object
    cat2 = load_catalog(str(tmp_path))
    s2 = Session(cat2, db="test")
    assert s2.execute("select a, s from t order by a").rows == [
        (1, "alpha"), (2, None), (3, "beta"),
    ]


def test_unique_check_with_int64_max_key():
    """A key equal to int64 max must not vanish into the NULL tail of
    the sorted index (ADVICE round-2 #4)."""
    import pytest as _pytest

    cat = Catalog()
    s = Session(cat, db="test")
    s.execute("create table t (a bigint primary key)")
    big = (1 << 63) - 1
    s.execute(f"insert into t values ({big})")
    with _pytest.raises(Exception, match="[Dd]uplicate"):
        s.execute(f"insert into t values ({big})")
    assert s.execute(f"select a from t where a = {big}").rows == [(big,)]
