"""Distributed SQL: the SAME queries through the mesh (SPMD shard_map)
session and the single-device session must return identical results.

Reference analog: TiDB's MPP mode runs the same SQL through TiFlash
exchange fragments and must agree with the single-node path
(pkg/planner/core/casetest/mpp golden tests). Here the mesh session
compiles each plan to ONE shard_map program over the virtual 8-device CPU
mesh (conftest.py) with all_to_all / all_gather exchanges inside.
"""

import numpy as np
import pytest

from tidb_tpu.bench import load_tpch
from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog

N_DEV = 8


@pytest.fixture(scope="module")
def sessions():
    cat = Catalog()
    load_tpch(
        cat,
        sf=0.01,
        tables=["orders", "lineitem", "customer", "supplier", "nation", "region"],
        seed=7,
    )
    single = Session(cat, db="tpch")
    mesh = Session(cat, db="tpch", mesh_devices=N_DEV)
    return single, mesh


QUERIES = [
    # packed-key group aggregation (partial/final + all_to_all)
    "select l_returnflag, l_linestatus, count(*), sum(l_quantity), "
    "avg(l_extendedprice) from lineitem group by l_returnflag, l_linestatus",
    # scalar aggregation (broadcast gather of partials)
    "select count(*), sum(l_extendedprice), min(l_shipdate), max(l_shipdate) "
    "from lineitem where l_discount <= 0.05",
    # int64-key aggregation through the distributed claim path
    "select l_suppkey, count(*) from lineitem group by l_suppkey "
    "order by count(*) desc, l_suppkey limit 5",
    # partitioned (all_to_all) inner join + aggregation
    "select o_orderpriority, count(*) from orders join lineitem "
    "on o_orderkey = l_orderkey where l_quantity < 10 "
    "group by o_orderpriority order by o_orderpriority",
    # left outer join, partitioned
    "select count(*), count(c_custkey) from customer "
    "left join orders on c_custkey = o_custkey and o_totalprice > 4000",
    # semi join (IN subquery)
    "select count(*) from orders where o_orderkey in "
    "(select l_orderkey from lineitem where l_quantity >= 49)",
    # anti join (NOT IN over non-null keys)
    "select count(*) from customer where c_custkey not in "
    "(select o_custkey from orders where o_totalprice > 1000)",
    # multi-key join via hash-combine + verify
    "select count(*) from lineitem a join lineitem b "
    "on a.l_orderkey = b.l_orderkey and a.l_linenumber = b.l_linenumber "
    "where a.l_suppkey < 20",
    # global sort + limit over a sharded scan (gather fragment)
    "select o_orderkey, o_totalprice from orders "
    "order by o_totalprice desc, o_orderkey limit 7",
    # window function over gathered fragment
    "select o_custkey, o_totalprice, "
    "rank() over (partition by o_custkey order by o_totalprice desc) rk "
    "from orders where o_custkey <= 5 order by o_custkey, rk",
    # union of two sharded branches
    "select l_returnflag x from lineitem where l_quantity > 49 "
    "union all select o_orderstatus from orders where o_totalprice < 1000",
    # broadcast-style join with small replicated side after a subquery
    "select n_name, count(*) from nation join supplier "
    "on n_nationkey = s_nationkey group by n_name order by 2 desc limit 4",
    # TPC-H Q1 shape end-to-end
    "select l_returnflag, l_linestatus, sum(l_quantity) sq, "
    "sum(l_extendedprice * (1 - l_discount)) sdp, avg(l_quantity) aq, "
    "count(*) c from lineitem where l_shipdate <= date '1998-12-01' "
    "group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus",
]


def _norm(rows):
    out = []
    for r in rows:
        nr = []
        for v in r:
            if isinstance(v, float):
                nr.append(round(v, 6))
            else:
                nr.append(v)
        out.append(tuple(nr))
    return sorted(out, key=lambda t: tuple((x is None, str(x)) for x in t))


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_mesh_matches_single(sessions, qi):
    single, mesh = sessions
    sql = QUERIES[qi]
    r1 = single.execute(sql)
    r2 = mesh.execute(sql)
    assert _norm(r1.rows) == _norm(r2.rows), sql


def test_mesh_repeat_uses_steady_state(sessions):
    """Second run of the same query goes through the cached shard_map
    program (steady state) and still matches."""
    single, mesh = sessions
    sql = QUERIES[0]
    r1 = mesh.execute(sql)
    r2 = mesh.execute(sql)
    assert _norm(r1.rows) == _norm(r2.rows)
    assert _norm(r2.rows) == _norm(single.execute(sql).rows)


def test_mesh_dml_visibility(sessions):
    """Writes invalidate the sharded scan cache too."""
    single, mesh = sessions
    mesh.execute("create database if not exists dml")
    mesh.execute("create table if not exists dml.t (a bigint, b double)")
    mesh.execute("insert into dml.t values (1, 1.5)")
    mesh.execute("insert into dml.t values (2, 2.5)")
    assert mesh.execute("select count(*) from dml.t").rows[0][0] == 2
    mesh.execute("insert into dml.t values (3, 3.5)")
    assert mesh.execute("select sum(a) from dml.t").rows[0][0] == 6


class TestExchangeSkewNegotiation:
    """Region-balance analog (pkg/store/copr/batch_coprocessor.go): the
    exchange reports the TRUE hot-bucket size, so a skewed key costs at
    most ONE capacity bump during discovery and the steady state never
    recompiles."""

    def test_skewed_key_no_steady_recompile(self):
        from tidb_tpu.utils import failpoint

        cat = Catalog()
        s = Session(cat, db="test")
        mesh = Session(cat, db="test", mesh_devices=N_DEV)
        s.execute("create table f (k int, v int)")
        s.execute("create table d (k int primary key, w int)")
        # 90% of fact rows share ONE key: a worst-case hot bucket
        rows = ", ".join(
            f"({7 if i % 10 else i}, {i})" for i in range(4000)
        )
        s.execute(f"insert into f values {rows}")
        s.execute(
            "insert into d values "
            + ", ".join(f"({i}, {i})" for i in range(4000))
        )
        sql = (
            "select count(*), sum(v + w) from f join d on f.k = d.k"
        )
        bumps: list = []
        failpoint.enable("executor/cap-overflow", lambda: bumps.append(1))
        try:
            r1 = mesh.execute(sql).rows
            discovery_bumps = len(bumps)
            bumps.clear()
            r2 = mesh.execute(sql).rows
            steady_bumps = len(bumps)
        finally:
            failpoint.disable("executor/cap-overflow")
        assert r1 == r2 == s.execute(sql).rows
        # true-need reporting: the hot bucket is sized in at most one
        # bump per knob during discovery...
        assert discovery_bumps <= 2, discovery_bumps
        # ...and the steady state replays the cached program untouched
        assert steady_bumps == 0, steady_bumps
