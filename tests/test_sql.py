"""SQL-level tests through the Session (reference: pkg/testkit MustQuery
pattern — SQL in, rows out, against the embedded engine)."""

import pytest

from tidb_tpu.session import Session


@pytest.fixture()
def s():
    sess = Session()
    sess.execute("create table t (a bigint, b bigint, c varchar(10))")
    sess.execute(
        "insert into t values (1, 10, 'x'), (2, 20, 'y'), (3, 30, 'x'), "
        "(4, null, 'z'), (null, 50, null)"
    )
    return sess


class TestBasics:
    def test_select_all(self, s):
        r = s.must_query("select a, b, c from t order by a")
        assert r.rows == [
            (None, 50, None), (1, 10, "x"), (2, 20, "y"), (3, 30, "x"), (4, None, "z"),
        ]

    def test_star_and_where(self, s):
        r = s.must_query("select * from t where a > 1 and b is not null order by a")
        assert r.rows == [(2, 20, "y"), (3, 30, "x")]

    def test_expressions(self, s):
        r = s.must_query("select a + b * 2, b div 7, b % 7 from t where a = 2")
        assert r.rows == [(42, 2, 6)]

    def test_case_and_cast(self, s):
        r = s.must_query(
            "select case when a >= 3 then 'big' when a is null then 'nul' else 'small' end = 'big', "
            "cast(a as double) / 2 from t where a = 3"
        )
        assert r.rows == [(True, 1.5)]

    def test_string_predicates(self, s):
        r = s.must_query("select a from t where c like '%x%' order by a")
        assert r.rows == [(1,), (3,)]
        r = s.must_query("select a from t where c in ('y', 'z') order by a")
        assert r.rows == [(2,), (4,)]

    def test_limit_offset(self, s):
        r = s.must_query("select a from t order by a desc limit 2")
        assert r.rows == [(4,), (3,)]
        r = s.must_query("select a from t order by a desc limit 1, 2")
        assert r.rows == [(3,), (2,)]

    def test_distinct(self, s):
        r = s.must_query("select distinct c from t order by c")
        assert r.rows == [(None,), ("x",), ("y",), ("z",)]


class TestAggregates:
    def test_scalar_agg(self, s):
        r = s.must_query("select count(*), count(b), sum(b), min(b), max(b), avg(b) from t")
        assert r.rows == [(5, 4, 110, 10, 50, 27.5)]

    def test_group_by(self, s):
        r = s.must_query(
            "select c, count(*), sum(a) from t group by c order by c"
        )
        assert r.rows == [(None, 1, None), ("x", 2, 4), ("y", 1, 2), ("z", 1, 4)]

    def test_having(self, s):
        r = s.must_query(
            "select c, count(*) as n from t group by c having n > 1"
        )
        assert r.rows == [("x", 2)]

    def test_group_by_alias_and_ordinal(self, s):
        r = s.must_query("select c as k, sum(b) from t group by k order by 1")
        assert r.rows == [(None, 50), ("x", 40), ("y", 20), ("z", None)]

    def test_empty_input_scalar(self, s):
        r = s.must_query("select count(*), sum(a) from t where a > 100")
        assert r.rows == [(0, None)]

    def test_order_by_agg(self, s):
        r = s.must_query(
            "select c, sum(b) from t where c is not null group by c order by sum(b) desc"
        )
        assert r.rows == [("x", 40), ("y", 20), ("z", None)]


class TestJoins:
    @pytest.fixture()
    def s2(self, s):
        s.execute("create table u (k bigint, v varchar(10))")
        s.execute("insert into u values (1, 'one'), (2, 'two'), (2, 'dos'), (9, 'nine')")
        return s

    def test_inner(self, s2):
        r = s2.must_query(
            "select t.a, u.v from t join u on t.a = u.k order by t.a, u.v"
        )
        assert r.rows == [(1, "one"), (2, "dos"), (2, "two")]

    def test_left(self, s2):
        r = s2.must_query(
            "select t.a, u.v from t left join u on t.a = u.k where t.a is not null order by t.a, u.v"
        )
        assert r.rows == [
            (1, "one"), (2, "dos"), (2, "two"), (3, None), (4, None),
        ]

    def test_join_with_residual(self, s2):
        r = s2.must_query(
            "select t.a, u.v from t join u on t.a = u.k and u.v like 't%'"
        )
        assert r.rows == [(2, "two")]

    def test_in_subquery(self, s2):
        r = s2.must_query("select a from t where a in (select k from u) order by a")
        assert r.rows == [(1,), (2,)]

    def test_not_in_subquery(self, s2):
        r = s2.must_query(
            "select a from t where a not in (select k from u) order by a"
        )
        assert r.rows == [(3,), (4,)]

    def test_not_in_with_null_build(self, s2):
        s2.execute("insert into u values (null, 'n')")
        r = s2.must_query("select a from t where a not in (select k from u)")
        assert r.rows == []

    def test_scalar_subquery(self, s2):
        r = s2.must_query("select a from t where a = (select min(k) from u)")
        assert r.rows == [(1,)]

    def test_derived_table(self, s2):
        r = s2.must_query(
            "select m.c, m.n from (select c, count(*) as n from t group by c) as m "
            "where m.n > 1"
        )
        assert r.rows == [("x", 2)]

    def test_cross_join(self, s2):
        r = s2.must_query(
            "select count(*) from t, u where t.a is not null"
        )
        assert r.rows == [(16,)]


class TestDML:
    def test_insert_delete(self, s):
        s.execute("delete from t where a >= 3")
        r = s.must_query("select count(*) from t")
        assert r.rows == [(3,)]
        s.execute("insert into t (a, c) values (7, 'w')")
        r = s.must_query("select a, b, c from t where a = 7")
        assert r.rows == [(7, None, "w")]

    def test_update(self, s):
        s.execute("update t set b = b + 1 where a <= 2")
        r = s.must_query("select a, b from t where a <= 2 order by a")
        assert r.rows == [(1, 11), (2, 21)]
        # untouched rows keep values
        r = s.must_query("select b from t where a = 3")
        assert r.rows == [(30,)]

    def test_ddl(self):
        sess = Session()
        sess.execute("create database if not exists d2")
        sess.execute("use d2")
        sess.execute("create table x (i int)")
        assert sess.must_query("show tables").rows == [("x",)]
        sess.execute("drop table x")
        assert sess.must_query("show tables").rows == []


class TestExplain:
    def test_explain_renders(self, s):
        r = s.must_query("explain select c, count(*) from t where a > 1 group by c")
        text = "\n".join(row[0] for row in r.rows)
        assert "Aggregate" in text and "Scan" in text and "Selection" in text


class TestUnionCte:
    def test_union_all(self, s):
        r = s.must_query(
            "select a from t where a <= 1 union all select b from t where a = 1 order by 1"
        )
        assert r.rows == [(1,), (10,)]

    def test_union_distinct(self, s):
        r = s.must_query("select a from t union select a from t order by a")
        assert r.rows == [(None,), (1,), (2,), (3,), (4,)]

    def test_union_type_coercion(self, s):
        r = s.must_query("select a from t where a = 1 union all select 2.5")
        vals = sorted(v for v, in r.rows)
        assert vals == [1.0, 2.5]

    def test_union_strings_merge_dicts(self, s):
        r = s.must_query(
            "select c from t where c = 'x' union all select 'new' order by 1"
        )
        assert [v for v, in r.rows] == ["new", "x", "x"]

    def test_with_cte(self, s):
        r = s.must_query(
            "with big as (select a, b from t where b >= 20) "
            "select count(*), sum(b) from big"
        )
        assert r.rows == [(3, 100)]

    def test_with_cte_joined(self, s):
        r = s.must_query(
            "with x as (select c, count(*) as n from t group by c) "
            "select t.a, x.n from t join x on t.c = x.c where t.a = 1"
        )
        assert r.rows == [(1, 2)]

    def test_cte_column_aliases(self, s):
        r = s.must_query(
            "with m (k, v) as (select a, b from t where a = 2) select k, v from m"
        )
        assert r.rows == [(2, 20)]
