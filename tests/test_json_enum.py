"""JSON type + functions, ENUM/SET domains, COLLATE.

Reference: pkg/types/json_binary.go (+ builtin_json_vec.go functions),
pkg/types enum/set write validation, pkg/util/collate. Device layout:
all three ride dictionary-coded strings; JSON ops run once per DISTINCT
value on host (the LIKE cost model) and gather on device.
"""

import pytest

from tidb_tpu.session.session import Session


@pytest.fixture()
def s():
    s = Session()
    s.execute(
        "create table e (st enum('open','closed'), tags set('a','b','c'), "
        "doc json)"
    )
    s.execute(
        "insert into e values "
        "('open', 'a,c', '{\"k\": [1, 2, {\"x\": \"y\"}], \"n\": null}'),"
        "('closed', '', '[10, 20]'),"
        "('open', 'b', '\"plain\"'),"
        "(null, null, null)"
    )
    return s


class TestDomains:
    def test_enum_rejects_outsiders(self, s):
        with pytest.raises(ValueError):
            s.execute("insert into e values ('bogus', 'a', '{}')")
        s.execute("insert into e values ('closed', 'a', '{}')")  # ok

    def test_set_rejects_non_members_and_dups(self, s):
        with pytest.raises(ValueError):
            s.execute("insert into e values ('open', 'a,z', '{}')")
        with pytest.raises(ValueError):
            s.execute("insert into e values ('open', 'a,a', '{}')")
        s.execute("insert into e values ('open', 'c,b', '{}')")  # ok

    def test_json_validated_on_write(self, s):
        with pytest.raises(ValueError):
            s.execute("insert into e values ('open', 'a', 'not json')")
        s.execute("insert into e values ('open', 'a', '[1,2]')")

    def test_null_always_allowed(self, s):
        s.execute("insert into e values (null, null, null)")

    def test_domains_persist(self, s, tmp_path):
        from tidb_tpu.storage.persist import load_catalog, save_catalog

        save_catalog(s.catalog, str(tmp_path / "snap"))
        cat2 = load_catalog(str(tmp_path / "snap"))
        s2 = Session(catalog=cat2)
        with pytest.raises(ValueError):
            s2.execute("insert into e values ('bogus', 'a', '{}')")


class TestJsonFunctions:
    def test_extract_nested(self, s):
        r = s.execute(
            "select json_extract(doc, '$.k[2].x') from e where st = 'open' "
            "and tags = 'a,c'"
        )
        assert r.rows == [('"y"',)]

    def test_unquote(self, s):
        r = s.execute(
            "select json_unquote(json_extract(doc, '$.k[2].x')) from e "
            "where tags = 'a,c'"
        )
        assert r.rows == [("y",)]

    def test_missing_path_is_null(self, s):
        r = s.execute(
            "select json_extract(doc, '$.nope') from e where tags = 'a,c'"
        )
        assert r.rows == [(None,)]

    def test_array_index(self, s):
        r = s.execute(
            "select json_extract(doc, '$[1]') from e where st = 'closed'"
        )
        assert r.rows == [("20",)]

    def test_type_valid_length(self, s):
        r = s.execute(
            "select json_type(doc), json_valid(doc), json_length(doc) "
            "from e where doc is not null order by json_type(doc)"
        )
        assert r.rows == [
            ("ARRAY", 1, 2), ("OBJECT", 1, 2), ("STRING", 1, 1),
        ]

    def test_filter_on_extract(self, s):
        r = s.execute(
            "select st from e where json_extract(doc, '$.k[0]') = '1'"
        )
        assert r.rows == [("open",)]

    def test_json_null_literal_vs_sql_null(self, s):
        r = s.execute(
            "select json_extract(doc, '$.n') from e where tags = 'a,c'"
        )
        assert r.rows == [("null",)]  # JSON null is the text 'null'


class TestCollate:
    @pytest.fixture()
    def c(self):
        s = Session()
        s.execute("create table c (v varchar(10))")
        s.execute("insert into c values ('Apple'), ('apple'), ('BANANA')")
        return s

    def test_ci_equality(self, c):
        assert c.execute(
            "select count(*) from c where v collate utf8mb4_general_ci = 'APPLE'"
        ).rows == [(2,)]
        assert c.execute("select count(*) from c where v = 'APPLE'").rows == [
            (0,)
        ]

    def test_ci_order(self, c):
        r = c.execute(
            "select v from c order by v collate utf8mb4_general_ci, v"
        )
        assert r.rows == [("Apple",), ("apple",), ("BANANA",)]

    def test_bin_collate_is_identity(self, c):
        assert c.execute(
            "select count(*) from c where v collate utf8mb4_bin = 'apple'"
        ).rows == [(1,)]

    def test_unknown_collation_rejected(self, c):
        with pytest.raises(Exception):
            c.execute("select v collate latin1_swedish_xx from c")


class TestReviewRegressions:
    def test_domains_survive_alter(self):
        s = Session()
        s.execute("create table t (st enum('open','closed'))")
        s.execute("alter table t add column x int")
        with pytest.raises(ValueError):
            s.execute("insert into t values ('bogus', 1)")
        s.execute("alter table t drop column x")
        with pytest.raises(ValueError):
            s.execute("insert into t values ('bogus')")

    def test_ci_like_in_between(self):
        s = Session()
        s.execute("create table c (v varchar(10))")
        s.execute("insert into c values ('Alice'), ('bob')")
        ci = "v collate utf8mb4_general_ci"
        assert s.execute(
            f"select count(*) from c where {ci} like 'ALICE'"
        ).rows == [(1,)]
        assert s.execute(
            f"select count(*) from c where {ci} in ('ALICE','X')"
        ).rows == [(1,)]
        assert s.execute(
            f"select count(*) from c where {ci} between 'AL' and 'AM'"
        ).rows == [(1,)]

    def test_json_multipath_rejected_and_length_path(self):
        s = Session()
        s.execute("create table j (doc json)")
        s.execute('insert into j values (\'{"a":1,"b":[1,2,3]}\')')
        with pytest.raises(Exception):
            s.execute("select json_extract(doc, '$.a', '$.b') from j")
        assert s.execute("select json_length(doc, '$.b') from j").rows == [(3,)]
