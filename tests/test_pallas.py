"""Pallas slot-table aggregation kernel (opt-in; interpret-mode tests).

The kernel runs in a SUBPROCESS because tests/conftest.py deregisters
non-CPU backend factories (to keep the TPU tunnel out of tests), which
breaks pallas's TPU-lowering registration at import time in this
process. A clean CPU child imports pallas fine and runs the kernel in
interpret mode against the float64 jnp oracle.
"""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = textwrap.dedent(
    """
    import sys; sys.path.insert(0, REPO_PATH)
    import tidb_tpu
    import numpy as np, jax.numpy as jnp
    from tidb_tpu.executor.pallas_kernels import (
        slot_sums_f32, slot_sums_reference,
    )

    rng = np.random.default_rng(7)
    for (A, N, S) in [(1, 100, 4), (4, 3000, 8), (10, 5000, 12), (2, 1024, 6)]:
        vals = jnp.asarray(rng.integers(0, 100, (A, N)).astype(np.float32))
        contrib = jnp.asarray(rng.random((A, N)) < 0.8)
        # seg includes the overflow slot S (dropped rows)
        seg = jnp.asarray(rng.integers(0, S + 1, N).astype(np.int32))
        got = slot_sums_f32(vals, contrib, seg, S, interpret=True)
        exp = slot_sums_reference(vals, contrib, seg, S).astype(jnp.float32)
        assert got.shape == (A, S), got.shape
        assert bool(jnp.allclose(got, exp, rtol=1e-6)), (A, N, S)
    # exact counting: values=1 contributions count rows per slot exactly
    ones = jnp.ones((1, 4096), jnp.float32)
    contrib = jnp.ones((1, 4096), bool)
    seg = jnp.asarray((np.arange(4096) % 3).astype(np.int32))
    got = slot_sums_f32(ones, contrib, seg, 3, interpret=True)
    assert got.tolist() == [[1366.0, 1365.0, 1365.0]], got.tolist()
    print("PALLAS_OK")
    """
)


def test_slot_sums_interpret_matches_oracle():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", CHILD.replace("REPO_PATH", repr(REPO))],
        capture_output=True, text=True, timeout=600, cwd="/tmp", env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "PALLAS_OK" in out.stdout


def test_disabled_by_default():
    from tidb_tpu.executor.pallas_kernels import pallas_enabled

    assert not pallas_enabled()


SQL_CHILD = textwrap.dedent(
    """
    import sys; sys.path.insert(0, REPO_PATH)
    import tidb_tpu
    from tidb_tpu.session.session import Session

    s = Session()
    s.execute("create table t (g int, v int, f double)")
    s.execute(
        "insert into t values "
        + ",".join(
            f"({i % 5},{i},{i / 4})" for i in range(2000)
        )
    )
    r = s.execute(
        "select g, count(*), sum(v), avg(f) from t group by g order by g"
    )
    exp = []
    for g in range(5):
        xs = [i for i in range(2000) if i % 5 == g]
        exp.append((g, len(xs), sum(xs), sum(i / 4 for i in xs) / len(xs)))
    for got, want in zip(r.rows, exp):
        assert got[0] == want[0] and got[1] == want[1], (got, want)
        assert abs(got[2] - want[2]) <= abs(want[2]) * 1e-6, (got, want)
        assert abs(got[3] - want[3]) <= abs(want[3]) * 1e-5, (got, want)
    print("PALLAS_SQL_OK")
    """
)


def test_enabled_path_through_sql():
    """TIDB_TPU_PALLAS=1 (+interpret escape hatch off-TPU) routes
    SUM/COUNT/AVG slot accumulation through the kernel; group results
    match the exact expectations within f32 tolerance."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["TIDB_TPU_PALLAS"] = "1"
    env["TIDB_TPU_PALLAS_INTERPRET"] = "1"
    out = subprocess.run(
        [sys.executable, "-c", SQL_CHILD.replace("REPO_PATH", repr(REPO))],
        capture_output=True, text=True, timeout=600, cwd="/tmp", env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "PALLAS_SQL_OK" in out.stdout


class TestPrefixSum:
    """Kernel #2: streaming prefix sum (interpret-mode parity; hardware
    validation rides scripts/pallas_validate.py at the next tunnel
    window). Same clean-child pattern as the slot-sum tests: the axon
    plugin breaks pallas lowering registration in-process."""

    CHILD2 = textwrap.dedent(
        """
        import sys; sys.path.insert(0, REPO_PATH)
        import tidb_tpu
        import numpy as np, jax.numpy as jnp
        from tidb_tpu.executor.pallas_kernels import (
            prefix_sum_i32, prefix_sum_reference,
        )

        rng = np.random.default_rng(11)
        for n in (100, 1024, 3001, 5000, 8192):
            x = jnp.asarray(rng.random(n) < 0.3)
            got = prefix_sum_i32(x, interpret=True)
            want = prefix_sum_reference(x)
            assert got.shape == want.shape, (got.shape, want.shape)
            assert (np.asarray(got) == np.asarray(want)).all(), n
        xi = jnp.asarray(rng.integers(0, 5, 3001).astype(np.int32))
        assert (
            np.asarray(prefix_sum_i32(xi, interpret=True))
            == np.asarray(prefix_sum_reference(xi))
        ).all()
        print("PREFIX_OK")
        """
    )

    def test_parity(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, "-c",
             self.CHILD2.replace("REPO_PATH", repr(REPO))],
            capture_output=True, text=True, timeout=600, cwd="/tmp",
            env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "PREFIX_OK" in out.stdout

    def test_dense_compaction_uses_kernel(self):
        # end-to-end in a clean child: dense-path GROUP BY compacts
        # identically with the Pallas scan (interpret) and jnp
        child = textwrap.dedent(
            """
            import sys; sys.path.insert(0, REPO_PATH)
            import os
            import tidb_tpu
            from tidb_tpu.session import Session

            def run():
                s = Session()
                s.execute("create table t (k int, v int)")
                rows = ", ".join(f"({i % 97}, {i})" for i in range(500))
                s.execute(f"insert into t values {rows}")
                return s.execute(
                    "select k, sum(v) from t group by k order by k"
                ).rows

            base = run()
            os.environ["TIDB_TPU_PALLAS"] = "1"
            os.environ["TIDB_TPU_PALLAS_INTERPRET"] = "1"
            assert run() == base
            print("COMPACT_OK")
            """
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, "-c", child.replace("REPO_PATH", repr(REPO))],
            capture_output=True, text=True, timeout=600, cwd="/tmp",
            env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "COMPACT_OK" in out.stdout
