"""Pessimistic transactions: blocking table locks, SELECT ... FOR
UPDATE, deadlock detection.

Reference: the pessimistic txn path takes locks per DML statement and
blocks conflicting writers (pkg/session/txn.go:50, LockKeys in
pkg/store/driver/txn/txn_driver.go); the wait-for-graph deadlock
detector aborts one member of a cycle
(pkg/store/mockstore/unistore/tikv/detector.go). VERDICT round-2 item
#4: interleaved writers must serialize instead of aborting.
"""

import threading
import time

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture()
def cat():
    c = Catalog()
    s = Session(c, db="test")
    s.execute("create table acc (id int primary key, bal int)")
    s.execute("insert into acc values (1, 100), (2, 200)")
    return c


def _bg(fn):
    th = threading.Thread(target=fn, daemon=True)
    th.start()
    return th


def test_blocked_writer_serializes(cat):
    s1, s2 = Session(cat), Session(cat)
    s1.execute("begin")
    s1.execute("update acc set bal = bal + 10 where id = 1")
    done = []

    def w2():
        s2.execute("begin")
        s2.execute("update acc set bal = bal + 5 where id = 1")
        s2.execute("commit")
        done.append(1)

    th = _bg(w2)
    time.sleep(0.5)
    assert not done, "conflicting writer must block, not abort"
    s1.execute("commit")
    th.join(timeout=15)
    assert done, "blocked writer must resume after the lock releases"
    # both updates applied -> no lost update, no write-conflict abort
    r = s1.execute("select bal from acc where id = 1")
    assert r.rows == [(115,)]


def test_select_for_update_blocks_writer(cat):
    s1, s2 = Session(cat), Session(cat)
    s1.execute("begin")
    assert s1.execute("select bal from acc where id = 2 for update").rows == [
        (200,)
    ]
    t0 = time.monotonic()
    done = []

    def w2():
        s2.execute("update acc set bal = 0 where id = 2")  # autocommit
        done.append(time.monotonic() - t0)

    th = _bg(w2)
    time.sleep(0.4)
    assert not done
    s1.execute("commit")
    th.join(timeout=15)
    assert done and done[0] >= 0.3


def test_deadlock_detected_and_victim_rolled_back(cat):
    s1, s2 = Session(cat), Session(cat)
    s1.execute("create table b (id int primary key, v int)")
    s1.execute("insert into b values (1, 1)")
    s1.execute("begin")
    s1.execute("update acc set bal = bal + 1 where id = 1")
    s2.execute("begin")
    s2.execute("update b set v = v + 1 where id = 1")
    errs = []

    def w2():
        try:
            s2.execute("update acc set bal = bal + 1 where id = 2")
            s2.execute("commit")
        except Exception as e:
            errs.append(str(e))

    th = _bg(w2)
    time.sleep(0.4)
    deadlocked = False
    try:
        s1.execute("update b set v = v + 1 where id = 1")  # closes cycle
        s1.execute("commit")
    except Exception as e:
        deadlocked = "Deadlock" in str(e)
    th.join(timeout=20)
    assert deadlocked or any("Deadlock" in e for e in errs)
    # the victim's txn was rolled back; survivors can proceed
    s3 = Session(cat)
    s3.execute("update b set v = 100 where id = 1")
    assert s3.execute("select v from b").rows == [(100,)]


def test_lock_wait_timeout(cat):
    s1, s2 = Session(cat), Session(cat)
    s1.execute("begin")
    s1.execute("update acc set bal = 1 where id = 1")
    s2.execute("set innodb_lock_wait_timeout = 1")
    t0 = time.monotonic()
    with pytest.raises(Exception, match="Lock wait timeout"):
        s2.execute("update acc set bal = 2 where id = 1")
    assert time.monotonic() - t0 < 10
    s1.execute("rollback")


def test_autocommit_writers_no_lost_update(cat):
    """Concurrent single-statement UPDATEs (read-modify-write) must all
    apply — the statement-scoped lock closes the race the optimistic
    path left open for autocommit writers."""
    sessions = [Session(cat) for _ in range(4)]
    n_each = 5

    def w(s):
        for _ in range(n_each):
            s.execute("update acc set bal = bal + 1 where id = 2")

    threads = [_bg(lambda s=s: w(s)) for s in sessions]
    for th in threads:
        th.join(timeout=60)
    r = sessions[0].execute("select bal from acc where id = 2")
    assert r.rows == [(200 + 4 * n_each,)]


def test_optimistic_mode_still_aborts(cat):
    s1, s2 = Session(cat), Session(cat)
    for s in (s1, s2):
        s.execute("set tidb_txn_mode = 'optimistic'")
    s1.execute("begin")
    s1.execute("update acc set bal = 1 where id = 1")
    s2.execute("update acc set bal = 2 where id = 1")  # wins immediately
    with pytest.raises(RuntimeError, match="conflict"):
        s1.execute("commit")
