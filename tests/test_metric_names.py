"""Tier-1 gate for scripts/check_metric_names.py: every metric
registered on the global REGISTRY follows tidbtpu_<subsystem>_<name>
(dashboards and BENCH metric snapshots key on these names)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "check_metric_names.py")


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, LINT, REPO], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        f"metric-name violations:\n{proc.stdout}{proc.stderr}"
    )


def test_lint_catches_violations(tmp_path):
    pkg = tmp_path / "tidb_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'from tidb_tpu.utils.metrics import REGISTRY\n'
        'REGISTRY.counter("tidb_tpu_old_style_total").inc()\n'   # bad prefix
        'REGISTRY.gauge(\n'
        '    "noprefix_gauge", "help"\n'                          # bad, multiline
        ').set(1)\n'
        'REGISTRY.histogram("tidbtpu_engine_good_seconds").observe(1)\n'
        # well-formed but the subsystem token is not in the declared
        # SUBSYSTEMS registry (the PR 6 vocabulary lint)
        'REGISTRY.counter("tidbtpu_flights_undeclared_total").inc()\n'
        'REGISTRY.counter("tidbtpu_link_frames_total").inc()\n'   # declared
    )
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_y.py").write_text(
        'REGISTRY.counter("anything_goes_in_tests")\n'
    )
    proc = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "tidb_tpu_old_style_total" in proc.stdout
    assert "noprefix_gauge" in proc.stdout
    assert "tidbtpu_engine_good_seconds" not in proc.stdout
    assert "tidbtpu_flights_undeclared_total" in proc.stdout
    assert "undeclared subsystem" in proc.stdout
    assert "tidbtpu_link_frames_total" not in proc.stdout
    assert "test_y.py" not in proc.stdout  # tests/ exempt
