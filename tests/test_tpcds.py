"""TPC-DS Q95 — benchmark ladder config #5 (BASELINE.md).

Exercises the full pushdown stack at once: a self-join CTE, two IN
subqueries (one over a join), COUNT(DISTINCT), a date window with
interval arithmetic, and a 4-way join. Verified against a pure-numpy
oracle over the same generated data, single-device and mesh.
"""

from tidb_tpu.bench.tpcds import Q95_SQL, load_tpcds, numpy_q95
from tidb_tpu.session.session import Session


def _check(sess):
    r = sess.execute(Q95_SQL)
    exp = numpy_q95(sess.catalog)
    got = r.rows[0] if r.rows else (0, None, None)
    assert got[0] == exp[0]
    if exp[0]:
        assert abs(got[1] - exp[1]) < 0.01
        assert abs(got[2] - exp[2]) < 0.01
    return exp


def test_q95_matches_oracle():
    s = Session()
    load_tpcds(s.catalog, sf=0.08)
    exp = _check(s)
    assert exp[0] > 0  # selective but non-empty at this scale


def test_q95_empty_result_shape():
    s = Session()
    load_tpcds(s.catalog, sf=0.005, seed=3)
    r = s.execute(Q95_SQL)
    # scalar aggregate over empty input: COUNT=0, sums NULL
    assert r.rows[0][0] == 0


def test_q95_mesh_parity():
    s1 = Session()
    load_tpcds(s1.catalog, sf=0.04)
    sm = Session(mesh_devices=8)
    load_tpcds(sm.catalog, sf=0.04)
    assert s1.execute(Q95_SQL).rows == sm.execute(Q95_SQL).rows
