"""Fleet timeline tracer (PR 9): recorder semantics, Chrome trace
export, cross-host merge/rebase, XLA compile cost analysis, the
declared-category lint, worker-reported admission peaks, and the
Tracer.add_remote relative-depth fix.

The 2-process trace (trace validity, clock-offset monotonicity,
pipelined-vs-barrier overlap) lives in tests/test_multihost.py; this
file covers everything testable in-process.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "check_timeline_events.py")


def _reset_recorder():
    """Stop, clear AND restore the default ring size — a test that
    shrank the ring (capacity tests, the /timeline?capacity endpoint)
    must not leave later tests evicting their own events."""
    from tidb_tpu.obs.timeline import TIMELINE

    TIMELINE.start(capacity=65536)
    TIMELINE.stop()
    TIMELINE.clear()


@pytest.fixture()
def timeline():
    """A started recorder (default-sized ring), fully reset afterwards
    so the capture (and its cost-analysis harvesting side effect)
    never leaks into other tests."""
    from tidb_tpu.obs import engine_watch
    from tidb_tpu.obs.timeline import TIMELINE

    TIMELINE.stop()
    TIMELINE.clear()
    TIMELINE.start(capacity=65536)
    try:
        yield TIMELINE
    finally:
        _reset_recorder()
        engine_watch.set_cost_analysis(False)


# ---------------------------------------------------------------------------
# recorder semantics
# ---------------------------------------------------------------------------


class TestRecorder:
    def test_undeclared_category_rejected_even_when_inactive(self):
        from tidb_tpu.obs.timeline import TIMELINE, TimelineBuffer

        assert not TIMELINE.active() or True  # state-independent check
        with pytest.raises(ValueError, match="undeclared timeline"):
            TIMELINE.emit_event("no-such-cat", "x", 0.0, 1.0)
        with pytest.raises(ValueError, match="undeclared timeline"):
            TIMELINE.emit_counter("no-such-cat", "x", 1.0)
        with pytest.raises(ValueError, match="undeclared timeline"):
            TimelineBuffer().emit_event("no-such-cat", "x", 0.0, 1.0)

    def test_inactive_recorder_drops_events(self):
        from tidb_tpu.obs.timeline import TIMELINE

        TIMELINE.stop()
        TIMELINE.clear()
        TIMELINE.emit_event("phase", "parse", time.time(), 0.1)
        assert len(TIMELINE) == 0

    def test_ring_bound(self, timeline):
        timeline.start(capacity=32)
        for i in range(100):
            timeline.emit_event("phase", f"e{i}", time.time(), 0.001)
        assert len(timeline) == 32
        # newest kept
        names = [e[2] for e in timeline.events()]
        assert names[-1] == "e99" and names[0] == "e68"

    def test_dump_is_valid_chrome_trace(self, timeline):
        t0 = time.time()
        timeline.emit_event(
            "statement", "select 1", t0, 0.25, track="conn-7",
            args={"qid": 1},
        )
        timeline.emit_event(
            "fragment", "execute q1/f0", t0 + 0.05, 0.1,
            host="worker-a:9000", track="q1/f0",
        )
        timeline.emit_counter("counter", "tidbtpu_admission_queue_depth", 3)
        trace = json.loads(timeline.dump_json())
        evs = trace["traceEvents"]
        # process metadata for both hosts, thread metadata for tracks
        procs = {
            e["args"]["name"]: e["pid"]
            for e in evs if e["ph"] == "M" and e["name"] == "process_name"
        }
        assert procs["coordinator"] == 1
        assert "worker-a:9000" in procs and procs["worker-a:9000"] != 1
        threads = [
            e for e in evs if e["ph"] == "M" and e["name"] == "thread_name"
        ]
        assert {t["args"]["name"] for t in threads} >= {"conn-7", "q1/f0"}
        xs = [e for e in evs if e["ph"] == "X"]
        assert len(xs) == 2
        for e in xs:
            assert e["ts"] >= 0 and e["dur"] > 0  # microseconds
        cs = [e for e in evs if e["ph"] == "C"]
        assert len(cs) == 1 and cs[0]["args"]["value"] == 3.0
        # the statement event window is where we put it (µs precision)
        stmt = next(e for e in xs if e["cat"] == "statement")
        assert abs(stmt["dur"] - 0.25e6) < 1.0

    def test_merge_remote_rebases_and_drops_malformed(self, timeline):
        from tidb_tpu.obs.timeline import TimelineBuffer

        buf = TimelineBuffer()
        t_worker = time.time() + 5.0  # worker clock runs 5s ahead
        buf.emit_event("shuffle", "produce#0", t_worker, 0.1, track="q1/p0")
        n = timeline.merge_remote(
            buf.events + [["bogus-cat", "x", 0, 0, "", None], ["short"]],
            host="w:1", offset_s=5.0,
        )
        assert n == 1  # malformed records dropped, not raised
        ev = [e for e in timeline.events() if e[1] == "shuffle"][0]
        # rebased back onto the coordinator clock: offset removed
        assert abs(ev[3] - (t_worker - 5.0)) < 1e-6
        assert ev[5] == "w:1"

    def test_buffer_bound(self):
        from tidb_tpu.obs.timeline import TimelineBuffer

        buf = TimelineBuffer(capacity=8)
        for i in range(20):
            buf.emit_event("shuffle", f"e{i}", 0.0, 1.0)
        assert len(buf.events) == 8

    def test_overlap_report_math(self):
        from tidb_tpu.obs.timeline import (
            _window_overlap,
            shuffle_overlap_report,
        )

        assert _window_overlap([(0.0, 1.0)], [(0.5, 1.0)]) == pytest.approx(0.5)
        assert _window_overlap([(0.0, 1.0)], [(2.0, 1.0)]) == 0.0
        # two overlapping pairs over the same region: not double-counted
        assert _window_overlap(
            [(0.0, 1.0), (0.2, 0.8)], [(0.5, 1.0)]
        ) == pytest.approx(0.5)
        events = [
            ("X", "shuffle", "produce#0", 0.0, 1.0, "w", "q1/p0",
             {"pipeline": True}),
            ("X", "shuffle", "push#0", 0.6, 1.0, "w", "q1/p0",
             {"pipeline": True}),
            ("X", "shuffle", "produce#0", 10.0, 1.0, "w", "q2/p0",
             {"pipeline": False}),
            ("X", "shuffle", "push#0", 11.5, 1.0, "w", "q2/p0",
             {"pipeline": False}),
        ]
        rep = shuffle_overlap_report(events)
        assert rep["w/q1/p0"]["pipeline"] is True
        assert rep["w/q1/p0"]["produce_push_overlap_s"] == pytest.approx(0.4)
        assert rep["w/q2/p0"]["produce_push_overlap_s"] == 0.0

    def test_sample_gauges_emits_declared_counter_tracks(self, timeline):
        from tidb_tpu.utils.metrics import REGISTRY

        REGISTRY.gauge(
            "tidbtpu_admission_queue_depth", "queries waiting for admission"
        ).set(7)
        timeline.sample_gauges()
        cs = [e for e in timeline.events() if e[0] == "C"]
        assert any(
            e[2] == "tidbtpu_admission_queue_depth" and e[4] == 7.0
            for e in cs
        )


# ---------------------------------------------------------------------------
# XLA compile cost analysis
# ---------------------------------------------------------------------------


class TestCompileCost:
    def test_watched_jit_harvests_cost_once_per_sig(self, timeline):
        import jax.numpy as jnp

        from tidb_tpu.obs import engine_watch as ew

        sig = ("test-cost", time.time())  # unique per run
        calls = []
        orig = ew._harvest_cost

        def counting(j, a, k):
            calls.append(1)
            return orig(j, a, k)

        ew._harvest_cost = counting
        try:
            j = ew.watched_jit(lambda x: (x * 2 + 1).sum(), sig=sig)
            j(jnp.arange(16.0))
            j(jnp.arange(16.0))          # cache hit: no trace
            j(jnp.arange(32.0))          # retrace: cached cost reused
        finally:
            ew._harvest_cost = orig
        assert len(calls) == 1
        cost = ew.ENGINE_WATCH.cost_for_sig(sig)
        assert cost and cost["flops"] > 0 and cost["bytes_accessed"] > 0
        # the compile landed as a timeline event carrying the cost
        compiles = [
            e for e in timeline.events() if e[1] == "compile" and e[7]
        ]
        assert any(
            (e[7].get("cost_analysis") or {}).get("flops", 0) > 0
            for e in compiles
        )

    def test_no_harvest_when_disabled(self):
        import jax.numpy as jnp

        from tidb_tpu.obs import engine_watch as ew
        from tidb_tpu.obs.timeline import TIMELINE

        TIMELINE.stop()
        ew.set_cost_analysis(False)
        assert not ew.cost_analysis_enabled()
        sig = ("test-cost-off", time.time())
        j = ew.watched_jit(lambda x: x + 1, sig=sig)
        j(jnp.arange(4.0))
        assert ew.ENGINE_WATCH.cost_for_sig(sig) is None

    def test_extract_cost_keys_is_key_guarded(self):
        from tidb_tpu.obs.engine_watch import extract_cost_keys

        # CPU lowered-analysis shape
        cpu = {"flops": 23.0, "bytes accessed": 304.0,
               "bytes accessedout{}": 132.0, "utilization0{}": 5.0}
        assert extract_cost_keys(cpu) == {
            "flops": 23.0, "bytes_accessed": 304.0, "output_bytes": 132.0,
        }
        # TPU compiled-analysis shape: a list, different key spelling
        tpu = [{"flops": 9.0, "bytes accessed output": 8.0}]
        assert extract_cost_keys(tpu) == {
            "flops": 9.0, "output_bytes": 8.0,
        }
        # garbage in, empty out — never raises
        assert extract_cost_keys(None) == {}
        assert extract_cost_keys([]) == {}
        assert extract_cost_keys({"flops": float("nan")}) == {}
        assert extract_cost_keys({"flops": "x"}) == {}

    def test_cost_lands_in_statements_summary_and_tpu_engine(self, timeline):
        from tidb_tpu.session import Session
        from tidb_tpu.utils.metrics import STMT_SUMMARY, sql_digest

        s = Session()
        s.execute("create table tcost (a int, b int)")
        s.execute("insert into tcost values (1,2),(3,4),(5,6)")
        q = "select sum(a * b + 1) from tcost where a > 0"
        r = s.must_query(q)
        assert r.rows == [(1 * 2 + 3 * 4 + 5 * 6 + 3,)]
        ent = next(
            e for e in STMT_SUMMARY.rows_full()
            if e["digest_text"] == sql_digest(q)
        )
        assert ent["compile_flops"] > 0
        assert ent["compile_bytes_accessed"] > 0
        # the SQL surface exposes the columns
        r = s.must_query(
            "select compile_flops, compile_bytes_accessed from"
            " information_schema.statements_summary where digest_text ="
            f" '{sql_digest(q)}'"
        )
        assert r.rows[0][0] > 0 and r.rows[0][1] > 0
        r = s.must_query(
            "select compile_flops from information_schema.tpu_engine"
            " where compile_flops > 0"
        )
        assert len(r.rows) >= 1

    def test_explain_analyze_compile_row(self):
        from tidb_tpu.obs.engine_watch import ENGINE_WATCH
        from tidb_tpu.session.session import _compile_cost_lines

        ENGINE_WATCH.begin_query("test-explain-cost")
        try:
            ENGINE_WATCH.note_compile_cost(
                ("ea", 1), {"flops": 123.0, "bytes_accessed": 456.0},
            )
            ENGINE_WATCH.current().jit_compilations = 2
            (line,) = _compile_cost_lines()
            assert line.startswith("XLACompile compiles=2")
            assert "flops=123" in line and "bytes_accessed=456" in line
        finally:
            ENGINE_WATCH.end_query(0.0)
        # no record open -> no row (a warm run reports nothing)
        assert _compile_cost_lines() == []

    def test_frag_stats_compile_suffix(self):
        from tidb_tpu.planner.physical import _compile_cost_suffix

        frags = [
            {"compile": {"flops": 10.0, "bytes_accessed": 100.0}},
            {"compile": None},
            {},
        ]
        s = _compile_cost_suffix(frags)
        assert "compile_flops=10" in s and "compile_bytes_accessed=100" in s
        assert _compile_cost_suffix([{}, {"compile": None}]) == ""


# ---------------------------------------------------------------------------
# sysvar + endpoint surfaces
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_sysvar_starts_and_stops_capture(self):
        from tidb_tpu.obs.timeline import TIMELINE
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table tv (a int)")
        s.execute("insert into tv values (1),(2)")
        try:
            s.execute("set tidb_timeline_capture = 1")
            assert TIMELINE.active()
            s.must_query("select sum(a) from tv")
            s.execute("set tidb_timeline_capture = 0")
            assert not TIMELINE.active()
            cats = {e[1] for e in TIMELINE.events()}
            # the statement span and its phase charges were captured
            assert "statement" in cats and "phase" in cats
        finally:
            _reset_recorder()

    def test_http_timeline_endpoint(self):
        import urllib.request

        from tidb_tpu.obs.timeline import TIMELINE
        from tidb_tpu.server.http_status import StatusServer
        from tidb_tpu.storage import Catalog

        http = StatusServer(Catalog(), port=0)
        http.start_background()
        try:
            def get(path):
                return json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}{path}", timeout=10
                ).read().decode())

            st = get("/timeline/start?capacity=128")
            assert st["active"] is True
            TIMELINE.emit_event("phase", "parse", time.time(), 0.01)
            trace = get("/timeline")
            assert any(
                e.get("ph") == "X" and e.get("name") == "parse"
                for e in trace["traceEvents"]
            )
            st = get("/timeline/stop")
            assert st["active"] is False and st["events"] >= 1
        finally:
            _reset_recorder()
            http.shutdown()

    def test_admission_controller_from_sysvars(self):
        from tidb_tpu.parallel.serving import AdmissionController
        from tidb_tpu.utils.sysvar import SysVars

        sv = SysVars({})
        sv.set("tidb_tpu_admission_budget_bytes", 123 << 20, "global")
        sv.set("tidb_tpu_admission_queue_limit", 7, "global")
        sv.set("tidb_tpu_admission_starvation_s", 2.5, "global")
        adm = AdmissionController.from_sysvars(sv, queue_timeout_s=1.0)
        assert adm.budget_bytes == 123 << 20
        assert adm.max_queue == 7
        assert adm.starvation_s == 2.5
        assert adm.queue_timeout_s == 1.0
        # defaults flow when nothing is set
        adm2 = AdmissionController.from_sysvars(SysVars({}))
        assert adm2.budget_bytes == 2 << 30 and adm2.max_queue == 256

    def test_set_admission_sysvar_retunes_attached_controller(self):
        from tidb_tpu.parallel.serving import AdmissionController
        from tidb_tpu.session import Session

        class _Sched:
            admission = AdmissionController()

        s = Session()
        s.attach_dcn_scheduler(_Sched())
        try:
            s.execute(f"set tidb_tpu_admission_budget_bytes = {64 << 20}")
            assert _Sched.admission.budget_bytes == 64 << 20
            s.execute("set tidb_tpu_admission_queue_limit = 3")
            assert _Sched.admission.max_queue == 3
            s.execute("set tidb_tpu_admission_starvation_s = 1.5")
            assert _Sched.admission.starvation_s == 1.5
        finally:
            s.attach_dcn_scheduler(None)


# ---------------------------------------------------------------------------
# worker-reported device-mem peaks (ROADMAP PR 8 item)
# ---------------------------------------------------------------------------


class TestWorkerPeaks:
    def test_fragment_reply_carries_worker_peak(self):
        """An in-process EngineServer's fragment reply ships the
        worker's OWN engine-watch device-mem high-water (and the
        scheduler folds the max into its per-query snapshot)."""
        from tidb_tpu.parallel.dcn import DCNFragmentScheduler
        from tidb_tpu.parser.sqlparse import parse
        from tidb_tpu.planner.logical import build_query
        from tidb_tpu.server.engine_rpc import EngineServer
        from tidb_tpu.session import Session

        sess = Session()
        sess.execute("create table tw (a int, b int)")
        sess.execute(
            "insert into tw values " + ",".join(
                f"({i},{i % 5})" for i in range(64)
            )
        )
        servers = [EngineServer(sess.catalog, port=0) for _ in range(2)]
        for srv in servers:
            srv.start_background()
        sched = DCNFragmentScheduler(
            [("127.0.0.1", srv.port) for srv in servers],
            catalog=sess.catalog,
        )
        try:
            q = "select b, count(*), sum(a) from tw group by b order by b"
            plan = build_query(
                parse(q)[0], sess.catalog, "test", sess._scalar_subquery
            )
            exp = sess.must_query(q).rows
            _cols, got = sched.execute_plan(plan)
            assert got == exp
            lq = sched.last_query_mine()
            assert lq["worker_mem_peak"] > 0
            assert all(
                f["mem_peak"] > 0 for f in lq["fragments"]
            )
        finally:
            sched.close()
            for srv in servers:
                srv.shutdown()

    def test_worker_heavier_plan_raises_learned_estimate(self):
        """The admission estimate learns max(coordinator peak, worker
        peaks): a plan whose workers see a bigger working set than the
        coordinator's final stage must not under-estimate."""
        from tidb_tpu.parallel.dcn import DCNFragmentScheduler
        from tidb_tpu.parallel.serving import AdmissionController

        adm = AdmissionController(default_estimate_bytes=1 << 20)
        # coordinator-eyed release (the pre-PR 9 behavior): 2 MiB
        t = adm.admit("shape-x")
        t.release(observed_bytes=2 << 20)
        assert adm.estimate("shape-x") == 2 << 20
        # the same shape reports a worker-eyed 32 MiB peak: the
        # session releases max(coordinator, worker) — the estimate
        # RISES to the fleet-eyed number
        infos = [
            {"mem_peak": 32 << 20}, {"mem_peak": 8 << 20},
        ]
        worker_peak = DCNFragmentScheduler._worker_mem_peak(infos)
        assert worker_peak == 32 << 20
        t = adm.admit("shape-x")
        t.release(observed_bytes=max(2 << 20, worker_peak))
        assert adm.estimate("shape-x") == 32 << 20


# ---------------------------------------------------------------------------
# Tracer.add_remote relative depth (satellite fix)
# ---------------------------------------------------------------------------


class TestAddRemoteDepth:
    def test_two_level_worker_span_stays_nested(self):
        from tidb_tpu.utils.tracing import Span, Tracer

        tr = Tracer()
        tr.enabled = True
        tr.reset()
        # a worker whose handler nested spans ships depths 2 and 3;
        # the old clamp kept them ABSOLUTE (phantom parents in the
        # merged trace) — relative depth under the host label is what
        # must survive
        tr.add_remote(
            [("outer", 0.0, 1.0, 2), ("inner", 0.1, 0.5, 3)], "w1"
        )
        d = {s.name: s.depth for s in tr.spans}
        assert d["w1:outer"] == 1
        assert d["w1:inner"] == 2
        rows = tr.rows()
        assert rows[0][0] == "w1:outer"           # no indent
        assert rows[1][0] == "  w1:inner"         # nested one level

    def test_flat_span_and_span_objects(self):
        from tidb_tpu.utils.tracing import Span, Tracer

        tr = Tracer()
        tr.add_remote([Span("only", 0.0, 1.0, 4)], "w2", base_s=2.0)
        (s,) = tr.spans
        assert s.depth == 1 and s.start_s == 2.0
        tr.add_remote([], "w3")  # empty list: no-op, no crash

    def test_base_depth_offsets_whole_group(self):
        from tidb_tpu.utils.tracing import Tracer

        tr = Tracer()
        tr.add_remote(
            [("a", 0.0, 1.0, 1), ("b", 0.0, 0.5, 2)], "w", base_depth=3
        )
        d = {s.name: s.depth for s in tr.spans}
        assert d["w:a"] == 3 and d["w:b"] == 4


# ---------------------------------------------------------------------------
# the declared-category lint (tier-1 gate for check_timeline_events.py)
# ---------------------------------------------------------------------------


def run_lint(root):
    return subprocess.run(
        [sys.executable, LINT, str(root)],
        capture_output=True, text=True, timeout=120,
    )


def _fixture_tree(tmp_path, categories, body):
    obs = tmp_path / "tidb_tpu" / "obs"
    obs.mkdir(parents=True)
    (obs / "timeline.py").write_text(
        f"EVENT_CATEGORIES = {categories!r}\n"
    )
    (tmp_path / "tidb_tpu" / "engine.py").write_text(
        textwrap.dedent(body)
    )
    return tmp_path


class TestTimelineLint:
    def test_clean_at_head(self):
        proc = run_lint(REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_undeclared_category_rejected(self, tmp_path):
        root = _fixture_tree(
            tmp_path, ("phase",),
            """
            def f(tl):
                tl.emit_event("phase", "x", 0.0, 1.0)
                tl.emit_event("mystery", "y", 0.0, 1.0)
            """,
        )
        proc = run_lint(root)
        assert proc.returncode == 1
        assert "undeclared timeline category 'mystery'" in proc.stdout

    def test_dead_declaration_rejected(self, tmp_path):
        root = _fixture_tree(
            tmp_path, ("phase", "ghost"),
            """
            def f(tl):
                tl.emit_counter("phase", "x", 1.0)
            """,
        )
        proc = run_lint(root)
        assert proc.returncode == 1
        assert "'ghost' has no" in proc.stdout

    def test_clean_fixture_passes(self, tmp_path):
        root = _fixture_tree(
            tmp_path, ("phase", "stall"),
            """
            def f(tl):
                tl.emit_event("phase", "x", 0.0, 1.0)
                tl.emit_counter("stall", "y", 2.0)
            """,
        )
        proc = run_lint(root)
        assert proc.returncode == 0, proc.stdout
