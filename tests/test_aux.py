"""Aux subsystem tests: sysvars, tracing, transactions, failpoints,
ANALYZE, LOAD DATA (reference: pkg/sessionctx/variable tests, txntest,
failpoint-enabled tests, statistics tests)."""

import math
import os
import tempfile

import pytest

from tidb_tpu.session import Session
from tidb_tpu.utils import failpoint


@pytest.fixture()
def s():
    sess = Session()
    sess.execute("create table t (a bigint, b varchar(10))")
    sess.execute("insert into t values (1, 'x'), (2, 'y'), (2, 'z')")
    return sess


class TestSysVars:
    def test_set_and_select(self, s):
        s.execute("set tidb_mem_quota_query = 1073741824")
        r = s.must_query("select @@tidb_mem_quota_query")
        assert r.rows == [(1073741824,)]

    def test_global_vs_session(self, s):
        s2 = Session(s.catalog)
        s.execute("set global tidb_tpu_group_capacity = 2048")
        assert s2.must_query("select @@tidb_tpu_group_capacity").rows == [(2048,)]
        s2.execute("set tidb_tpu_group_capacity = 512")
        assert s2.must_query("select @@tidb_tpu_group_capacity").rows == [(512,)]
        assert s.must_query("select @@tidb_tpu_group_capacity").rows == [(2048,)]

    def test_validation(self, s):
        with pytest.raises(Exception):
            s.execute("set tidb_mem_quota_query = 1")
        with pytest.raises(Exception):
            s.execute("set version = 'nope'")

    def test_show_variables_like(self, s):
        r = s.must_query("show variables like 'tidb_tpu%'")
        names = [row[0] for row in r.rows]
        assert "tidb_tpu_min_tile" in names and "tidb_tpu_group_capacity" in names

    def test_tableless_select(self, s):
        r = s.must_query("select 1 + 2, 'const' = 'const', @@version_comment")
        assert r.rows[0][0] == 3


class TestTrace:
    def test_trace_select(self, s):
        r = s.execute("trace select count(*) from t")
        ops = [row[0].strip() for row in r.rows]
        assert any("plan" in o for o in ops)
        assert any("run" in o or "execute" in o for o in ops)


class TestTxn:
    def test_read_own_writes_and_commit(self, s):
        s.execute("begin")
        s.execute("insert into t values (9, 'w')")
        assert s.must_query("select count(*) from t").rows == [(4,)]
        # another session must not see it yet
        s2 = Session(s.catalog)
        assert s2.must_query("select count(*) from t").rows == [(3,)]
        s.execute("commit")
        assert s2.must_query("select count(*) from t").rows == [(4,)]

    def test_rollback(self, s):
        s.execute("begin")
        s.execute("delete from t where a = 1")
        assert s.must_query("select count(*) from t").rows == [(2,)]
        s.execute("rollback")
        assert s.must_query("select count(*) from t").rows == [(3,)]

    def test_repeatable_read(self, s):
        s.execute("begin")
        assert s.must_query("select count(*) from t").rows == [(3,)]
        s2 = Session(s.catalog)
        s2.execute("insert into t values (7, 'q')")
        # snapshot: still 3 inside the txn
        assert s.must_query("select count(*) from t").rows == [(3,)]
        s.execute("commit")
        assert s.must_query("select count(*) from t").rows == [(4,)]

    def test_write_conflict(self, s):
        # optimistic mode: first committer wins, second aborts (the
        # pessimistic default would make s2 BLOCK on s's table lock)
        s.execute("set tidb_txn_mode = 'optimistic'")
        try:
            s.execute("begin")
            s.execute("insert into t values (5, 'c')")
            s2 = Session(s.catalog)
            s2.execute("set tidb_txn_mode = 'optimistic'")
            s2.execute("insert into t values (6, 'd')")
            with pytest.raises(RuntimeError, match="conflict"):
                s.execute("commit")
        finally:
            s.execute("set tidb_txn_mode = 'pessimistic'")


class TestFailpoint:
    def test_inject_error(self, s):
        failpoint.enable("session/before-commit", RuntimeError("boom"))
        try:
            s.execute("begin")
            s.execute("insert into t values (8, 'f')")
            with pytest.raises(RuntimeError, match="boom"):
                s.execute("commit")
        finally:
            failpoint.disable_all()


class TestAnalyze:
    def test_analyze_table(self, s):
        s.execute("analyze table t")
        t = s.catalog.table("test", "t")
        st = t.stats["a"]
        assert st.row_count == 3 and st.ndv == 2 and st.null_count == 0
        assert st.min_val == 1 and st.max_val == 2
        top = dict(t.stats["b"].topn)
        assert top == {"x": 1, "y": 1, "z": 1}

    def test_analyze_with_nulls(self, s):
        s.execute("insert into t values (null, null)")
        s.execute("analyze table t")
        st = s.catalog.table("test", "t").stats["a"]
        assert st.null_count == 1 and st.ndv == 2


class TestLoadData:
    def test_load_tsv(self, s):
        with tempfile.NamedTemporaryFile("w", suffix=".tsv", delete=False) as f:
            f.write("10\thello\n11\tworld\n\\N\tnullrow\n")
            path = f.name
        try:
            r = s.execute(f"load data infile '{path}' into table t")
            assert r.affected == 3
            rows = s.must_query("select a, b from t where b in ('hello','world','nullrow') order by b").rows
            assert rows == [(10, "hello"), (None, "nullrow"), (11, "world")]
        finally:
            os.unlink(path)

    def test_load_pipe_sep(self, s):
        with tempfile.NamedTemporaryFile("w", suffix=".tbl", delete=False) as f:
            f.write("20|pipe|\n")  # dbgen trailing separator
            path = f.name
        try:
            r = s.execute(
                f"load data infile '{path}' into table t fields terminated by '|'"
            )
            assert r.affected == 1
            assert s.must_query("select a from t where b = 'pipe'").rows == [(20,)]
        finally:
            os.unlink(path)


class TestExplainAnalyze:
    def test_explain_analyze(self, s):
        r = s.execute("explain analyze select b, count(*) from t group by b")
        text = "\n".join(row[0] for row in r.rows)
        assert "Aggregate" in text and "rows=" in text and "time=" in text


class TestNativeLoader:
    def test_native_vs_python(self, s):
        """Native C++ loader produces identical results to the Python path."""
        from tidb_tpu.storage import native as nat

        if nat._load() is None:
            pytest.skip("native loader unavailable")
        sess = Session()
        sess.execute(
            "create table n (i bigint, f double, s varchar(20), d date, "
            "m decimal(10,2), b boolean)"
        )
        with tempfile.NamedTemporaryFile("w", suffix=".tbl", delete=False) as f:
            f.write("1|1.5|abc|1994-01-01|12.345|1|\n")
            f.write("-2|\\N|x y|2024-02-29|-0.5|0|\n")
            f.write("\\N|2e3||1970-01-01|99999999.99|\\N|\n")
            path = f.name
        try:
            r = sess.execute(
                f"load data infile '{path}' into table n fields terminated by '|'"
            )
            assert r.affected == 3
            rows = sess.must_query(
                "select i, f, s, d, m, b from n order by d"
            ).rows
            assert rows[0][0] is None and rows[0][1] == 2000.0 and rows[0][2] is None
            assert rows[0][4] == 99999999.99
            assert rows[1] == (1, 1.5, "abc", "1994-01-01", 12.35, True)  # .345 rounds to .35
            assert rows[2][0] == -2 and rows[2][1] is None and rows[2][2] == "x y"
            assert rows[2][4] == -0.5
        finally:
            os.unlink(path)


class TestTopSQLAndReplayer:
    """TopSQL analog (infoschema top_sql ranking) and PLAN REPLAYER DUMP
    (reference: pkg/util/topsql; optimizor/plan_replayer.go)."""

    def test_top_sql_ranking(self):
        import time as _time

        from tidb_tpu.obs.profiler import TOPSQL
        from tidb_tpu.session import Session
        from tidb_tpu.utils.metrics import STMT_SUMMARY

        # the summary + profiler stores are process-global; start
        # clean for determinism in full-suite runs
        STMT_SUMMARY.reset()
        TOPSQL.stop()
        TOPSQL.store.reset()
        s = Session()
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (a int)")
        s.execute("insert into t values (1), (2)")
        # sampler OFF: an informative hint row, never a silent
        # latency re-ranking (PR 14 — the old stub's behavior)
        rows = s.execute(
            "select rank, digest_text from information_schema.top_sql"
        ).rows
        assert len(rows) == 1 and rows[0][0] == 0
        assert "tidb_enable_top_sql" in rows[0][1]
        s.execute("set global tidb_enable_top_sql = ON")
        try:
            # sampling is probabilistic: keep the statement hot until
            # the sampler has attributed it, bounded — a fixed window
            # flakes when a loaded machine starves the sampler thread
            t0 = _time.time()
            rows, mine = [], []
            while _time.time() - t0 < 5.0:
                for _ in range(25):
                    s.execute("select sum(a) from t")
                rows = s.execute(
                    "select rank, digest_text, exec_count, cpu_ms, "
                    "device_ms from information_schema.top_sql "
                    "order by rank"
                ).rows
                mine = [r for r in rows if "select sum" in r[1]]
                if mine and mine[0][2] >= 3 and mine[0][3] + mine[0][4] > 0:
                    break
            assert rows and rows[0][0] == 1
            mine = [r for r in rows if "select sum" in r[1]]
            assert mine and mine[0][2] >= 3
            # sampled attribution is the ranking signal now
            assert mine[0][3] + mine[0][4] > 0
        finally:
            s.execute("set global tidb_enable_top_sql = OFF")
            TOPSQL.store.reset()

    def test_plan_replayer_dump(self, tmp_path, monkeypatch):
        import zipfile

        from tidb_tpu.session import Session

        monkeypatch.setenv("TIDB_TPU_PLAN_REPLAYER_DIR", str(tmp_path))
        s = Session()
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (a int, b int)")
        s.execute("insert into t values (1, 2), (3, 4)")
        s.execute("analyze table t")
        r = s.execute("plan replayer dump explain select a from t where b > 1")
        fn = r.rows[0][0]
        assert fn.endswith(".zip")
        with zipfile.ZipFile(fn) as z:
            names = set(z.namelist())
            assert "sql/sql0.sql" in names
            assert "explain.txt" in names
            assert "schema/d.t.schema.txt" in names
            assert "stats/d.t.json" in names
            assert "variables.toml" in names
            import json as _json

            st = _json.loads(z.read("stats/d.t.json"))
            assert st["a"]["row_count"] == 2
            assert b"select a from t" in z.read("sql/sql0.sql")
