"""Top SQL continuous profiler (obs/profiler.py): sampler lifecycle,
per-digest attribution, bounded caps with evicted-digest fold-in,
worker ship/merge round-trip, collapsed-stack export, the live sysvar
hooks, the rewritten information_schema.top_sql, and the
check_topsql_attrib house lint."""

import os
import sys
import threading
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from tidb_tpu.obs import profiler  # noqa: E402
from tidb_tpu.obs.profiler import (  # noqa: E402
    CATEGORIES,
    OTHERS_DIGEST,
    TRUNCATED_STACK,
    TopSqlProfiler,
    TopSqlStore,
    digest_of,
)


def _sampler_threads():
    return [
        t for t in threading.enumerate()
        if t.name.startswith("obs-topsql-sampler") and t.is_alive()
    ]


class TestDigest:
    def test_digest_stable_and_short(self):
        d = digest_of("select sum ( a ) from t")
        assert len(d) == 16
        assert d == digest_of("select sum ( a ) from t")
        assert d != digest_of("select count ( * ) from t")
        # stable ACROSS PROCESSES (hash() is per-process salted; a
        # salted digest could never join worker attributions to the
        # coordinator's)
        import subprocess

        out = subprocess.run(
            [
                sys.executable, "-c",
                "from tidb_tpu.obs.profiler import digest_of;"
                "print(digest_of('select sum ( a ) from t'))",
            ],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONHASHSEED": "77"},
        )
        assert out.stdout.strip() == d


class TestSamplerLifecycle:
    def test_start_stop_idempotence(self):
        p = TopSqlProfiler(TopSqlStore(instance="t-lifecycle"))
        n0 = len(_sampler_threads())
        p.retune(0.05)
        assert p.running()
        assert len(_sampler_threads()) == n0 + 1
        # same interval again: a no-op — no second thread
        p.retune(0.05)
        assert len(_sampler_threads()) == n0 + 1
        # re-cadence: still exactly one
        p.retune(0.01)
        assert len(_sampler_threads()) == n0 + 1
        p.stop()
        p.stop()  # idempotent
        assert not p.running()
        deadline = time.time() + 5
        while _sampler_threads() and time.time() < deadline:
            time.sleep(0.01)
        assert len(_sampler_threads()) == n0

    def test_apply_config_idempotent_and_off(self):
        p = TopSqlProfiler(TopSqlStore(instance="t-cfg"))
        p.apply_config({"on": True, "interval_s": 0.05,
                        "max_digests": 7, "max_meta": 99})
        assert p.running() and p.store.max_digests == 7
        th = _sampler_threads()
        p.apply_config({"on": True, "interval_s": 0.05,
                        "max_digests": 7, "max_meta": 99})
        assert _sampler_threads() == th  # unchanged config: no churn
        p.apply_config(None)  # dispatch says profiler is off
        assert not p.running()

    def test_sample_pass_without_tasks_is_empty(self):
        p = TopSqlProfiler(TopSqlStore(instance="t-empty"))
        # no registered thread contexts: nothing attributed, nothing
        # dropped (unregistered threads are invisible, not errors)
        assert p.sample_once() == 0
        assert p.store.status()["dropped"] == 0


class TestAttribution:
    def test_known_hot_digest_attributed(self):
        p = TopSqlProfiler(TopSqlStore(instance="t-hot"))
        stop = threading.Event()

        def burn():
            with profiler.task_context(
                "statement", digest="feedbeeffeedbeef"
            ):
                while not stop.is_set():
                    sum(i * i for i in range(500))

        th = threading.Thread(target=burn, daemon=True,
                              name="obs-topsql-test-burn")
        th.start()
        try:
            p._last_pass = time.time()
            for _ in range(20):
                time.sleep(0.01)
                p.sample_once()
        finally:
            stop.set()
            th.join(timeout=5)
        rows = {
            r["digest"]: r for r in p.store.rows()
            if r["instance"] == "t-hot"
        }
        assert "feedbeeffeedbeef" in rows
        r = rows["feedbeeffeedbeef"]
        assert r["samples"] >= 10
        assert r["cpu_s"] > 0
        # the hot frame is the generator expression actually burning
        assert "burn" in r["top_frame"] or "genexpr" in r["top_frame"]

    def test_stall_classification_on_cv_wait(self):
        p = TopSqlProfiler(TopSqlStore(instance="t-stall"))
        ev = threading.Event()

        def park():
            with profiler.task_context(
                "shuffle", digest="0123456789abcdef",
                phase="shuffle-wait",
            ):
                ev.wait(timeout=5)

        th = threading.Thread(target=park, daemon=True,
                              name="obs-topsql-test-park")
        th.start()
        try:
            time.sleep(0.05)
            p._last_pass = time.time() - 0.02
            p.sample_once()
        finally:
            ev.set()
            th.join(timeout=5)
        rows = {r["digest"]: r for r in p.store.rows()}
        r = rows["0123456789abcdef"]
        # parked in Event.wait -> stall, charged to the live phase the
        # task context carries
        assert r["stall_s"] > 0 and r["cpu_s"] == 0
        assert "shuffle-wait" in r["by_phase"]

    def test_undeclared_category_raises(self):
        with pytest.raises(ValueError, match="undeclared"):
            profiler.begin_task("not-a-category")

    def test_long_statement_digest_matches_summary_digest(self):
        # regression: the flight record truncates sql to 2048 chars
        # for display; the attribution digest must come from the FULL
        # statement or long queries fork from their summary join
        from tidb_tpu.obs.flight import FLIGHT
        from tidb_tpu.utils.metrics import sql_digest

        sql = (
            "select a from t where a in ("
            + ", ".join(str(i) for i in range(1500))
            + ")"
        )
        assert len(sql) > 2048
        FLIGHT.begin(sql, 1)
        try:
            assert profiler.current_digest() == digest_of(
                sql_digest(sql)
            )
        finally:
            FLIGHT.finish(0.0)

    def test_nested_task_context_restores(self):
        with profiler.task_context("statement", digest="a" * 16):
            assert profiler.current_digest() == "a" * 16
            with profiler.task_context("fragment", digest="b" * 16):
                assert profiler.current_digest() == "b" * 16
            assert profiler.current_digest() == "a" * 16
        assert profiler.current_digest() is None


class TestStoreCaps:
    def test_digest_cap_evicts_coldest_into_others(self):
        st = TopSqlStore(instance="t-cap", max_digests=3)
        # six digests with increasing heat; the cap keeps the hottest
        for i, heat in enumerate([1, 2, 3, 4, 5, 6]):
            d = f"{i:016x}"
            for _ in range(heat):
                st.record(d, "execute", "cpu", 0.01, f"root;f{i}")
        local = [
            r for r in st.rows()
            if r["instance"] == "t-cap" and r["digest"] != OTHERS_DIGEST
        ]
        assert len(local) <= 3
        others = [
            r for r in st.rows() if r["digest"] == OTHERS_DIGEST
        ]
        assert others and others[0]["samples"] > 0
        # seconds conserved: every recorded 0.01 is SOMEWHERE
        total = sum(r["cpu_s"] for r in st.rows())
        assert total == pytest.approx(0.01 * (1 + 2 + 3 + 4 + 5 + 6))

    def test_retune_caps_live_shrinks(self):
        st = TopSqlStore(instance="t-retune", max_digests=8)
        for i in range(8):
            st.record(f"{i:016x}", "execute", "cpu", 0.01, "r;f")
        st.retune_caps(max_digests=2)
        local = [
            r for r in st.rows()
            if r["instance"] == "t-retune"
            and r["digest"] != OTHERS_DIGEST
        ]
        assert len(local) <= 2
        assert st.max_digests == 2

    def test_meta_cap_folds_stacks_into_truncated(self):
        st = TopSqlStore(instance="t-meta", max_digests=4, max_meta=8)
        for i in range(40):
            st.record("d" * 16, "execute", "cpu", 0.001,
                      f"root;leaf{i}")
        r = [x for x in st.rows() if x["digest"] == "d" * 16][0]
        assert r["samples"] == 40  # counts stay exact
        assert st.status()["meta"] <= 8
        merged = st.collapsed(digest="d" * 16)
        assert any(TRUNCATED_STACK in line for line in merged)

    def test_meta_count_stays_exact_under_eviction_churn(self):
        # regression: _fold_into_others once decremented the cap-
        # EXEMPT (truncated) bucket and leaked popped text meta —
        # churn drifted the accountant until the caps lied
        st = TopSqlStore(instance="t-drift", max_digests=2, max_meta=6)
        for i in range(30):
            d = f"{i:016x}"
            st.note_text(d, f"select {i}")
            for j in range(3):
                st.record(d, "execute", "cpu", 0.001,
                          f"root;leaf{i};{j}")
        with st._lock:
            counted = sum(
                1
                for (_inst, _d), ent in st._entries.items()
                for s in ent.stacks
                if s != TRUNCATED_STACK
            ) + len(st._texts)
            assert st._meta_count == counted
        assert st.status()["meta"] <= st.max_meta

    def test_registry_children_bounded_by_digest_cap(self):
        # regression: evicting a digest from the store must also drop
        # its per-digest REGISTRY counter children, or label (and
        # tsdb series) cardinality grows with every digest EVER seen
        from tidb_tpu.obs.profiler import _c_cpu_seconds

        fam = _c_cpu_seconds()
        fam.remove_matching(lambda lv: lv[0].startswith("cafe"))
        st = TopSqlStore(instance="t-cards", max_digests=3)
        for i in range(25):
            st.record(f"cafe{i:012x}", "execute", "cpu", 0.001, "r;f")
        live = {
            lv[0] for lv, _c in fam.children()
            if lv[0].startswith("cafe")
        }
        assert len(live) <= st.max_digests

    def test_remote_merge_capped_per_instance(self):
        st = TopSqlStore(instance="coord", max_digests=3)
        payload = {
            "agg": [
                [f"{i:016x}", "execute", 0.01, 0.0, 0.0, 1]
                for i in range(10)
            ],
            "stacks": [],
        }
        st.merge_remote(payload, instance="w1:1")
        w1 = [
            r for r in st.rows()
            if r["instance"] == "w1:1" and r["digest"] != OTHERS_DIGEST
        ]
        assert len(w1) <= 3
        # the overflow folded into the instance's (others), seconds
        # conserved
        total = sum(
            r["cpu_s"] for r in st.rows() if r["instance"] == "w1:1"
        )
        assert total == pytest.approx(0.1)


class TestShipMerge:
    def test_ship_merge_roundtrip_and_at_most_once(self):
        worker = TopSqlStore(instance="local", max_digests=10)
        worker.record("a" * 16, "execute", "cpu", 0.02, "r;x")
        worker.record("a" * 16, "shuffle-push", "stall", 0.01, "r;y")
        worker.record("b" * 16, "execute", "device", 0.03, "r;z")
        payload = worker.ship()
        assert payload is not None
        # at-most-once: the drain is destructive
        assert worker.ship() is None
        coord = TopSqlStore(instance="coordinator")
        merged = coord.merge_remote(payload, instance="w:9")
        assert merged > 0
        rows = {
            (r["instance"], r["digest"]): r for r in coord.rows()
        }
        ra = rows[("w:9", "a" * 16)]
        assert ra["cpu_s"] == pytest.approx(0.02)
        assert ra["stall_s"] == pytest.approx(0.01)
        assert ra["by_phase"]["shuffle-push"][2] == pytest.approx(0.01)
        rb = rows[("w:9", "b" * 16)]
        assert rb["device_s"] == pytest.approx(0.03)
        # stacks merged under the worker's instance for /profile
        assert coord.collapsed(instance="w:9")

    def test_malformed_payload_never_raises(self):
        coord = TopSqlStore(instance="coordinator")
        coord.merge_remote(
            {"agg": [["only-two", "fields"], None, 42],
             "stacks": [["x"], "nope"]},
            instance="w:1",
        )
        coord.merge_remote(None, instance="w:1")
        coord.merge_remote({"garbage": True}, instance="w:1")


class TestCollapsed:
    def test_collapsed_stack_roundtrip(self):
        st = TopSqlStore(instance="t-fg")
        st.record("e" * 16, "execute", "cpu", 0.120, "main;plan;exec")
        st.record("e" * 16, "execute", "cpu", 0.080, "main;plan;exec")
        st.record("e" * 16, "execute", "cpu", 0.050, "main;merge")
        lines = st.collapsed()
        # FlameGraph collapsed format: "frame;...;frame <int>", digest
        # as the root frame; counts are milliseconds
        parsed = {}
        for line in lines:
            stack, count = line.rsplit(" ", 1)
            parsed[stack] = int(count)
        key = f"{'e' * 16};main;plan;exec"
        assert parsed[key] == 200
        assert parsed[f"{'e' * 16};main;merge"] == 50
        # filters
        assert st.collapsed(digest="f" * 16) == []
        assert st.collapsed(instance="t-fg") != []
        assert st.collapsed(instance="nope") == []

    def test_collapse_stack_frames_have_no_spaces(self):
        frame = sys._getframe()
        s = profiler.collapse_stack(frame)
        assert " " not in s
        assert "test_topsql" in s


class TestRacecheckHammer:
    def test_eight_thread_hammer_under_racecheck(self):
        from tidb_tpu.utils import racecheck

        was = racecheck.enabled()
        racecheck.enable()
        try:
            st = TopSqlStore(instance="t-race", max_digests=8,
                             max_meta=64)
            p = TopSqlProfiler(st)
            coord = TopSqlStore(instance="t-race-coord")
            errs = []
            done = []

            def hammer(k):
                try:
                    for i in range(120):
                        with profiler.task_context(
                            "fragment", digest=f"{k:08x}{i % 12:08x}",
                        ):
                            st.record(
                                f"{k:08x}{i % 12:08x}", "execute",
                                ("cpu", "device", "stall")[i % 3],
                                0.001, f"r;h{k};f{i % 5}",
                            )
                        if i % 17 == 0:
                            payload = st.ship()
                            if payload:
                                coord.merge_remote(
                                    payload, instance=f"w{k % 2}"
                                )
                        if i % 29 == 0:
                            st.retune_caps(
                                max_digests=6 + (i % 3)
                            )
                        if i % 13 == 0:
                            st.rows()
                            st.collapsed()
                    done.append(k)
                except Exception as e:  # pragma: no cover
                    errs.append(f"{k}: {type(e).__name__}: {e}")

            threads = [
                threading.Thread(
                    target=hammer, args=(k,), daemon=True,
                    name=f"obs-topsql-hammer-{k}",
                )
                for k in range(8)
            ]
            p.retune(0.005)  # a live sampler walks the hammer threads
            try:
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=60)
                assert not [t for t in threads if t.is_alive()], (
                    "hammer thread hung"
                )
            finally:
                p.stop()
            assert not errs, errs
            assert len(done) == 8  # every hammer COMPLETED its loop
            assert "obs.topsql" in racecheck.seen_classes()
            assert "obs.topsql_sampler" in racecheck.seen_classes()
        finally:
            if not was:
                racecheck.disable()


class TestSysvarHooks:
    def test_live_enable_caps_and_session_scope_errors(self):
        from tidb_tpu.obs.profiler import TOPSQL
        from tidb_tpu.session import Session

        s = Session()
        try:
            # session-scoped SET errors loudly (the DCN-knob contract)
            # — values chosen to PASS each knob's validator, so the
            # raise is the scope check, not a range error
            for name, val in (
                ("tidb_enable_top_sql", "1"),
                ("tidb_top_sql_max_time_series_count", "50"),
                ("tidb_top_sql_max_meta_count", "500"),
                ("tidb_tpu_topsql_sample_interval_s", "0.05"),
            ):
                with pytest.raises(ValueError, match="global"):
                    s.execute(f"set {name} = {val}")
            s.execute("set global tidb_top_sql_max_time_series_count = 41")
            s.execute("set global tidb_top_sql_max_meta_count = 443")
            s.execute(
                "set global tidb_tpu_topsql_sample_interval_s = 0.011"
            )
            s.execute("set global tidb_enable_top_sql = ON")
            assert TOPSQL.running()
            assert TOPSQL.interval_s() == pytest.approx(0.011)
            assert TOPSQL.store.max_digests == 41
            assert TOPSQL.store.max_meta == 443
            # caps re-tune LIVE while running (the PR 12 pattern)
            s.execute("set global tidb_top_sql_max_time_series_count = 17")
            assert TOPSQL.store.max_digests == 17
            s.execute("set global tidb_enable_top_sql = 0")
            assert not TOPSQL.running()
        finally:
            TOPSQL.stop()
            TOPSQL.store.retune_caps(100, 5000)
            TOPSQL.store.reset()


class TestTopSqlTable:
    def test_off_returns_hint_row_not_latency_reranking(self):
        from tidb_tpu.obs.profiler import TOPSQL
        from tidb_tpu.session import Session

        TOPSQL.stop()
        TOPSQL.store.reset()
        s = Session()
        rows = s.execute(
            "select rank, instance, digest_text from "
            "information_schema.top_sql"
        ).rows
        assert len(rows) == 1
        assert rows[0][0] == 0
        assert "tidb_enable_top_sql" in rows[0][2]

    def test_on_ranks_hot_digest_first_with_phase_split(self):
        from tidb_tpu.obs.profiler import TOPSQL
        from tidb_tpu.session import Session

        TOPSQL.store.reset()
        s = Session()
        s.execute("create database tsq")
        s.execute("use tsq")
        s.execute("create table t (a int)")
        s.execute("insert into t values (1), (2), (3)")
        s.execute("set global tidb_enable_top_sql = ON")
        try:
            t0 = time.time()
            while time.time() - t0 < 0.7:
                s.execute("select sum(a), count(*) from t where a > 0")
            rows = s.execute(
                "select rank, instance, digest, digest_text, cpu_ms, "
                "device_ms, stall_ms, samples, top_phase, exec_count "
                "from information_schema.top_sql order by rank"
            ).rows
            assert rows
            top = rows[0]
            assert top[0] == 1
            assert top[1] == "coordinator"
            assert "select sum" in top[3]
            # the split is measured, nonzero, and attributed
            assert top[4] + top[5] > 0  # cpu + device
            assert top[7] >= 5  # samples
            assert top[8] in (
                "execute", "compile", "plan", "final-merge",
            )
            assert top[9] >= 3  # statements_summary join: exec_count
        finally:
            s.execute("set global tidb_enable_top_sql = OFF")
            TOPSQL.store.reset()


class TestAttribLint:
    def test_head_tree_is_clean(self):
        from check_topsql_attrib import check

        assert check(REPO) == []

    def test_declared_categories_match_runtime(self):
        from check_topsql_attrib import load_categories

        assert tuple(load_categories(REPO)) == CATEGORIES

    def _tree(self, tmp_path, engine_src,
              cats="(\n    \"statement\",\n    \"fragment\",\n)"):
        obs = tmp_path / "tidb_tpu" / "obs"
        obs.mkdir(parents=True)
        (obs / "profiler.py").write_text(
            f"CATEGORIES = {cats}\n"
        )
        (tmp_path / "tidb_tpu" / "engine.py").write_text(engine_src)
        return str(tmp_path)

    def test_seeded_undeclared_category_fails(self, tmp_path):
        from check_topsql_attrib import check

        root = self._tree(
            tmp_path,
            "from tidb_tpu.obs import profiler\n"
            "def f():\n"
            "    with profiler.task_context('statement'):\n"
            "        pass\n"
            "    profiler.begin_task('mystery')\n",
        )
        v = check(root)
        assert any("undeclared" in msg for _f, _l, msg in v)
        # 'fragment' is declared but never registered: dead
        assert any("dead declaration" in msg for _f, _l, msg in v)

    def test_seeded_nonliteral_category_fails(self, tmp_path):
        from check_topsql_attrib import check

        root = self._tree(
            tmp_path,
            "from tidb_tpu.obs.profiler import begin_task,"
            " task_context\n"
            "def f(cat):\n"
            "    begin_task(cat)\n"
            "    task_context('statement')\n"
            "    begin_task('fragment')\n",
        )
        v = check(root)
        assert any("non-literal" in msg for _f, _l, msg in v)

    def test_seeded_clean_tree_passes(self, tmp_path):
        from check_topsql_attrib import check

        root = self._tree(
            tmp_path,
            "from tidb_tpu.obs.profiler import begin_task,"
            " task_context\n"
            "def f():\n"
            "    begin_task('statement')\n"
            "    with task_context('fragment'):\n"
            "        pass\n",
        )
        assert check(root) == []

    def test_lint_all_discovers_it(self):
        import subprocess

        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "lint_all.py"), "--list"],
            capture_output=True, text=True, cwd=REPO,
        )
        assert "check_topsql_attrib.py" in out.stdout


class TestInspectionRule:
    def test_cpu_hog_digest_fires_on_synthetic_history(self):
        from tidb_tpu.obs.inspection import InspectionEngine
        from tidb_tpu.obs.tsdb import TimeSeriesStore

        store = TimeSeriesStore()
        now = time.time()
        hog = "c0ffee0000000000"
        # a hog burning 90% of the window vs a small background digest
        for i, t in enumerate([now - 30, now - 20, now - 10, now]):
            store.merge_remote(
                [
                    ["tidbtpu_topsql_cpu_seconds",
                     ["digest", "phase"], [hog, "execute"],
                     t, 1.0 * i, "counter"],
                    ["tidbtpu_topsql_cpu_seconds",
                     ["digest", "phase"],
                     ["dead000000000000", "execute"],
                     t, 0.05 * i, "counter"],
                ],
                host="coordinator",
            )
        eng = InspectionEngine(store)
        findings = eng.run(
            t_lo=now - 35, t_hi=now + 1, rules=["cpu-hog-digest"]
        )
        hits = [f for f in findings if f.item == hog]
        assert hits, findings
        assert hits[0].severity in ("warning", "critical")
        assert hits[0].t0 >= now - 35 and hits[0].t1 <= now + 1

    def test_quiet_on_balanced_load(self):
        from tidb_tpu.obs.inspection import InspectionEngine
        from tidb_tpu.obs.tsdb import TimeSeriesStore

        store = TimeSeriesStore()
        now = time.time()
        for d in ("aa" * 8, "bb" * 8, "cc" * 8):
            for i, t in enumerate([now - 20, now - 10, now]):
                store.merge_remote(
                    [["tidbtpu_topsql_cpu_seconds",
                      ["digest", "phase"], [d, "execute"],
                      t, 0.3 * i, "counter"]],
                    host="coordinator",
                )
        eng = InspectionEngine(store)
        findings = eng.run(
            t_lo=now - 25, t_hi=now + 1, rules=["cpu-hog-digest"]
        )
        assert findings == []
