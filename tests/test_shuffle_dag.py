"""Shuffle DAGs: multi-stage exchanges, range-partitioned distributed
ORDER BY, per-partition top-K, and the per-edge broadcast cost model.

Planner shapes (split_plan_dag), the range-partition wire helpers, the
coordinator's boundary merge, and end-to-end parity against in-process
EngineServer fleets — including whole-DAG retry after a boundary-sample
loss and after a worker "dies" between stage N and N+1
(shuffle/stage-input), with held-output drain audited after every run.
The multi-process dryruns live in tests/test_multihost.py.
"""

import numpy as np
import pytest

from tidb_tpu.chunk import HostBlock, column_from_values
from tidb_tpu.dtypes import FLOAT64, INT64, SQLType, Kind
from tidb_tpu.parallel.dcn import DCNFragmentScheduler
from tidb_tpu.parallel.wire import (
    range_key_values,
    range_partition_map,
    sample_range_keys,
)
from tidb_tpu.parser.sqlparse import parse
from tidb_tpu.planner import logical as L
from tidb_tpu.planner.fragmenter import (
    DagStage,
    ShuffleSide,
    choose_edge_modes,
    split_plan_dag,
)
from tidb_tpu.planner.logical import build_query
from tidb_tpu.server.engine_rpc import EngineServer
from tidb_tpu.session.session import Session
from tidb_tpu.utils import failpoint


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create table t (a int, b varchar(8), c int)")
    s.execute(
        "insert into t values (1,'x',5),(2,'y',null),(3,'x',7),"
        "(4,null,8),(2,'x',5),(7,'y',null),(1,'y',2),(3,'z',3),"
        "(5,'w',5)"
    )
    s.execute("create table u (k int, v int)")
    s.execute(
        "insert into u values (1,10),(2,20),(3,30),(4,40),(1,11),"
        "(7,70),(3,31),(5,50)"
    )
    return s


def _plan(sess, q):
    return build_query(
        parse(q)[0], sess.catalog, "test", sess._scalar_subquery
    )


def _block(vals, typ=INT64):
    return HostBlock({"k": column_from_values(vals, typ)}, len(vals))


# ---------------------------------------------------------------------------
# range partitioning (wire helpers)
# ---------------------------------------------------------------------------


class TestRangePartition:
    def test_partitions_by_boundaries_ties_colocate(self):
        blk = _block([1, 5, 5, 9, 2, 7, 5])
        pmap = range_partition_map(blk, "k", [2, 5])
        # partition p owns (b[p-1], b[p]]: 1,2 -> 0; 5,5,5 -> 1; 9,7 -> 2
        assert pmap.tolist() == [0, 1, 1, 2, 0, 2, 1]

    def test_null_keys_land_partition_zero(self):
        blk = _block([None, 9, None, 1])
        pmap = range_partition_map(blk, "k", [4])
        assert pmap.tolist() == [0, 1, 0, 0]

    def test_empty_boundaries_collapse_to_partition_zero(self):
        blk = _block([3, 1, 2])
        assert range_partition_map(blk, "k", []).tolist() == [0, 0, 0]

    def test_float_and_decimal_domains_order(self):
        fblk = _block([2.5, -1.0, 0.0], FLOAT64)
        assert range_partition_map(fblk, "k", [0.0]).tolist() == [1, 0, 0]
        dec = SQLType(Kind.DECIMAL, scale=2)
        dblk = _block([1.50, 0.25, 4.75], dec)
        # scaled-unit ints order like the values; boundaries come from
        # sample_range_keys so they share the scaled domain
        b = sample_range_keys(dblk, "k", 3, seed=1, part=0)
        assert b == sorted(b)
        assert range_key_values(dblk.columns["k"]).tolist() == [150, 25, 475]

    def test_string_keys_rejected(self):
        sblk = HostBlock(
            {"k": column_from_values(["a", "b"], SQLType(Kind.STRING))}, 2
        )
        with pytest.raises(ValueError):
            range_key_values(sblk.columns["k"])

    def test_sampling_deterministic_under_fixed_seed(self):
        blk = _block(list(range(1000)))
        a = sample_range_keys(blk, "k", 32, seed=7, part=1)
        b = sample_range_keys(blk, "k", 32, seed=7, part=1)
        assert a == b and len(a) == 32
        c = sample_range_keys(blk, "k", 32, seed=8, part=1)
        assert a != c  # a different seed draws a different sample

    def test_merge_boundaries_quantile_cut(self):
        b = DCNFragmentScheduler.merge_boundaries(
            [[1, 3, 5], [2, 4, 6]], 3
        )
        assert b == [3, 5] and len(b) == 2  # thirds of the merged set
        assert DCNFragmentScheduler.merge_boundaries([[], []], 3) == []
        assert DCNFragmentScheduler.merge_boundaries([[1, 2]], 1) == []


# ---------------------------------------------------------------------------
# planner shapes
# ---------------------------------------------------------------------------


class TestDagPlanner:
    def test_pure_order_by_limit_is_one_range_stage(self, sess):
        dag = split_plan_dag(
            _plan(sess, "select c, b from t order by c desc limit 3"),
            sess.catalog,
        )
        assert dag is not None and len(dag.stages) == 1
        (st,) = dag.stages
        assert st.exchange == "range" and st.limit == 3 and st.desc
        assert isinstance(st.consumer, L.Limit)  # pushed-down top-K
        assert dag.merge["kind"] == "concat"
        assert dag.merge["reverse"] is True

    def test_join_rekeyed_groupby_orderby_chains_three_stages(self, sess):
        dag = split_plan_dag(
            _plan(
                sess,
                "select b, count(*), sum(v) from t join u on a = k "
                "group by b order by count(*) desc, b limit 2",
            ),
            sess.catalog,
        )
        assert dag is not None
        assert [s.exchange for s in dag.stages] == ["hash", "hash", "range"]
        # stage 1 re-stages stage 0's HELD join output (no re-scan)
        assert isinstance(dag.stages[1].sides[0].template, L.StageInput)
        assert dag.stages[1].sides[0].template.stage == 0
        assert dag.stages[1].requires_key_partition
        # per-partition top-K under the range sort
        assert dag.stages[2].limit == 2

    def test_group_key_equals_join_key_fuses_agg_into_join_stage(
        self, sess
    ):
        dag = split_plan_dag(
            _plan(
                sess,
                "select a, count(*), sum(v) from t join u on a = k "
                "group by a order by a",
            ),
            sess.catalog,
        )
        assert dag is not None
        assert [s.exchange for s in dag.stages] == ["hash", "range"]
        assert dag.stages[0].requires_key_partition  # complete groups

    def test_plan_merge_for_chain_without_range_root(self, sess):
        dag = split_plan_dag(
            _plan(
                sess,
                "select b, count(*), sum(v) from t join u on a = k "
                "group by b",
            ),
            sess.catalog,
        )
        assert dag is not None and dag.merge["kind"] == "plan"
        assert [s.exchange for s in dag.stages] == ["hash", "hash"]

    def test_no_dag_for_single_stage_shapes(self, sess):
        # a bare group-by has nothing to chain and nothing to range
        assert (
            split_plan_dag(
                _plan(sess, "select b, count(*) from t group by b"),
                sess.catalog,
            )
            is None
        )
        # string first sort key: no range exchange (collation order
        # lives in per-batch dictionaries) -> coordinator sort
        assert (
            split_plan_dag(
                _plan(sess, "select b, c from t order by b"),
                sess.catalog,
            )
            is None
        )

    def test_temporal_first_key_distributes(self, sess):
        # DATE/DATETIME/TIME encodings are chronological int64s
        # (wire.range_key_values): a date-keyed ORDER BY must range-
        # partition, not fall back to the coordinator sort
        sess.execute("create table ev (d date, n int)")
        sess.execute(
            "insert into ev values ('2024-01-05',1),('2023-06-01',2),"
            "(null,3),('2024-01-05',4),('2025-12-31',5)"
        )
        dag = split_plan_dag(
            _plan(sess, "select d, n from ev order by d desc limit 3"),
            sess.catalog,
        )
        assert dag is not None
        assert dag.stages[-1].exchange == "range"

    def test_window_partition_key_distributes(self, sess):
        dag = split_plan_dag(
            _plan(
                sess,
                "select a, c, sum(c) over (partition by a order by c) "
                "from t order by a, c",
            ),
            sess.catalog,
        )
        assert dag is not None
        # window stage (complete partitions per hash partition) + a
        # range stage for the ORDER BY
        assert [s.exchange for s in dag.stages] == ["hash", "range"]
        assert dag.stages[0].requires_key_partition

    def test_window_above_aggregate_stays_on_coordinator(self, sess):
        # a Window between the ORDER BY and the Aggregate computes
        # over the WHOLE set: it must never fold into a per-partition
        # stage (the range wrap guard), so no DAG forms here
        assert (
            split_plan_dag(
                _plan(
                    sess,
                    "select b, count(*), rank() over (order by "
                    "count(*)) from t group by b order by b",
                ),
                sess.catalog,
            )
            is None
        )
        # a GLOBAL window (no PARTITION BY) has no distribution key
        assert (
            split_plan_dag(
                _plan(sess, "select a, rank() over (order by c) from t"),
                sess.catalog,
            )
            is None
        )

    def test_choose_edge_modes_broadcasts_small_inner_side(self):
        def stage(l_rows, r_rows, kind="inner", requires=False):
            sides = [
                ShuffleSide(None, None, "a", 0, l_rows),
                ShuffleSide(None, None, "k", 1, r_rows),
            ]
            return DagStage(
                "hash", sides, None, join_kind=kind,
                requires_key_partition=requires,
            )

        st = stage(100_000, 500)
        assert choose_edge_modes(st, broadcast_max_rows=1000) == "broadcast"
        assert [s.mode for s in st.sides] == ["local", "broadcast"]
        # too big to broadcast / ratio unmet / disabled -> hash
        assert choose_edge_modes(stage(100_000, 5000), 1000) == "hash"
        assert choose_edge_modes(stage(1000, 500), 1000) == "hash"
        assert choose_edge_modes(stage(100_000, 500), 0) == "hash"
        # key-partition-requiring consumers never trade their edges
        assert (
            choose_edge_modes(stage(100_000, 500, requires=True), 1000)
            == "hash"
        )
        # left joins preserve the LEFT side: only the right broadcasts
        st = stage(500, 100_000, kind="left")
        assert choose_edge_modes(st, 1000) == "hash"
        st = stage(100_000, 500, kind="left")
        assert choose_edge_modes(st, 1000) == "broadcast"
        assert [s.mode for s in st.sides] == ["local", "broadcast"]


# ---------------------------------------------------------------------------
# end-to-end: in-process 2-server fleet
# ---------------------------------------------------------------------------


DAG_QUERIES = [
    # distributed windows: complete PARTITION BY partitions per hash
    # partition (frames and running aggregates included), then a range
    # exchange for the ORDER BY
    "select a, c, sum(c) over (partition by a order by c) from t "
    "order by a, c",
    "select a, c, row_number() over (partition by a order by c "
    "rows between 1 preceding and current row) from t order by a, c",
    "select c, b from t order by c desc limit 3",
    "select c, a from t order by c",
    "select b, count(*), sum(v) from t join u on a = k group by b "
    "order by count(*) desc, b limit 2",
    "select a, count(*), sum(v) from t join u on a = k group by a "
    "order by a",
    "select b, count(*) from t group by b order by count(*) desc limit 2",
    "select b, count(*), sum(v) from t join u on a = k group by b",
    "select a, c from t order by c desc limit 3 offset 2",
]


def _fleet(sess, n=2, **kw):
    servers = [EngineServer(sess.catalog, port=0) for _ in range(n)]
    for s in servers:
        s.start_background()
    kw.setdefault("shuffle_wait_timeout_s", 30.0)
    sched = DCNFragmentScheduler(
        [("127.0.0.1", s.port) for s in servers],
        catalog=sess.catalog, shuffle_mode="always",
        shuffle_dag="always", **kw,
    )
    return servers, sched


def _teardown(servers, sched):
    sched.close()
    for s in servers:
        s.shutdown()


def _run(sess, sched, q):
    plan = _plan(sess, q)
    kind, cut = sched._choose_cut(plan)
    assert kind == "dag", f"{q} did not plan as a DAG ({kind})"
    return sched.execute_plan(plan, cut_hint=(kind, cut))


class TestDagExecution:
    def test_dag_parity_and_held_drain(self, sess):
        servers, sched = _fleet(sess)
        try:
            for q in DAG_QUERIES:
                exp = sess.must_query(q).rows
                _cols, got = _run(sess, sched, q)
                if "order by" not in q:
                    # no ORDER BY = no row-order contract (complete
                    # groups land in partition order): set parity
                    # (repr key: NULLs don't compare to strings)
                    got = sorted(got, key=repr)
                    exp = sorted(exp, key=repr)
                assert got == exp, f"{q}\n got={got}\n exp={exp}"
            for s in servers:
                assert s._shuffle is not None
                assert s._shuffle.held_count() == 0
                assert s._shuffle.store.buffered_stages() == 0
        finally:
            _teardown(servers, sched)

    def test_chained_stages_report_stage_index_and_scan_rows(self, sess):
        servers, sched = _fleet(sess)
        try:
            q = (
                "select b, count(*), sum(v) from t join u on a = k "
                "group by b order by count(*) desc, b limit 2"
            )
            exp = sess.must_query(q).rows
            _cols, got = _run(sess, sched, q)
            assert got == exp
            stages = sched.last_query["shuffle_stages"]
            assert [s["stage"] for s in stages] == [0, 1, 2]
            assert [s["exchange"] for s in stages] == [
                "hash", "hash", "range",
            ]
            # stage 0 scans BOTH sides fragment-sliced: total scanned
            # rows across hosts == the two tables' row counts exactly
            # (no unsliced re-scan), and stages 1/2 scan NOTHING
            nt = sess.catalog.table("test", "t").nrows
            nu = sess.catalog.table("test", "u").nrows
            assert stages[0]["scan_rows"] == nt + nu
            assert stages[1]["scan_rows"] == 0
            assert stages[2]["scan_rows"] == 0
            # the range stage recorded its merged boundaries
            assert stages[2]["boundaries"] is not None
        finally:
            _teardown(servers, sched)

    def test_boundaries_deterministic_across_runs(self, sess):
        servers, sched = _fleet(sess)
        try:
            q = "select c, b from t order by c desc limit 3"
            _run(sess, sched, q)
            b1 = sched.last_query["shuffle_stages"][-1]["boundaries"]
            _run(sess, sched, q)
            b2 = sched.last_query["shuffle_stages"][-1]["boundaries"]
            assert b1 == b2  # fixed sample seed -> identical cut
        finally:
            _teardown(servers, sched)

    def test_per_partition_topk_bounds_returned_rows(self, sess):
        servers, sched = _fleet(sess)
        try:
            q = "select a, c from t order by c desc limit 3 offset 2"
            exp = sess.must_query(q).rows
            _cols, got = _run(sess, sched, q)
            assert got == exp
            stages = sched.last_query["shuffle_stages"]
            frags = sched.last_query["fragments"]
            last = [f for f in frags if f["stage"] == len(stages) - 1]
            # each partition shipped at most count+offset rows
            assert all(f["rows"] <= 3 + 2 for f in last)
        finally:
            _teardown(servers, sched)

    def test_broadcast_edge_ships_zero_probe_bytes(self, sess):
        # big probe side, small build side: the cost model broadcasts
        # the small side; the big side never crosses the wire
        sess.execute("create table big (a int, c int)")
        vals = ",".join(f"({i % 7},{i % 13})" for i in range(200))
        sess.execute(f"insert into big values {vals}")
        sess.execute("create table dim (k int, v int)")
        sess.execute(
            "insert into dim values (0,100),(1,101),(2,102),(3,103),"
            "(4,104),(5,105),(6,106)"
        )
        servers, sched = _fleet(sess, shuffle_broadcast_rows=50)
        try:
            q = (
                "select c, count(*), sum(v) from big join dim on a = k "
                "group by c order by c"
            )
            exp = sess.must_query(q).rows
            plan = _plan(sess, q)
            kind, cut = sched._choose_cut(plan)
            assert kind == "dag"
            assert [s.mode for s in cut.stages[0].sides] == [
                "local", "broadcast",
            ]
            _cols, got = sched.execute_plan(plan, cut_hint=(kind, cut))
            assert got == exp
            st0 = sched.last_query["shuffle_stages"][0]
            # only the small side's rows tunneled (m-1 copies of <= 7
            # dictionary rows each); the 200-row side stayed local
            assert st0["rows_tunneled"] <= 7 * (2 - 1) + 1
            assert st0["local_rows"] >= 200
        finally:
            _teardown(servers, sched)

    def test_sample_loss_retries_to_identical_boundaries(self, sess):
        from tidb_tpu.server.engine_rpc import DropConnection

        servers, sched = _fleet(sess)
        try:
            q = "select c, b from t order by c desc limit 3"
            exp = sess.must_query(q).rows
            _run(sess, sched, q)
            clean = sched.last_query["shuffle_stages"][-1]["boundaries"]
            # drop the FIRST boundary-sample reply: the coordinator
            # verifies the suspect (alive), retries the whole DAG, and
            # the fixed seed reproduces the same cut
            failpoint.enable(
                "shuffle/sample-lost",
                failpoint.after_n(1, DropConnection("test")),
            )
            _cols, got = _run(sess, sched, q)
            assert got == exp
            st = sched.last_query["shuffle_stages"][-1]
            assert st["attempts"] > 1  # the DAG really retried
            assert st["boundaries"] == clean
            assert len(sched.alive_endpoints()) == 2  # no quarantine
        finally:
            _teardown(servers, sched)

    def test_interstage_loss_retries_whole_dag_with_parity(self, sess):
        from tidb_tpu.server.engine_rpc import DropConnection

        servers, sched = _fleet(sess)
        try:
            q = (
                "select b, count(*), sum(v) from t join u on a = k "
                "group by b order by count(*) desc, b limit 2"
            )
            exp = sess.must_query(q).rows
            # the reply vanishes exactly when stage 1 reads stage 0's
            # held output — the "worker died between stages" shape
            failpoint.enable(
                "shuffle/stage-input",
                failpoint.after_n(1, DropConnection("test")),
            )
            _cols, got = _run(sess, sched, q)
            assert got == exp
            assert any(
                s["attempts"] > 1
                for s in sched.last_query["shuffle_stages"]
            )
            for s in servers:
                assert s._shuffle.held_count() == 0
                assert s._shuffle.store.buffered_stages() == 0
        finally:
            _teardown(servers, sched)

    def test_explain_analyze_renders_stage_dag(self, sess):
        servers, sched = _fleet(sess)
        try:
            q = (
                "select b, count(*), sum(v) from t join u on a = k "
                "group by b order by count(*) desc, b limit 2"
            )
            exp = sess.must_query(q).rows
            _cols, rows, lines = sched.explain_analyze(_plan(sess, q))
            assert rows == exp
            text = "\n".join(lines)
            assert "RangeConcatMerge" in text
            assert "stage=1/3 exchange=hash" in text
            assert "stage=2/3 exchange=hash" in text
            assert "stage=3/3 exchange=range" in text
            assert "produce=" in text and "wait=" in text
            # plan-merge DAG: stages render under the Staged node
            q2 = (
                "select b, count(*), sum(v) from t join u on a = k "
                "group by b"
            )
            exp2 = sess.must_query(q2).rows
            _cols2, rows2, lines2 = sched.explain_analyze(
                _plan(sess, q2)
            )
            # no ORDER BY: set parity (rows land in partition order)
            assert sorted(rows2, key=repr) == sorted(exp2, key=repr)
            text2 = "\n".join(lines2)
            assert "stage=1/2 exchange=hash" in text2
            assert "stage=2/2 exchange=hash" in text2
        finally:
            _teardown(servers, sched)

    def test_auto_policy_defers_small_dags_to_single_cut(self, sess):
        servers, sched = _fleet(sess)
        sched.shuffle_dag = "auto"  # tiny tables: below min_rows
        try:
            kind, _cut = sched._choose_cut(
                _plan(
                    sess,
                    "select b, count(*), sum(v) from t join u on a = k "
                    "group by b",
                )
            )
            assert kind != "dag"
            sched.shuffle_min_rows = 1
            kind2, cut2 = sched._choose_cut(
                _plan(
                    sess,
                    "select b, count(*), sum(v) from t join u on a = k "
                    "group by b",
                )
            )
            assert kind2 == "dag" and len(cut2.stages) == 2
        finally:
            _teardown(servers, sched)
