"""CHECK constraints, FOREIGN KEYs (RESTRICT), and SAVEPOINTs.

Reference: constraint checks in the write path (pkg/table/tables.go
CheckRowConstraint), FK enforcement (pkg/executor FK checks/cascades —
RESTRICT only here), savepoints (pkg/session savepoint support).
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture()
def sess():
    return Session()


class TestCheck:
    def test_basic_check(self, sess):
        sess.execute("create table t (a int, b int, check (a > 0))")
        sess.execute("insert into t values (1, 2)")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("insert into t values (0, 5)")
        assert sess.execute("select count(*) from t").rows == [(1,)]

    def test_null_passes(self, sess):
        # SQL: CHECK fails only on FALSE; UNKNOWN (NULL) passes
        sess.execute("create table t (a int, check (a > 0))")
        sess.execute("insert into t values (null)")
        assert sess.execute("select count(*) from t").rows == [(1,)]

    def test_named_and_multi_column(self, sess):
        sess.execute(
            "create table t (lo int, hi int, "
            "constraint ordered check (lo <= hi))"
        )
        sess.execute("insert into t values (1, 5)")
        with pytest.raises(ValueError, match="ordered"):
            sess.execute("insert into t values (9, 5)")

    def test_column_level_check(self, sess):
        sess.execute("create table t (pct int check (pct between 0 and 100))")
        sess.execute("insert into t values (50)")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("insert into t values (101)")

    def test_check_on_update(self, sess):
        sess.execute("create table t (a int, check (a < 10))")
        sess.execute("insert into t values (5)")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("update t set a = 20 where a = 5")
        assert sess.execute("select a from t").rows == [(5,)]

    def test_check_with_strings_and_in(self, sess):
        sess.execute(
            "create table t (s varchar(10), check (s in ('a', 'b')))"
        )
        sess.execute("insert into t values ('a')")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("insert into t values ('c')")

    def test_unknown_column_rejected_at_create(self, sess):
        with pytest.raises(ValueError, match="unknown columns"):
            sess.execute("create table t (a int, check (b > 0))")

    def test_atomic_multi_row_insert(self, sess):
        sess.execute("create table t (a int, check (a > 0))")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("insert into t values (1), (2), (-1)")
        assert sess.execute("select count(*) from t").rows == [(0,)]

    def test_drop_column_guard(self, sess):
        sess.execute("create table t (a int, b int, check (a > 0))")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("alter table t drop column a")
        sess.execute("alter table t drop column b")


class TestForeignKey:
    @pytest.fixture()
    def fk(self, sess):
        sess.execute("create table parent (id int primary key, v int)")
        sess.execute("insert into parent values (1, 10), (2, 20)")
        sess.execute(
            "create table child (id int, pid int, "
            "foreign key (pid) references parent (id))"
        )
        return sess

    def test_child_insert(self, fk):
        fk.execute("insert into child values (100, 1)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            fk.execute("insert into child values (101, 99)")
        fk.execute("insert into child values (102, null)")  # NULL FK ok

    def test_parent_delete_restricted(self, fk):
        fk.execute("insert into child values (100, 1)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            fk.execute("delete from parent where id = 1")
        fk.execute("delete from parent where id = 2")  # unreferenced: ok
        fk.execute("delete from child where id = 100")
        fk.execute("delete from parent where id = 1")  # now unreferenced

    def test_parent_update_restricted(self, fk):
        fk.execute("insert into child values (100, 1)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            fk.execute("update parent set id = 5 where id = 1")
        fk.execute("update parent set v = 99 where id = 1")  # non-key ok

    def test_child_update_checked(self, fk):
        fk.execute("insert into child values (100, 1)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            fk.execute("update child set pid = 42 where id = 100")
        fk.execute("update child set pid = 2 where id = 100")

    def test_drop_parent_blocked(self, fk):
        with pytest.raises(ValueError, match="referenced by"):
            fk.execute("drop table parent")
        fk.execute("drop table child")
        fk.execute("drop table parent")

    def test_self_referential(self, sess):
        sess.execute(
            "create table emp (id int primary key, mgr int, "
            "foreign key (mgr) references emp (id))"
        )
        # a manager inserted in the same statement is a valid target
        sess.execute("insert into emp values (1, null), (2, 1)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            sess.execute("insert into emp values (3, 77)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            sess.execute("delete from emp where id = 1")
        sess.execute("delete from emp")  # full truncate removes both sides

    def test_column_level_references(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute("insert into p values (7)")
        sess.execute("create table c (pid int references p (id))")
        sess.execute("insert into c values (7)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            sess.execute("insert into c values (8)")

    def test_unknown_parent_at_create(self, sess):
        with pytest.raises(ValueError, match="unknown table"):
            sess.execute(
                "create table c (pid int, "
                "foreign key (pid) references ghost (id))"
            )

    def test_bare_numeric_check_is_sql_truthy(self, sess):
        # CHECK (a) fails on 0, like MySQL's boolean coercion
        sess.execute("create table t (a int, check (a))")
        sess.execute("insert into t values (1)")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("insert into t values (0)")

    def test_replace_cannot_orphan_children(self, sess):
        sess.execute("create table p (id int primary key, code int)")
        sess.execute("insert into p values (1, 10)")
        sess.execute(
            "create table c (x int, foreign key (x) references p (code))"
        )
        sess.execute("insert into c values (10)")
        # replacing pk=1 would swap code 10 -> 20, dangling the child
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            sess.execute("replace into p values (1, 20)")
        assert sess.execute("select code from p").rows == [(10,)]
        sess.execute("replace into p values (1, 10)")  # same code: fine

    def test_drop_database_blocked_by_external_child(self, sess):
        sess.execute("create database pdb")
        sess.execute("create table pdb.p (id int primary key)")
        sess.execute(
            "create table c (x int, foreign key (x) references pdb.p (id))"
        )
        with pytest.raises(ValueError, match="referenced by"):
            sess.execute("drop database pdb")
        sess.execute("drop table c")
        sess.execute("drop database pdb")

    def test_persist_roundtrip(self, fk, tmp_path):
        from tidb_tpu.storage.persist import load_catalog, save_catalog

        fk.execute("insert into child values (100, 1)")
        save_catalog(fk.catalog, str(tmp_path))
        s2 = Session(load_catalog(str(tmp_path)))
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            s2.execute("insert into child values (101, 99)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            s2.execute("delete from parent where id = 1")

    def test_show_create_table_lists_constraints(self, fk):
        out = fk.execute("show create table child").rows[0][1]
        assert "foreign key (pid) references test.parent (id)" in out


class TestSavepoint:
    def test_rollback_to(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("begin")
        sess.execute("insert into t values (1)")
        sess.execute("savepoint s1")
        sess.execute("insert into t values (2)")
        assert sess.execute("select count(*) from t").rows == [(2,)]
        sess.execute("rollback to savepoint s1")
        assert sess.execute("select count(*) from t").rows == [(1,)]
        sess.execute("commit")
        assert sess.execute("select a from t").rows == [(1,)]

    def test_nested_savepoints(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("begin")
        sess.execute("savepoint s1")
        sess.execute("insert into t values (1)")
        sess.execute("savepoint s2")
        sess.execute("insert into t values (2)")
        sess.execute("rollback to s1")  # destroys s2 as well
        assert sess.execute("select count(*) from t").rows == [(0,)]
        with pytest.raises(ValueError, match="does not exist"):
            sess.execute("rollback to s2")
        sess.execute("rollback")

    def test_savepoint_before_first_write(self, sess):
        # table first touched AFTER the savepoint: rollback forgets the
        # shadow entirely
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (7)")
        sess.execute("begin")
        sess.execute("savepoint s1")
        sess.execute("delete from t")
        sess.execute("rollback to s1")
        assert sess.execute("select a from t").rows == [(7,)]
        sess.execute("commit")

    def test_release(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("begin")
        sess.execute("savepoint s1")
        sess.execute("insert into t values (1)")
        sess.execute("release savepoint s1")
        with pytest.raises(ValueError, match="does not exist"):
            sess.execute("rollback to s1")
        sess.execute("commit")
        assert sess.execute("select count(*) from t").rows == [(1,)]

    def test_unknown_savepoint(self, sess):
        sess.execute("begin")
        with pytest.raises(ValueError, match="does not exist"):
            sess.execute("rollback to nope")
        sess.execute("rollback")

    def test_savepoint_outside_txn_noop(self, sess):
        sess.execute("savepoint sx")  # MySQL: silent no-op in autocommit

    def test_rollback_to_keeps_conflict_baseline(self, sess):
        # a shadow rebuilt after ROLLBACK TO SAVEPOINT must still
        # conflict with commits that landed since the txn's first touch
        # (optimistic mode: under the pessimistic default the other
        # session would block on the table lock instead)
        sess.execute("create table t (a int)")
        sess.execute("set tidb_txn_mode = 'optimistic'")
        other = Session(sess.catalog)
        other.execute("set tidb_txn_mode = 'optimistic'")
        try:
            sess.execute("begin")
            sess.execute("savepoint s1")
            sess.execute("insert into t values (1)")
            other.execute("insert into t values (99)")  # concurrent commit
            sess.execute("rollback to s1")
            sess.execute("insert into t values (2)")  # shadow rebuilt
            with pytest.raises(RuntimeError, match="write conflict"):
                sess.execute("commit")
            assert other.execute("select a from t").rows == [(99,)]
        finally:
            sess.execute("set tidb_txn_mode = 'pessimistic'")

    def test_redeclare_moves(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("begin")
        sess.execute("insert into t values (1)")
        sess.execute("savepoint s1")
        sess.execute("insert into t values (2)")
        sess.execute("savepoint s1")  # moves s1 here
        sess.execute("insert into t values (3)")
        sess.execute("rollback to s1")
        assert sess.execute("select count(*) from t").rows == [(2,)]
        sess.execute("rollback")


class TestFKReferentialActions:
    """ON DELETE CASCADE / SET NULL (reference:
    pkg/executor/foreign_key.go FKCascadeExec); RESTRICT stays the
    default, and ON UPDATE actions are rejected at DDL."""

    @pytest.fixture()
    def env(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute("create table p (id int primary key, v int)")
        s.execute(
            "create table c (id int, pid int, constraint fc foreign key "
            "(pid) references p (id) on delete cascade)"
        )
        s.execute(
            "create table g (id int, cid int, constraint fg foreign key "
            "(cid) references c (id) on delete cascade)"
        )
        s.execute(
            "create table n (id int, pid int, constraint fn foreign key "
            "(pid) references p (id) on delete set null)"
        )
        s.execute("insert into p values (1, 10), (2, 20)")
        s.execute("insert into c values (100, 1), (101, 1), (102, 2)")
        s.execute("insert into g values (1000, 100), (1001, 102)")
        s.execute("insert into n values (5, 1), (6, 2)")
        return cat, s

    def test_cascade_transitive_and_set_null(self, env):
        _cat, s = env
        s.execute("delete from p where id = 1")
        assert s.execute("select id from c order by id").rows == [(102,)]
        assert s.execute("select id from g order by id").rows == [(1001,)]
        assert s.execute("select id, pid from n order by id").rows == [
            (5, None), (6, 2),
        ]

    def test_truncate_cascades(self, env):
        _cat, s = env
        s.execute("truncate table p")
        assert s.execute("select count(*) from c").rows == [(0,)]
        assert s.execute("select count(*) from g").rows == [(0,)]
        assert s.execute("select pid from n where pid is not null").rows == []

    def test_update_stays_restrict(self, env):
        _cat, s = env
        with pytest.raises(ValueError, match="restricts"):
            s.execute("update p set id = 9 where id = 1")

    def test_on_update_cascade_accepted_at_ddl(self, env):
        # formerly rejected; ON UPDATE actions are first-class now
        # (TestFKOnUpdateActions covers the runtime semantics)
        _cat, s = env
        s.execute(
            "create table okc (id int, pid int, constraint fb foreign "
            "key (pid) references p (id) on update cascade)"
        )
        t = _cat.table("test", "okc")
        assert t.fk_update_actions.get("fb") == "cascade"

    def test_show_create_and_persistence(self, env, tmp_path):
        cat, s = env
        ddl = s.execute("show create table c").rows[0][1]
        assert "on delete cascade" in ddl
        from tidb_tpu.storage.persist import load_catalog, save_catalog

        save_catalog(cat, str(tmp_path))
        cat2 = load_catalog(str(tmp_path))
        s2 = Session(cat2, db="test")
        s2.execute("delete from p where id = 1")
        assert s2.execute("select id from c order by id").rows == [(102,)]


class TestCompositeKeys:
    """Multi-column PK/UNIQUE enforcement across the whole conflict
    surface: plain INSERT, INSERT IGNORE, ON DUPLICATE KEY UPDATE, and
    REPLACE INTO (reference: the unique-key list walked by
    pkg/executor/replace.go removeRow; AddRecord duplicate checks in
    pkg/table/tables.go)."""

    def test_composite_pk_enforced(self, sess):
        sess.execute("create table t (a int, b int, c int, primary key (a, b))")
        sess.execute("insert into t values (1, 1, 10), (1, 2, 20)")
        with pytest.raises(ValueError, match="duplicate"):
            sess.execute("insert into t values (1, 2, 99)")
        # same first column, different second: NOT a duplicate
        sess.execute("insert into t values (1, 3, 30)")
        assert sess.execute("select count(*) from t").rows == [(3,)]

    def test_composite_unique_index(self, sess):
        sess.execute("create table t (a int, b int, v int)")
        sess.execute("create unique index uab on t (a, b)")
        sess.execute("insert into t values (1, 1, 10), (1, 2, 20), (2, 1, 30)")
        with pytest.raises(ValueError, match="duplicate"):
            sess.execute("insert into t values (2, 1, 99)")
        # a NULL in any component exempts the row, repeatedly
        sess.execute("insert into t values (2, null, 1), (2, null, 2)")
        assert sess.execute("select count(*) from t").rows == [(5,)]

    def test_composite_insert_ignore(self, sess):
        sess.execute("create table t (a int, b int, v int, primary key (a, b))")
        sess.execute("insert into t values (1, 1, 10)")
        sess.execute("insert ignore into t values (1, 1, 99), (1, 2, 20)")
        assert sess.execute(
            "select a, b, v from t order by a, b"
        ).rows == [(1, 1, 10), (1, 2, 20)]

    def test_composite_on_duplicate_key(self, sess):
        sess.execute("create table t (a int, b int, v int, primary key (a, b))")
        sess.execute("insert into t values (1, 1, 10), (1, 2, 20)")
        r = sess.execute(
            "insert into t values (1, 1, 99), (3, 3, 30) "
            "on duplicate key update v = values(v)"
        )
        assert r.affected == 3  # one update (2) + one insert (1)
        assert sess.execute(
            "select a, b, v from t order by a, b"
        ).rows == [(1, 1, 99), (1, 2, 20), (3, 3, 30)]

    def test_composite_replace_into(self, sess):
        sess.execute("create table t (a int, b int, v int)")
        sess.execute("create unique index uab on t (a, b)")
        sess.execute("insert into t values (1, 1, 10), (1, 2, 20)")
        sess.execute("replace into t values (1, 1, 99)")
        assert sess.execute(
            "select a, b, v from t order by a, b"
        ).rows == [(1, 1, 99), (1, 2, 20)]
        # statement-internal duplicate keys: last one wins
        sess.execute("replace into t values (5, 5, 1), (5, 5, 2)")
        assert sess.execute(
            "select v from t where a = 5 and b = 5"
        ).rows == [(2,)]

    def test_composite_pk_string_component(self, sess):
        sess.execute(
            "create table t (k varchar(10), n int, v int, primary key (k, n))"
        )
        sess.execute("insert into t values ('x', 1, 10), ('y', 1, 20)")
        with pytest.raises(ValueError, match="duplicate"):
            sess.execute("insert into t values ('x', 1, 99)")
        sess.execute("replace into t values ('x', 1, 99)")
        assert sess.execute(
            "select v from t where k = 'x' and n = 1"
        ).rows == [(99,)]

    def test_pk_rejects_null_components(self, sess):
        # MySQL: PRIMARY KEY implies NOT NULL on every component
        sess.execute("create table t (a int, b int, primary key (a, b))")
        with pytest.raises(ValueError, match="cannot be null"):
            sess.execute("insert into t values (1, null)")
        sess.execute("create table u (a int primary key)")
        with pytest.raises(ValueError, match="cannot be null"):
            sess.execute("insert into u values (null)")

    def test_composite_unique_index_over_altered_blocks(self, sess):
        # blocks written before ALTER ADD COLUMN lack the new column;
        # CREATE UNIQUE INDEX over it must treat those rows as NULL
        # (exempt), not crash
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1), (1)")
        sess.execute("alter table t add column b int")
        sess.execute("create unique index uab on t (a, b)")
        sess.execute("insert into t values (1, 2)")
        with pytest.raises(ValueError, match="duplicate"):
            sess.execute("insert into t values (1, 2)")

    def test_composite_key_date_component(self, sess):
        # raw-vs-encoded regression: DATE/DECIMAL key components must
        # conflict through REPLACE / IGNORE / ON DUP (the raw string
        # '1994-01-01' and the stored day int are the same key)
        sess.execute(
            "create table t (dt date, n int, v int, primary key (dt, n))"
        )
        sess.execute("insert into t values ('1994-01-01', 1, 10)")
        sess.execute("replace into t values ('1994-01-01', 1, 99)")
        assert sess.execute("select v from t").rows == [(99,)]
        sess.execute("insert ignore into t values ('1994-01-01', 1, 50)")
        assert sess.execute("select v from t").rows == [(99,)]
        sess.execute(
            "insert into t values ('1994-01-01', 1, 77) "
            "on duplicate key update v = values(v)"
        )
        assert sess.execute("select v from t").rows == [(77,)]

    def test_composite_key_decimal_component(self, sess):
        sess.execute(
            "create table t (d decimal(6,2), n int, v int, "
            "primary key (d, n))"
        )
        sess.execute("insert into t values (1.25, 1, 10)")
        with pytest.raises(ValueError, match="duplicate"):
            sess.execute("insert into t values (1.25, 1, 20)")
        sess.execute("replace into t values (1.25, 1, 30)")
        assert sess.execute("select v from t").rows == [(30,)]

    def test_composite_key_string_unseen_values(self, sess):
        # two DIFFERENT strings the dictionary has never seen must not
        # collide with each other; the SAME unseen string must dedupe
        sess.execute(
            "create table t (k varchar(8), n int, v int, primary key (k, n))"
        )
        sess.execute("replace into t values ('aa', 1, 1), ('bb', 1, 2)")
        assert sess.execute("select count(*) from t").rows == [(2,)]
        sess.execute("replace into t values ('cc', 1, 3), ('cc', 1, 4)")
        assert sess.execute(
            "select v from t where k = 'cc'"
        ).rows == [(4,)]

    def test_insert_ignore_null_pk_takes_implicit_default(self, sess):
        # MySQL IGNORE demotes the NULL-PK error to a warning and
        # inserts the column's IMPLICIT default (0 for ints) — the row
        # is kept, not dropped (advisor r3)
        sess.execute("create table t (a int, b int, v int, primary key (a, b))")
        sess.execute("insert ignore into t values (1, null, 9), (2, 2, 8)")
        assert sess.execute(
            "select a, b, v from t order by a"
        ).rows == [(1, 0, 9), (2, 2, 8)]
        # a second NULL in the same slot now COLLIDES with the implicit
        # default already stored — that duplicate is dropped
        sess.execute("insert ignore into t values (1, null, 7)")
        assert sess.execute(
            "select v from t where a = 1"
        ).rows == [(9,)]
        # string PK component: implicit default is ''
        sess.execute(
            "create table s (k varchar(8), n int, v int, primary key (k, n))"
        )
        sess.execute("insert ignore into s values (null, 1, 5)")
        assert sess.execute("select k, n, v from s").rows == [("", 1, 5)]

    def test_insert_ignore_null_pk_with_on_dup_updates(self, sess):
        # the implicit-default fill happens BEFORE ON DUPLICATE KEY
        # matching, so a NULL-keyed row updates the implicit-default row
        # (MySQL semantics) instead of erroring or being dropped
        sess.execute("create table t (a int, b int, v int, primary key (a, b))")
        sess.execute("insert into t values (1, 0, 5)")
        sess.execute(
            "insert ignore into t values (1, null, 9) "
            "on duplicate key update v = 99"
        )
        assert sess.execute("select a, b, v from t").rows == [(1, 0, 99)]


class TestFKOnUpdateActions:
    """ON UPDATE CASCADE / SET NULL referential actions
    (reference: pkg/executor/foreign_key.go onUpdate handling)."""

    def test_on_update_cascade_rewrites_child_keys(self, sess):
        sess.execute("create table p (id int primary key, v int)")
        sess.execute(
            "create table c (x int, pid int, constraint f foreign key "
            "(pid) references p (id) on update cascade)"
        )
        sess.execute("insert into p values (1, 10), (2, 20)")
        sess.execute("insert into c values (100, 1), (101, 1), (102, 2)")
        sess.execute("update p set id = 7 where id = 1")
        assert sess.execute(
            "select x, pid from c order by x"
        ).rows == [(100, 7), (101, 7), (102, 2)]
        # chain intact: further updates keep cascading
        sess.execute("update p set id = id + 100")
        assert sorted(
            r[1] for r in sess.execute("select x, pid from c").rows
        ) == [102, 107, 107]

    def test_on_update_set_null(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c (x int, pid int, constraint f foreign key "
            "(pid) references p (id) on update set null)"
        )
        sess.execute("insert into p values (1), (2)")
        sess.execute("insert into c values (100, 1), (101, 2)")
        sess.execute("update p set id = 9 where id = 1")
        assert sess.execute(
            "select x, pid from c order by x"
        ).rows == [(100, None), (101, 2)]

    def test_on_update_restrict_default(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c (pid int, constraint f foreign key (pid) "
            "references p (id))"
        )
        sess.execute("insert into p values (1)")
        sess.execute("insert into c values (1)")
        with pytest.raises(ValueError, match="restricts"):
            sess.execute("update p set id = 2 where id = 1")

    def test_on_update_cascade_rollback_on_failure(self, sess):
        from tidb_tpu.utils import failpoint

        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c (pid int, constraint f foreign key (pid) "
            "references p (id) on update cascade)"
        )
        sess.execute("insert into p values (1)")
        sess.execute("insert into c values (1)")
        failpoint.enable("fk/cascade-update", RuntimeError("boom"))
        try:
            with pytest.raises(RuntimeError, match="boom"):
                sess.execute("update p set id = 2 where id = 1")
        finally:
            failpoint.disable("fk/cascade-update")
        # the whole statement rolled back: parent AND child intact
        assert sess.execute("select id from p").rows == [(1,)]
        assert sess.execute("select pid from c").rows == [(1,)]

    def test_self_fk_on_update_set_null(self, sess):
        # self-FK: the SET NULL must survive the table rewrite
        sess.execute(
            "create table e (id int primary key, mgr int, constraint fm "
            "foreign key (mgr) references e (id) on update set null)"
        )
        sess.execute("insert into e values (1, null), (2, 1)")
        sess.execute("update e set id = 9 where id = 1")
        assert sess.execute(
            "select id, mgr from e order by id"
        ).rows == [(2, None), (9, None)]

    def test_set_null_not_leaked_when_restrict_sibling_fires(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c1 (pid int, constraint f1 foreign key (pid) "
            "references p (id) on update set null)"
        )
        sess.execute(
            "create table c2 (pid int, constraint f2 foreign key (pid) "
            "references p (id))"
        )
        sess.execute("insert into p values (1)")
        sess.execute("insert into c1 values (1)")
        sess.execute("insert into c2 values (1)")
        with pytest.raises(ValueError, match="restricts"):
            sess.execute("update p set id = 2 where id = 1")
        # the RESTRICT sibling aborted the statement; c1 must be intact
        assert sess.execute("select pid from c1").rows == [(1,)]

    def test_cascade_to_null_nulls_child(self, sess):
        sess.execute("create table p (id int primary key, r int)")
        sess.execute(
            "create table c (rid int, constraint f foreign key (rid) "
            "references p (r) on update cascade)"
        )
        sess.execute("insert into p values (1, 5)")
        sess.execute("insert into c values (5)")
        sess.execute("update p set r = null where id = 1")
        assert sess.execute("select rid from c").rows == [(None,)]

    def test_partial_rewrite_of_nonunique_key_is_ambiguous(self, sess):
        sess.execute("create table p (pk int primary key, r int)")
        sess.execute(
            "create table c (rid int, constraint f foreign key (rid) "
            "references p (r) on update cascade)"
        )
        sess.execute("insert into p values (1, 7), (2, 7)")
        sess.execute("insert into c values (7)")
        with pytest.raises(ValueError, match="ambiguous"):
            sess.execute("update p set r = 8 where pk = 1")

    def test_mixed_case_constraint_name_cascades(self, sess):
        # fk_update_actions is keyed lowercase; a mixed-case constraint
        # name must not silently degrade CASCADE to RESTRICT
        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c (pid int, constraint MyFK foreign key (pid) "
            "references p (id) on update cascade)"
        )
        sess.execute("insert into p values (1)")
        sess.execute("insert into c values (1)")
        sess.execute("update p set id = 3 where id = 1")
        assert sess.execute("select pid from c").rows == [(3,)]
