"""Engine pool failover + failed-engine prober (MPP resilience analog).

Reference: GlobalMPPFailedStoreProber (pkg/store/copr/mpp_probe.go:33)
detect/recover semantics, ExecutorWithRetry + RecoveryHandler
(pkg/executor/internal/mpp/recovery_handler.go:26) retry-on-surviving-
stores. TPU analog in server/engine_pool.py over the plan IR seam.
"""

import time

import pytest

from tidb_tpu.parser.sqlparse import parse
from tidb_tpu.planner.logical import build_query
from tidb_tpu.server.engine_pool import (
    EngineEndpoint,
    FailedEngineProber,
    PooledEngineClient,
)
from tidb_tpu.server.engine_rpc import EngineServer, SchemaOutOfDateError
from tidb_tpu.session.session import Session
from tidb_tpu.utils import failpoint

Q = "select b, count(*) from t group by b order by b"


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create table t (a int, b varchar(8))")
    s.execute("insert into t values (1,'x'),(2,'y'),(3,'x')")
    return s


def _plan(sess, q=Q):
    return build_query(
        parse(q)[0], sess.catalog, "test", sess._scalar_subquery
    )


def _server(sess):
    srv = EngineServer(sess.catalog, port=0)
    srv.start_background()
    return srv


EXPECT = [("x", 2), ("y", 1)]


class TestPoolDispatch:
    def test_round_robin_over_alive_engines(self, sess):
        s1, s2 = _server(sess), _server(sess)
        pool = PooledEngineClient(
            [("127.0.0.1", s1.port), ("127.0.0.1", s2.port)]
        )
        try:
            for _ in range(4):
                cols, rows = pool.execute_plan(_plan(sess))
                assert sorted(rows) == EXPECT
            # both endpoints stayed alive and in rotation
            assert len(pool.alive_endpoints()) == 2
        finally:
            pool.close()
            s1.shutdown()
            s2.shutdown()

    def test_failover_on_dead_engine(self, sess):
        s1, s2 = _server(sess), _server(sess)
        pool = PooledEngineClient(
            [("127.0.0.1", s1.port), ("127.0.0.1", s2.port)]
        )
        try:
            s1.shutdown()  # first dispatch target dies
            for _ in range(3):  # every call still answers
                cols, rows = pool.execute_plan(_plan(sess))
                assert sorted(rows) == EXPECT
            # the dead endpoint was quarantined by the prober
            failed = pool.prober.failed_endpoints()
            assert [ep.port for ep in failed] == [s1.port]
            assert ep_state(pool, s1.port) is False
        finally:
            pool.close()
            s2.shutdown()

    def test_all_engines_down_raises(self, sess):
        s1 = _server(sess)
        pool = PooledEngineClient([("127.0.0.1", s1.port)], max_retry=2)
        try:
            s1.shutdown()
            with pytest.raises(ConnectionError, match="no alive engine"):
                pool.execute_plan(_plan(sess))
        finally:
            pool.close()

    def test_execution_error_does_not_fail_over(self, sess):
        """A plan that errors on the engine (missing table) must raise,
        not quarantine the engine: it would fail identically on every
        replica."""
        s1 = _server(sess)
        other = Session()
        other.execute("create table t (a int, b varchar(8))")
        other.execute("create table only_here (z int)")
        pool = PooledEngineClient([("127.0.0.1", s1.port)])
        try:
            plan = _plan(other, "select z from only_here")
            with pytest.raises(RuntimeError):
                pool.execute_plan(plan)
            assert len(pool.alive_endpoints()) == 1  # still alive
        finally:
            pool.close()
            s1.shutdown()

    def test_schema_out_of_date_propagates(self, sess):
        s1 = _server(sess)
        pool = PooledEngineClient([("127.0.0.1", s1.port)])
        try:
            with pytest.raises(SchemaOutOfDateError):
                pool.execute_plan(_plan(sess), schema_version=10**9)
            assert len(pool.alive_endpoints()) == 1
        finally:
            pool.close()
            s1.shutdown()


def ep_state(pool, port):
    for ep in pool.endpoints:
        if ep.port == port:
            return ep.alive
    raise AssertionError(f"no endpoint on port {port}")


class TestProber:
    def test_recovery_after_restart(self, sess):
        s1, s2 = _server(sess), _server(sess)
        prober = FailedEngineProber(initial_backoff_s=0.01)
        pool = PooledEngineClient(
            [("127.0.0.1", s1.port), ("127.0.0.1", s2.port)],
            prober=prober,
        )
        try:
            port1 = s1.port
            s1.shutdown()
            pool.execute_plan(_plan(sess))  # triggers detect
            assert ep_state(pool, port1) is False
            # engine comes back on the SAME address (store restart)
            time.sleep(0.02)
            s1b = EngineServer(sess.catalog, port=port1)
            s1b.start_background()
            try:
                deadline = time.time() + 5
                while time.time() < deadline and not ep_state(pool, port1):
                    prober.probe_once()
                    time.sleep(0.02)
                assert ep_state(pool, port1) is True
                assert prober.failed_endpoints() == []
                # recovered endpoint serves traffic again
                for _ in range(2):
                    cols, rows = pool.execute_plan(_plan(sess))
                    assert sorted(rows) == EXPECT
            finally:
                s1b.shutdown()
        finally:
            pool.close()
            s2.shutdown()

    def test_probe_backoff_doubles_until_cap(self):
        prober = FailedEngineProber(
            initial_backoff_s=1.0, max_backoff_s=4.0
        )
        ep = EngineEndpoint("127.0.0.1", 1)  # nothing listens
        prober.detect(ep)
        assert ep.probe_backoff_s == 1.0
        t0 = ep.next_probe
        prober.probe_once(now=t0)  # due -> ping fails -> backoff doubles
        assert ep.probe_backoff_s == 2.0
        prober.probe_once(now=ep.next_probe)
        assert ep.probe_backoff_s == 4.0
        prober.probe_once(now=ep.next_probe)
        assert ep.probe_backoff_s == 4.0  # capped

    def test_probe_respects_backoff_window(self):
        prober = FailedEngineProber(initial_backoff_s=3600.0)
        ep = EngineEndpoint("127.0.0.1", 1)
        prober.detect(ep)
        # not due yet: probe_once must not ping (failpoint would count)
        calls = []
        failpoint.enable("engine/probe-fail", lambda: calls.append(1))
        try:
            prober.probe_once()
            assert calls == []
        finally:
            failpoint.disable("engine/probe-fail")

    def test_detect_idempotent(self):
        prober = FailedEngineProber()
        ep = EngineEndpoint("127.0.0.1", 1)
        prober.detect(ep)
        prober.detect(ep)
        assert len(prober.failed_endpoints()) == 1
        assert ep.detect_count == 1

    def test_background_prober_thread(self, sess):
        s1 = _server(sess)
        prober = FailedEngineProber(
            initial_backoff_s=0.01, interval_s=0.02
        )
        pool = PooledEngineClient(
            [("127.0.0.1", s1.port)], prober=prober
        )
        try:
            port1 = s1.port
            s1.shutdown()
            with pytest.raises(ConnectionError):
                pool.execute_plan(_plan(sess))
            s1b = EngineServer(sess.catalog, port=port1)
            s1b.start_background()
            try:
                deadline = time.time() + 5
                while time.time() < deadline and not ep_state(pool, port1):
                    time.sleep(0.02)  # daemon thread recovers it
                assert ep_state(pool, port1) is True
                cols, rows = pool.execute_plan(_plan(sess))
                assert sorted(rows) == EXPECT
            finally:
                s1b.shutdown()
        finally:
            pool.close()
