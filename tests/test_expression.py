"""Expression kernel tests (reference model: pkg/expression/builtin_*_vec.go
unit tests and pkg/util/chunk/chunk_test.go)."""

import numpy as np
import pytest

from tidb_tpu import DECIMAL, FLOAT64, INT64, STRING, DATE
from tidb_tpu.chunk import HostBlock, block_to_batch, column_from_values
from tidb_tpu.expression import ColumnRef, Func, Literal, bind_expr, compile_expr


def make_batch(cols, types):
    block = HostBlock.from_columns(
        {k: column_from_values(v, types[k]) for k, v in cols.items()}
    )
    dicts = {
        k: c.dictionary
        for k, c in block.columns.items()
        if c.dictionary is not None
    }
    return block_to_batch(block), {k: t for k, t in types.items()}, dicts, block.nrows


def run(expr, cols, types):
    batch, schema, dicts, n = make_batch(cols, types)
    bound = bind_expr(expr, schema)
    out = compile_expr(bound, dicts)(batch)
    return np.asarray(out.data)[:n], np.asarray(out.valid)[:n], bound.type


def col(name):
    return ColumnRef(name=name)


def lit(v):
    return Literal(value=v)


def f(op, *args):
    return Func(op=op, args=tuple(args))


class TestArith:
    def test_add_int(self):
        d, v, t = run(
            f("add", col("a"), col("b")),
            {"a": [1, 2, None], "b": [10, 20, 30]},
            {"a": INT64, "b": INT64},
        )
        assert t == INT64
        np.testing.assert_array_equal(d[:2], [11, 22])
        np.testing.assert_array_equal(v, [True, True, False])

    def test_decimal_mul_scale(self):
        # 1.50 * 0.10 = 0.1500 (scale 2 * scale 2 -> scale 4)
        d, v, t = run(
            f("mul", col("p"), col("d")),
            {"p": [1.50], "d": [0.10]},
            {"p": DECIMAL(2), "d": DECIMAL(2)},
        )
        assert t == DECIMAL(4)
        assert d[0] == 1500

    def test_decimal_add_rescale(self):
        d, v, t = run(
            f("add", col("a"), col("b")),
            {"a": [1.5], "b": [0.25]},
            {"a": DECIMAL(1), "b": DECIMAL(2)},
        )
        assert t == DECIMAL(2)
        assert d[0] == 175

    def test_div_null_on_zero(self):
        d, v, t = run(
            f("div", col("a"), col("b")),
            {"a": [10, 10], "b": [4, 0]},
            {"a": INT64, "b": INT64},
        )
        assert t == FLOAT64
        assert d[0] == 2.5
        assert not v[1]


class TestLogic:
    def test_three_valued_and(self):
        d, v, _ = run(
            f("and", f("gt", col("a"), lit(0)), f("gt", col("b"), lit(0))),
            {"a": [1, 1, -1, None], "b": [1, None, None, None]},
            {"a": INT64, "b": INT64},
        )
        # true, null, false (a>0 false dominates), null
        assert d[0] and v[0]
        assert not v[1]
        assert not d[2] and v[2]
        assert not v[3]

    def test_case_when(self):
        d, v, _ = run(
            f("case", f("lt", col("a"), lit(0)), lit(-1), f("gt", col("a"), lit(0)), lit(1), lit(0)),
            {"a": [-5, 7, 0, None]},
            {"a": INT64},
        )
        np.testing.assert_array_equal(d[:3], [-1, 1, 0])
        assert v[3] and d[3] == 0  # null cond -> false -> ELSE


class TestStrings:
    def test_eq_and_order(self):
        d, v, _ = run(
            f("eq", col("s"), lit("banana")),
            {"s": ["apple", "banana", "cherry", None]},
            {"s": STRING},
        )
        np.testing.assert_array_equal(d[:3], [False, True, False])
        assert not v[3]

        d, _, _ = run(
            f("lt", col("s"), lit("bb")),
            {"s": ["apple", "banana", "cherry"]},
            {"s": STRING},
        )
        np.testing.assert_array_equal(d, [True, True, False])

    def test_like(self):
        d, _, _ = run(
            f("like", col("s"), lit("%an%")),
            {"s": ["banana", "cherry", "mango"]},
            {"s": STRING},
        )
        np.testing.assert_array_equal(d, [True, False, True])

    def test_in_strings(self):
        d, _, _ = run(
            f("in", col("s"), lit("a"), lit("c")),
            {"s": ["a", "b", "c"]},
            {"s": STRING},
        )
        np.testing.assert_array_equal(d, [True, False, True])


class TestDates:
    def test_extract(self):
        d, _, _ = run(
            f("year", col("d")),
            {"d": ["1994-01-01", "1998-12-31", "1970-01-01", "2024-02-29"]},
            {"d": DATE},
        )
        np.testing.assert_array_equal(d, [1994, 1998, 1970, 2024])
        d, _, _ = run(
            f("month", col("d")),
            {"d": ["1994-01-01", "1998-12-31", "2024-02-29"]},
            {"d": DATE},
        )
        np.testing.assert_array_equal(d, [1, 12, 2])
        d, _, _ = run(
            f("day", col("d")),
            {"d": ["1994-01-15", "1998-12-31", "2024-02-29"]},
            {"d": DATE},
        )
        np.testing.assert_array_equal(d, [15, 31, 29])

    def test_date_compare_literal(self):
        from tidb_tpu.dtypes import date_to_days

        d, _, _ = run(
            f("lt", col("d"), lit(int(date_to_days("1995-01-01")))),
            {"d": ["1994-06-01", "1996-01-01"]},
            {"d": DATE},
        )
        np.testing.assert_array_equal(d, [True, False])


class TestMisc:
    def test_cast_string_to_float(self):
        d, _, t = run(
            Func(op="cast", args=(col("s"),), type=FLOAT64),
            {"s": ["1.5", "2", "-3.25"]},
            {"s": STRING},
        )
        np.testing.assert_allclose(d, [1.5, 2.0, -3.25])

    def test_coalesce(self):
        d, v, _ = run(
            f("coalesce", col("a"), col("b")),
            {"a": [None, 2, None], "b": [7, 9, None]},
            {"a": INT64, "b": INT64},
        )
        np.testing.assert_array_equal(d[:2], [7, 2])
        assert not v[2]


class TestReviewFixes:
    """Regressions from the first code review pass."""

    def test_float_mod(self):
        d, v, _ = run(
            f("mod", col("a"), col("b")),
            {"a": [5.5, -5.0], "b": [2.0, 3.0]},
            {"a": FLOAT64, "b": FLOAT64},
        )
        np.testing.assert_allclose(d, [1.5, -2.0])

    def test_intdiv_mod_truncate_toward_zero(self):
        d, _, t = run(
            f("intdiv", col("a"), col("b")),
            {"a": [-7, 7], "b": [2, 2]},
            {"a": INT64, "b": INT64},
        )
        assert t == INT64
        np.testing.assert_array_equal(d, [-3, 3])
        d, _, _ = run(
            f("mod", col("a"), col("b")),
            {"a": [-5, 5], "b": [3, -3]},
            {"a": INT64, "b": INT64},
        )
        np.testing.assert_array_equal(d, [-2, 2])

    def test_intdiv_decimal_is_integer(self):
        d, _, t = run(
            f("intdiv", col("a"), col("b")),
            {"a": [5.00], "b": [2.00]},
            {"a": DECIMAL(2), "b": DECIMAL(2)},
        )
        assert t == INT64
        assert d[0] == 2

    def test_date_vs_string_literal(self):
        d, _, _ = run(
            f("lt", col("d"), lit("1995-01-01")),
            {"d": ["1994-06-01", "1996-01-01"]},
            {"d": DATE},
        )
        np.testing.assert_array_equal(d, [True, False])

    def test_string_eq_null_literal(self):
        d, v, _ = run(
            f("eq", col("s"), lit(None)),
            {"s": ["None", "a"]},
            {"s": STRING},
        )
        np.testing.assert_array_equal(v, [False, False])

    def test_in_with_null(self):
        d, v, _ = run(
            f("in", col("a"), lit(1), lit(None)),
            {"a": [1, 2]},
            {"a": INT64},
        )
        assert d[0] and v[0]
        assert not v[1]  # no match + NULL in list -> NULL
