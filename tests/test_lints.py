"""Tier-1 gate for the unified lint runner (scripts/lint_all.py) and
the concurrency lint's four rules (scripts/check_concurrency.py).

One test file guards EVERY discovered scripts/check_*.py — a future
lint dropped into scripts/ is enforced here with no new test file.
Each concurrency rule additionally proves it rejects a seeded
violation (fixture trees through the checker, the test_flight_phases
pattern) and that its marker/idiom escapes work.
"""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "scripts", "lint_all.py")
LINT = os.path.join(REPO, "scripts", "check_concurrency.py")


def run_lint(root):
    return subprocess.run(
        [sys.executable, LINT, str(root)],
        capture_output=True, text=True, timeout=120,
    )


def test_all_lints_clean_at_head():
    proc = subprocess.run(
        [sys.executable, RUNNER, REPO], capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, (
        f"lint_all failures:\n{proc.stdout}{proc.stderr}"
    )


def test_runner_lists_every_check_script():
    proc = subprocess.run(
        [sys.executable, RUNNER, "--list"], capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == 0
    listed = set(proc.stdout.split())
    on_disk = {
        fn for fn in os.listdir(os.path.join(REPO, "scripts"))
        if fn.startswith("check_") and fn.endswith(".py")
    }
    assert listed == on_disk
    assert "check_concurrency.py" in listed


def test_runner_fails_on_first_failure(tmp_path):
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "check_aaa.py").write_text("import sys; sys.exit(0)\n")
    (scripts / "check_bbb.py").write_text(
        "print('seeded violation'); import sys; sys.exit(1)\n"
    )
    (scripts / "check_ccc.py").write_text("import sys; sys.exit(0)\n")
    (scripts / "lint_all.py").write_text(
        open(RUNNER, encoding="utf-8").read()
    )
    proc = subprocess.run(
        [sys.executable, str(scripts / "lint_all.py"), str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "[FAIL] check_bbb.py" in proc.stdout
    assert "seeded violation" in proc.stdout
    # stopped at the first failure: ccc never ran
    assert "check_ccc" not in proc.stdout


# ---------------------------------------------------------------------------
# concurrency lint fixtures
# ---------------------------------------------------------------------------

_RACECHECK_STUB = textwrap.dedent(
    '''
    LOCK_CLASSES = {
        "a": "fixture class a",
        "b": "fixture class b",
    }
    THREAD_NAME_PREFIXES = frozenset({"good"})

    def make_lock(name):
        pass

    def make_rlock(name):
        pass

    def make_condition(name):
        pass
    '''
)


def make_tree(tmp_path, engine_source, racecheck_src=_RACECHECK_STUB):
    utils = tmp_path / "tidb_tpu" / "utils"
    utils.mkdir(parents=True)
    (utils / "racecheck.py").write_text(racecheck_src)
    (tmp_path / "tidb_tpu" / "engine.py").write_text(
        textwrap.dedent(engine_source)
    )
    return tmp_path


def test_rule1_raw_lock_and_undeclared_class_rejected(tmp_path):
    make_tree(
        tmp_path,
        '''
        import threading
        from tidb_tpu.utils.racecheck import make_lock

        raw = threading.Lock()
        raw_cv = threading.Condition()
        ok = make_lock("a")
        ok2 = make_lock("b")
        typo = make_lock("not-declared")
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    assert "raw threading.Lock() construction" in proc.stdout
    assert "raw threading.Condition() construction" in proc.stdout
    assert "'not-declared'" in proc.stdout
    # declared + constructed classes are clean
    assert "make_lock('a')" not in proc.stdout


def test_rule1_dead_declaration_and_nonliteral_rejected(tmp_path):
    make_tree(
        tmp_path,
        '''
        from tidb_tpu.utils.racecheck import make_lock

        name = "a"
        lk = make_lock(name)
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    assert "non-literal lock class" in proc.stdout
    # neither "a" nor "b" has a literal construction site
    assert "dead declaration" in proc.stdout
    assert "'b'" in proc.stdout


def test_rule2_blocking_under_lock_needs_marker(tmp_path):
    make_tree(
        tmp_path,
        '''
        import time
        from tidb_tpu.utils.racecheck import make_lock

        class S:
            def __init__(self):
                self._lock = make_lock("a")
                self._other = make_lock("b")

            def bad(self):
                with self._lock:
                    time.sleep(1)

            def justified(self):
                with self._other:
                    # lock-blocking-ok: fixture justification
                    time.sleep(1)
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    assert "blocking call sleep() under lock" in proc.stdout
    assert "S.bad" in proc.stdout
    assert "S.justified" not in proc.stdout  # marker escape honored


def test_rule2_same_object_cv_wait_is_the_idiom(tmp_path):
    make_tree(
        tmp_path,
        '''
        from tidb_tpu.utils.racecheck import make_condition

        class S:
            def __init__(self):
                self._cv = make_condition("a")
                self._other_cv = make_condition("b")

            def fine(self):
                with self._cv:
                    self._cv.wait(0.1)

            def bad(self):
                with self._cv:
                    self._other_cv.wait(0.1)
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    out = proc.stdout
    assert "S.fine" not in out     # waiting on the held cv is allowed
    assert "wait() under lock" in out and "S.bad" in out


def test_rule3_static_cycle_detected(tmp_path):
    make_tree(
        tmp_path,
        '''
        from tidb_tpu.utils.racecheck import make_lock

        class S:
            def __init__(self):
                self._a_lock = make_lock("a")
                self._b_lock = make_lock("b")

            def forward(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def reversed_(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    assert "static lock-order cycle" in proc.stdout
    assert "a -> b" in proc.stdout or "b -> a" in proc.stdout


def test_rule3_consistent_order_is_clean(tmp_path):
    make_tree(
        tmp_path,
        '''
        from tidb_tpu.utils.racecheck import make_lock

        class S:
            def __init__(self):
                self._a_lock = make_lock("a")
                self._b_lock = make_lock("b")

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 0, proc.stdout


def test_rule3_one_level_interprocedural_cycle(tmp_path):
    make_tree(
        tmp_path,
        '''
        from tidb_tpu.utils.racecheck import make_lock

        a_lock = make_lock("a")
        b_lock = make_lock("b")

        def inner():
            with a_lock:
                pass

        def outer():
            with b_lock:
                inner()

        def forward():
            with a_lock:
                with b_lock:
                    pass
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    assert "static lock-order cycle" in proc.stdout


def test_rule4_thread_hygiene(tmp_path):
    make_tree(
        tmp_path,
        '''
        import threading

        from tidb_tpu.utils.racecheck import make_lock

        _ = make_lock("a")
        __ = make_lock("b")

        t1 = threading.Thread(target=print)  # no daemon, no name
        t2 = threading.Thread(
            target=print, daemon=True, name="rogue-worker"
        )
        t3 = threading.Thread(
            target=print, daemon=True, name="good-worker"
        )
        t4 = threading.Thread(  # thread-non-daemon-ok
            target=print, daemon=False, name="good-flusher"
        )
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    out = proc.stdout
    assert "without daemon=True" in out
    assert "without a literal name=" in out
    assert "'rogue'" in out           # undeclared prefix
    assert "good-worker" not in out   # declared prefix is clean
    # exactly ONE daemon violation (t1): t4's marker escape honored
    assert out.count("without daemon=True") == 1


def test_rule2_acquire_release_span_is_a_lock_scope(tmp_path):
    """Explicit acquire()/release() spans get the same rule-2
    treatment as `with` scopes — the lint's coverage claim, not just
    the common idiom."""
    make_tree(
        tmp_path,
        '''
        import time
        from tidb_tpu.utils.racecheck import make_lock

        class S:
            def __init__(self):
                self._lock = make_lock("a")
                self._other = make_lock("b")

            def bad(self):
                self._lock.acquire()
                time.sleep(1)
                self._lock.release()

            def fine(self):
                self._other.acquire()
                x = 1 + 1
                self._other.release()
                time.sleep(x)  # after release: not under the lock

            def branchy(self):
                if True:
                    self._lock.acquire()
                    time.sleep(2)
                else:
                    self._lock.acquire()
                self._lock.release()
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    out = proc.stdout
    assert "blocking call sleep() under lock" in out and "S.bad" in out
    assert "S.fine" not in out
    # a re-acquire in another branch must not drop the first span's
    # recorded calls (span overwrite false negative)
    assert "S.branchy" in out


def test_rule4_thread_subclass_super_init_covered(tmp_path):
    """A `class X(threading.Thread)` defines its name/daemon in
    super().__init__ — rule 4 must see that call, or subclasses escape
    the hygiene contract (the InstanceWatchdog pattern)."""
    make_tree(
        tmp_path,
        '''
        import threading

        from tidb_tpu.utils.racecheck import make_lock

        _ = make_lock("a")
        __ = make_lock("b")

        class Rogue(threading.Thread):
            def __init__(self):
                super().__init__(daemon=True, name="rogue-sub")

        class Fine(threading.Thread):
            def __init__(self):
                super().__init__(daemon=True, name="good-sub")

        class NotAThread:
            def __init__(self):
                super().__init__()
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    out = proc.stdout
    assert "'rogue'" in out           # subclass kwargs are checked
    assert "good-sub" not in out      # compliant subclass is clean
    # plain super().__init__ outside a Thread subclass is ignored
    assert "NotAThread" not in out and out.count("name=") == 0


def test_head_has_no_raw_locks_outside_racecheck():
    """The acceptance bar, asserted directly: zero raw threading
    lock constructions under tidb_tpu/ outside utils/racecheck.py."""
    import re

    pat = re.compile(r"threading\.(Lock|RLock|Condition)\(")
    offenders = []
    for dirpath, dirnames, filenames in os.walk(
        os.path.join(REPO, "tidb_tpu")
    ):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if path.endswith(os.path.join("utils", "racecheck.py")):
                continue
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if pat.search(line) and "make_" not in line:
                        offenders.append(f"{path}:{i}")
    assert not offenders, offenders


def test_rule3_sees_method_defined_above_init(tmp_path):
    """fn_acquires must resolve AFTER the full file visit: a method
    using `with self._lock:` textually above the __init__ that
    constructs the lock still contributes its interprocedural edge
    (eager resolution dropped it, letting this cycle pass clean)."""
    make_tree(
        tmp_path,
        '''
        from tidb_tpu.utils.racecheck import make_lock

        class Engine:
            def _bump(self):  # defined ABOVE __init__
                with self._a_lock:
                    pass

            def __init__(self):
                self._a_lock = make_lock("a")
                self._b_lock = make_lock("b")

            def fwd(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def rev(self):
                with self._b_lock:
                    self._bump()
        ''',
    )
    proc = run_lint(tmp_path)
    assert proc.returncode == 1
    assert "static lock-order cycle" in proc.stdout


def _load_checker():
    import importlib.util

    spec = importlib.util.spec_from_file_location("_cc_test", LINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_rule3_deep_edges_participate_in_cycle_check(tmp_path):
    """A declared DEEP_EDGES entry (an edge below the one-level
    interprocedural horizon) completes cycles the scope pass alone
    cannot see, and undeclared endpoints are themselves violations."""
    make_tree(
        tmp_path,
        '''
        from tidb_tpu.utils.racecheck import make_lock

        class Engine:
            def __init__(self):
                self._a_lock = make_lock("a")
                self._b_lock = make_lock("b")

            def fwd(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        ''',
    )
    mod = _load_checker()
    mod.DEEP_EDGES = [("a", "b", "tidb_tpu/engine.py")]
    msgs = [m for _, _, m in mod.check(str(tmp_path))]
    assert any("static lock-order cycle" in m for m in msgs), msgs

    mod.DEEP_EDGES = [("a", "nope", "tidb_tpu/engine.py")]
    msgs = [m for _, _, m in mod.check(str(tmp_path))]
    assert any("undeclared lock class 'nope'" in m for m in msgs), msgs

    # an entry citing a file absent from the tree neither applies nor
    # fails validation (lint fixture trees)
    mod.DEEP_EDGES = [("a", "nope", "tidb_tpu/not_there.py")]
    msgs = [m for _, _, m in mod.check(str(tmp_path))]
    assert not any("undeclared" in m for m in msgs), msgs


def test_rule3_bare_local_lock_names_are_function_scoped(tmp_path):
    """The same bare local name bound to DIFFERENT classes in two
    functions must not share one file-global lock_vars entry — that
    fabricated edges (failing the lint on a runtime-impossible cycle)
    and dropped the first function's real edges."""
    stub = textwrap.dedent(
        '''
        LOCK_CLASSES = {"a": "x", "b": "y", "c": "z"}
        THREAD_NAME_PREFIXES = frozenset({"good"})

        def make_lock(name):
            pass

        def make_rlock(name):
            pass

        def make_condition(name):
            pass
        '''
    )
    clean = make_tree(
        tmp_path / "clean",
        '''
        from tidb_tpu.utils.racecheck import make_lock

        C_LOCK = make_lock("c")

        def f():
            lk = make_lock("a")
            with lk:          # a -> c
                with C_LOCK:
                    pass

        def g():
            lk = make_lock("b")
            with C_LOCK:      # c -> b: no cycle unless f's lk
                with lk:      # is mislabeled as class b
                    pass
        ''',
        racecheck_src=stub,
    )
    proc = run_lint(clean)
    assert proc.returncode == 0, proc.stdout

    # a REAL inversion through bare locals is still caught
    stub2 = stub.replace('"b": "y", ', "")
    bad = make_tree(
        tmp_path / "bad",
        '''
        from tidb_tpu.utils.racecheck import make_lock

        C_LOCK = make_lock("c")

        def f():
            lk = make_lock("a")
            with lk:
                with C_LOCK:
                    pass

        def g():
            other_lk = make_lock("a")
            with C_LOCK:
                with other_lk:
                    pass
        ''',
        racecheck_src=stub2,
    )
    proc = run_lint(bad)
    assert proc.returncode == 1
    assert "static lock-order cycle" in proc.stdout


def test_failpoint_lint_does_not_poison_sys_modules(tmp_path):
    """check_failpoints.load_sites registers stub tidb_tpu modules to
    read SITES without importing jax; the stubs must be removed again
    or an in-process caller's later REAL `import tidb_tpu.x` breaks
    (a ModuleType without __path__ is not a package)."""
    code = textwrap.dedent(
        f'''
        import importlib.util, sys
        spec = importlib.util.spec_from_file_location(
            "_cf", {os.path.join(REPO, "scripts", "check_failpoints.py")!r}
        )
        cf = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cf)
        sites = cf.load_sites({REPO!r})
        assert sites, "no failpoint sites loaded"
        assert "tidb_tpu" not in sys.modules, "stub package leaked"
        assert "tidb_tpu.utils" not in sys.modules, "stub subpackage leaked"
        sys.path.insert(0, {REPO!r})
        import tidb_tpu.utils.metrics  # must be importable afterwards
        '''
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240,
    )
    assert proc.returncode == 0, proc.stderr
