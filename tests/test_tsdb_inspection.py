"""PR 12: metric time-series store (obs/tsdb.py), metrics_schema
virtual tables with predicate pushdown, statements_summary_history,
and the inspection engine (obs/inspection.py).

Reference: pkg/infoschema/metrics_schema.go (Prometheus history as SQL)
and pkg/executor/inspection_result.go (rules reading it back). The
chaos-driven acceptance tier (fault class -> finding) also lives here
over the in-process fleet; the 2-process dryrun is in
test_multihost.py.
"""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from tidb_tpu.obs.tsdb import (
    SAMPLER,
    TSDB,
    TimeSeriesStore,
    TsdbSampler,
    clear_scan_hint,
    scan_hint_for,
    set_scan_hint,
)
from tidb_tpu.utils import racecheck
from tidb_tpu.utils.metrics import (
    REGISTRY,
    Registry,
    StmtHistory,
    StmtSummary,
    sample_rows,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def sess():
    from tidb_tpu.session import Session

    s = Session()
    s.execute("create table t (a int, b varchar(8))")
    s.execute("insert into t values (1,'x'),(2,'y'),(3,'x')")
    return s


# ---------------------------------------------------------------------------
# store unit tier
# ---------------------------------------------------------------------------


class TestTimeSeriesStore:
    def test_sample_rows_covers_all_kinds(self):
        reg = Registry()
        reg.counter("tidbtpu_session_statements_total").inc(3)
        reg.gauge("tidbtpu_dcn_hosts_alive").set(2)
        reg.histogram("tidbtpu_flight_query_seconds").observe(0.5)
        reg.counter(
            "tidbtpu_dcn_dispatches", labels=("host",)
        ).labels(host="w1").inc()
        rows = {(r[0], r[2]): (r[3], r[4]) for r in sample_rows(reg)}
        assert rows[("tidbtpu_session_statements_total", ())] == (
            3.0, "counter"
        )
        assert rows[("tidbtpu_dcn_hosts_alive", ())] == (2.0, "gauge")
        # histograms decompose into count/sum stat series
        assert rows[
            ("tidbtpu_flight_query_seconds", ("count",))
        ] == (1.0, "histogram")
        assert rows[
            ("tidbtpu_flight_query_seconds", ("sum",))
        ] == (0.5, "histogram")
        assert rows[("tidbtpu_dcn_dispatches", ("w1",))][0] == 1.0

    def test_retention_ring_and_downsample_bounds(self):
        store = TimeSeriesStore(
            retention_points=8, downsample_every=4
        )
        reg = Registry()
        c = reg.counter("tidbtpu_session_statements_total")
        for i in range(64):
            c.inc()
            store.sample_registry(registry=reg, now=1000.0 + i)
        key = (
            "tidbtpu_session_statements_total", "coordinator", (), (),
        )
        s = store._series[key]
        assert len(s.raw) == 8          # raw ring bounded
        assert len(s.coarse) <= 8       # coarse ring bounded
        # counters downsample to the LAST cumulative value of the fold
        pts = store.query("tidbtpu_session_statements_total")
        raw = [p for p in pts if p[4] == "raw"]
        ds = [p for p in pts if p[4] == "ds"]
        assert len(raw) == 8 and ds
        assert raw[-1][3] == 64.0
        # downsampled values are cumulative (monotone) too
        assert [p[3] for p in ds] == sorted(p[3] for p in ds)
        # total memory stays bounded no matter how many samples landed
        assert store.point_count() <= 16

    def test_gauge_downsample_keeps_mean(self):
        store = TimeSeriesStore(retention_points=4, downsample_every=4)
        reg = Registry()
        g = reg.gauge("tidbtpu_dcn_hosts_alive")
        vals = [0.0, 4.0, 0.0, 4.0, 1.0, 1.0, 1.0, 1.0]
        for i, v in enumerate(vals):
            g.set(v)
            store.sample_registry(registry=reg, now=2000.0 + i)
        ds = [
            p for p in store.query("tidbtpu_dcn_hosts_alive")
            if p[4] == "ds"
        ]
        assert ds and ds[0][3] == pytest.approx(2.0)  # mean of 0,4,0,4

    def test_eviction_counter_moves_on_coarse_overflow(self):
        from tidb_tpu.obs.tsdb import _c_evicted

        store = TimeSeriesStore(retention_points=4, downsample_every=1)
        reg = Registry()
        g = reg.gauge("tidbtpu_dcn_hosts_alive")
        before = _c_evicted().value
        for i in range(32):
            g.set(i)
            store.sample_registry(registry=reg, now=3000.0 + i)
        # downsample_every=1: every raw eviction becomes a coarse
        # point; coarse cap 4 -> overflow beyond 8 retained points
        assert _c_evicted().value > before
        assert store.point_count() <= 8

    def test_series_cap_bounds_label_blowup(self):
        store = TimeSeriesStore(retention_points=8, max_series=16)
        reg = Registry()
        fam = reg.counter(
            "tidbtpu_dcn_dispatches", labels=("host",)
        )
        for i in range(64):
            fam.labels(host=f"w{i}").inc()
        store.sample_registry(registry=reg, now=4000.0)
        assert store.series_count() <= 16
        assert store.series_cap_drops > 0

    def test_query_time_and_label_pushdown(self):
        store = TimeSeriesStore(retention_points=32)
        reg = Registry()
        fam = reg.counter(
            "tidbtpu_dcn_dispatches", labels=("host",)
        )
        fam.labels(host="w1").inc()
        fam.labels(host="w2").inc()
        for i in range(10):
            store.sample_registry(registry=reg, now=5000.0 + i)
        allpts = store.query("tidbtpu_dcn_dispatches")
        assert len(allpts) == 20
        bounded = store.query(
            "tidbtpu_dcn_dispatches", t_lo=5007.0, t_hi=5008.5
        )
        assert len(bounded) == 4  # 2 hosts x samples 5007, 5008
        w1 = store.query(
            "tidbtpu_dcn_dispatches", labels={"host": "w1"}
        )
        assert len(w1) == 10
        assert all(lv == ("w1",) for _t, _h, lv, _v, _r in w1)

    def test_merge_remote_rebases_filters_and_survives_garbage(self):
        store = TimeSeriesStore()
        rows = [
            ["tidbtpu_shuffle_bytes_total", [], [], 1000.0, 5.0,
             "counter"],
            ["not_ours_metric", [], [], 1000.0, 1.0, "counter"],
            ["tidbtpu_shuffle_bytes_total", "garbage"],  # malformed
        ]
        n = store.merge_remote(rows, host="w1:1", offset_s=2.0)
        assert n == 1
        pts = store.query("tidbtpu_shuffle_bytes_total")
        assert pts == [(998.0, "w1:1", (), 5.0, "raw")]

    def test_retune_retention_shrinks_live_series(self):
        store = TimeSeriesStore(retention_points=32)
        reg = Registry()
        g = reg.gauge("tidbtpu_dcn_hosts_alive")
        for i in range(32):
            g.set(i)
            store.sample_registry(registry=reg, now=6000.0 + i)
        store.retune_retention(retention_points=8)
        key = ("tidbtpu_dcn_hosts_alive", "coordinator", (), ())
        assert len(store._series[key].raw) == 8
        # the shrink folded the overflow through downsampling
        assert any(
            p[4] == "ds"
            for p in store.query("tidbtpu_dcn_hosts_alive")
        )


class TestSampler:
    def test_passive_tick_spacing_and_background_retune(self):
        store = TimeSeriesStore()
        sampler = TsdbSampler(store, passive_interval_s=3600.0)
        assert sampler.maybe_sample(now=10.0) is True
        assert sampler.maybe_sample(now=11.0) is False  # too soon
        # background thread: starts, samples, stops on retune(0)
        sampler.retune(0.01)
        try:
            assert sampler.interval_s() == 0.01
            # the thread owns the cadence: passive ticks are no-ops
            assert sampler.maybe_sample(now=1e12) is False
            deadline = time.monotonic() + 10
            base = store.point_count()
            while store.point_count() <= base:
                assert time.monotonic() < deadline, "sampler idle"
                time.sleep(0.02)
        finally:
            sampler.stop()
        assert sampler.interval_s() == 0.0
        assert not [
            t for t in threading.enumerate()
            if t.name == "obs-tsdb-sampler" and t.is_alive()
        ]

    def test_tick_feeds_timeline_counter_tracks(self):
        """ISSUE 12 satellite: while a capture is live, the tsdb
        cadence samples the 'C' counter tracks — gauge movement
        BETWEEN statements lands in the trace instead of flatlining
        until the next statement close."""
        from tidb_tpu.obs.timeline import TIMELINE

        REGISTRY.gauge(
            "tidbtpu_admission_queue_depth",
            "queries waiting for admission",
        ).set(7)
        sampler = TsdbSampler(TimeSeriesStore())
        TIMELINE.start()
        try:
            sampler.sample_once()
            counters = [
                e for e in TIMELINE.events()
                if e[0] == "C"
                and e[2] == "tidbtpu_admission_queue_depth"
            ]
            assert counters and counters[-1][4] == 7.0
        finally:
            TIMELINE.stop()
            TIMELINE.clear()


# ---------------------------------------------------------------------------
# SQL surface: metrics_schema + pushdown + statements_summary_history
# ---------------------------------------------------------------------------


class TestMetricsSchemaSQL:
    def test_select_with_time_pushdown(self, sess):
        sess.execute("select count(*) from t")
        t_mid = time.time()
        SAMPLER.sample_once(now=t_mid - 30.0)
        SAMPLER.sample_once(now=t_mid)
        r = sess.must_query(
            "select time, instance, value from "
            "metrics_schema.tidbtpu_session_statements_total "
            f"where time >= {t_mid - 1.0}"
        )
        assert r.rows and all(row[0] >= t_mid - 1.0 for row in r.rows)
        assert all(row[1] == "coordinator" for row in r.rows)
        # the pushdown reached the store: only the bounded slice was
        # materialized, not the whole ring (read the scan gauge BEFORE
        # the unbounded count query overwrites it)
        bounded = TSDB.last_scan_points
        total = len(TSDB.query("tidbtpu_session_statements_total"))
        assert bounded < total

    def test_label_columns_and_label_pushdown(self, sess):
        REGISTRY.counter(
            "tidbtpu_dcn_dispatches", "fragment dispatches",
            labels=("host",),
        ).labels(host="w1:9").inc()
        SAMPLER.sample_once()
        r = sess.must_query(
            "select host, value from "
            "metrics_schema.tidbtpu_dcn_dispatches "
            "where host = 'w1:9'"
        )
        assert r.rows and all(row[0] == "w1:9" for row in r.rows)

    def test_histogram_family_has_stat_column(self, sess):
        sess.execute("select count(*) from t")
        SAMPLER.sample_once()
        r = sess.must_query(
            "select stat, value from "
            "metrics_schema.tidbtpu_session_query_duration_seconds "
            "where stat = 'count'"
        )
        assert r.rows and all(row[0] == "count" for row in r.rows)

    def test_unknown_family_and_show_tables(self, sess):
        with pytest.raises(ValueError, match="metrics_schema"):
            sess.execute(
                "select * from metrics_schema.tidbtpu_nope_nothing"
            )
        SAMPLER.sample_once()
        sess.execute("use metrics_schema")
        rows = {r[0] for r in sess.execute("show tables").rows}
        assert "tidbtpu_session_statements_total" in rows

    def test_scan_hint_is_thread_local_and_metric_scoped(self):
        set_scan_hint("tidbtpu_x_y", t_lo=1.0)
        try:
            assert scan_hint_for("tidbtpu_x_y") == (1.0, None, {})
            assert scan_hint_for("tidbtpu_other_z") is None
            seen = []
            t = threading.Thread(
                target=lambda: seen.append(
                    scan_hint_for("tidbtpu_x_y")
                ),
                daemon=True, name="obs-hint-probe",
            )
            t.start()
            t.join()
            assert seen == [None]
        finally:
            clear_scan_hint()

    def test_no_hint_bleed_into_same_family_subquery(self, sess):
        """A statement referencing the family TWICE (scalar subquery)
        must not push the outer bounds down — the inner unbounded
        aggregate would silently inherit them and compute over the
        sliced history."""
        t0 = time.time()
        sess.execute("select count(*) from t")
        SAMPLER.sample_once(now=t0 - 50.0)
        sess.execute("select count(*) from t")
        sess.execute("select count(*) from t")
        SAMPLER.sample_once(now=t0)
        r = sess.must_query(
            "select value from "
            "metrics_schema.tidbtpu_session_statements_total "
            f"where time >= {t0 - 1.0} and value > ("
            "select min(value) from "
            "metrics_schema.tidbtpu_session_statements_total)"
        )
        # the inner min spans the FULL history (smaller than any
        # in-window value), so the bounded outer rows all qualify; a
        # hint bleed would bound the inner min to the newest sample
        # and return nothing
        assert r.rows

    def test_downsampled_histogram_stats_stay_cumulative(self):
        store = TimeSeriesStore(retention_points=4, downsample_every=4)
        reg = Registry()
        h = reg.histogram("tidbtpu_flight_query_seconds")
        for i in range(8):
            h.observe(1.0)
            store.sample_registry(registry=reg, now=7000.0 + i)
        ds = [
            p for p in store.query(
                "tidbtpu_flight_query_seconds",
                labels={"stat": "count"},
            )
            if p[4] == "ds"
        ]
        # cumulative count at the fold boundary, NOT the fold mean
        # (the mean would under-read and inflate window deltas that
        # straddle the coarse->raw boundary)
        assert ds and ds[0][3] == 4.0

    def test_predicates_stay_exact_beyond_the_hint(self, sess):
        """The hint is a superset scan, never the filter: a predicate
        the store cannot push (value comparison) still filters."""
        SAMPLER.sample_once()
        r = sess.must_query(
            "select value from "
            "metrics_schema.tidbtpu_session_statements_total "
            "where value < -1"
        )
        assert r.rows == []


class TestStatementsSummaryHistory:
    def test_windows_survive_eviction_boundary(self):
        """ISSUE 12 acceptance: >= 2 windows per digest across an
        eviction boundary — the AQE trajectory must not vanish when
        the live summary churns."""
        summ = StmtSummary(capacity=2)
        hist = StmtHistory(max_windows=8, refresh_interval_s=3600.0)
        summ.history = hist
        summ.record("select a from q1", 0.1)
        summ.record("select a from q2", 0.1)
        summ.record("select a from q2", 0.2)
        hist.rotate(summ, now=100.0)          # window 1: q1 live
        # a new digest evicts q1 (least-executed) from the live map
        summ.record("select a from q3", 0.1)
        digests = {d for d, *_ in summ.rows()}
        assert not any("q1" in d for d in digests)  # evicted
        hist.rotate(summ, now=200.0)          # window 2: q1 via evict
        q1 = [
            (b, e, r) for b, e, r in hist.rows() if "q1" in
            r["digest_text"]
        ]
        assert len(q1) >= 2
        # the eviction snapshot kept the aggregates
        assert all(r["exec_count"] == 1 for _b, _e, r in q1)

    def test_window_capacity_and_maybe_rotate(self):
        summ = StmtSummary(capacity=8)
        hist = StmtHistory(max_windows=2, refresh_interval_s=50.0)
        summ.record("select 1 from w", 0.1)
        assert hist.maybe_rotate(summ, now=hist._open_t0 + 1) is False
        assert hist.maybe_rotate(summ, now=hist._open_t0 + 60) is True
        for i in range(4):
            hist.rotate(summ, now=1000.0 + i)
        assert len(hist._windows) == 2  # bounded

    def test_infoschema_table_serves_history(self, sess):
        from tidb_tpu.utils.metrics import STMT_HISTORY, STMT_SUMMARY

        sess.execute("select a, b from t where a = 1")
        STMT_HISTORY.rotate(STMT_SUMMARY)
        r = sess.must_query(
            "select digest_text, exec_count from "
            "information_schema.statements_summary_history "
            "where digest_text like '%from t where%'"
        )
        assert r.rows and all(row[1] >= 1 for row in r.rows)


# ---------------------------------------------------------------------------
# inspection engine
# ---------------------------------------------------------------------------


def _feed(store, name, lnames, lvalues, series, kind="counter",
          host="coordinator"):
    """Feed (ts, value) points for one series through the public
    merge path."""
    store.merge_remote(
        [[name, list(lnames), list(lvalues), t, v, kind]
         for t, v in series],
        host=host,
    )


class TestInspectionRules:
    def _engine(self):
        from tidb_tpu.obs.inspection import InspectionEngine

        store = TimeSeriesStore()
        return store, InspectionEngine(store)

    def test_healthy_history_yields_no_findings(self):
        store, eng = self._engine()
        _feed(store, "tidbtpu_dcn_retries", (), (),
              [(100.0, 5.0), (200.0, 5.0)])
        _feed(store, "tidbtpu_link_heartbeat_age_seconds", ("host",),
              ("w1",), [(100.0, 0.0), (200.0, 0.01)], kind="gauge")
        assert eng.run(t_lo=50.0, t_hi=250.0) == []

    def test_heartbeat_gap_and_miss_escalation(self):
        store, eng = self._engine()
        _feed(store, "tidbtpu_link_heartbeat_age_seconds", ("host",),
              ("w1",), [(100.0, 0.0), (150.0, 4.0)], kind="gauge")
        fs = eng.run(t_lo=50.0, t_hi=200.0)
        gap = [f for f in fs if f.rule == "heartbeat-gap"]
        assert gap and gap[0].item == "w1"
        assert gap[0].severity == "warning"
        assert 100.0 <= gap[0].t0 <= gap[0].t1 <= 150.0
        # repeated misses on THE SAME host escalate it; another
        # host's misses must not (severity is per-host evidence)
        _feed(store, "tidbtpu_dcn_heartbeat_misses", ("host",),
              ("w2",), [(100.0, 0.0), (150.0, 5.0)])
        fs = eng.run(t_lo=50.0, t_hi=200.0)
        w1 = [f for f in fs if f.rule == "heartbeat-gap"
              and f.item == "w1" and "age" in f.detail]
        assert w1 and w1[0].severity == "warning"
        _feed(store, "tidbtpu_dcn_heartbeat_misses", ("host",),
              ("w1",), [(100.0, 0.0), (150.0, 2.0)])
        fs = eng.run(t_lo=50.0, t_hi=200.0)
        w1 = [f for f in fs if f.rule == "heartbeat-gap"
              and f.item == "w1" and "age" in f.detail]
        assert w1 and w1[0].severity == "critical"

    def test_retry_storm_thresholds_and_evidence_window(self):
        store, eng = self._engine()
        _feed(store, "tidbtpu_dcn_retries", (), (),
              [(100.0, 0.0), (150.0, 2.0), (200.0, 2.0)])
        fs = eng.run(t_lo=50.0, t_hi=250.0)
        storm = [f for f in fs if f.rule == "retry-storm"]
        assert storm and storm[0].severity == "warning"
        # evidence brackets the movement, not the whole window
        assert storm[0].t0 == 100.0 and storm[0].t1 == 200.0
        _feed(store, "tidbtpu_shuffle_stage_retries", (), (),
              [(100.0, 0.0), (180.0, 10.0)])
        fs = eng.run(t_lo=50.0, t_hi=250.0)
        storm = [f for f in fs if f.rule == "retry-storm"]
        assert storm[0].severity == "critical"

    def test_counter_born_inside_window_counts_from_zero(self):
        store, eng = self._engine()
        _feed(store, "tidbtpu_shuffle_retransmits", (), (),
              [(150.0, 3.0)])
        fs = eng.run(t_lo=100.0, t_hi=200.0)
        assert any(
            f.rule == "shuffle-retransmit-storm" for f in fs
        )

    def test_preexisting_counter_standing_value_is_not_an_increase(
        self
    ):
        store, eng = self._engine()
        _feed(store, "tidbtpu_shuffle_retransmits", (), (),
              [(50.0, 100.0), (150.0, 100.0)])
        fs = eng.run(t_lo=100.0, t_hi=200.0)
        assert not any(
            f.rule == "shuffle-retransmit-storm" for f in fs
        )

    def test_clock_skew_and_tunnel_backpressure(self):
        store, eng = self._engine()
        _feed(store, "tidbtpu_link_clock_offset_seconds", ("host",),
              ("w2",), [(100.0, -3.0)], kind="gauge")
        _feed(store, "tidbtpu_link_stall_seconds", ("src", "dst"),
              ("a:1", "b:2"), [(100.0, 0.0), (150.0, 0.8)])
        fs = eng.run(t_lo=50.0, t_hi=200.0)
        rules = {f.rule: f for f in fs}
        assert rules["clock-skew"].severity == "critical"
        assert rules["clock-skew"].item == "w2"
        assert rules["tunnel-backpressure"].item == "a:1->b:2"

    def test_admission_starvation_and_plan_cache_thrash(self):
        store, eng = self._engine()
        # histogram stat series: 4 waits totalling 8s -> mean 2s
        _feed(store, "tidbtpu_admission_queue_wait_seconds",
              ("stat",), ("sum",), [(100.0, 0.0), (150.0, 8.0)],
              kind="histogram")
        _feed(store, "tidbtpu_admission_queue_wait_seconds",
              ("stat",), ("count",), [(100.0, 0.0), (150.0, 4.0)],
              kind="histogram")
        _feed(store, "tidbtpu_admission_outcomes_total",
              ("outcome",), ("reject",), [(100.0, 0.0), (150.0, 2.0)])
        _feed(store, "tidbtpu_executor_plan_cache_misses_total", (),
              (), [(100.0, 0.0), (150.0, 20.0)])
        _feed(store, "tidbtpu_executor_plan_cache_hits_total", (),
              (), [(100.0, 0.0), (150.0, 2.0)])
        fs = eng.run(t_lo=50.0, t_hi=200.0)
        rules = {f.rule for f in fs}
        assert "admission-starvation" in rules
        assert "plan-cache-thrash" in rules
        rejects = [
            f for f in fs if f.rule == "admission-starvation"
            and f.item == "reject"
        ]
        assert rejects and rejects[0].severity == "critical"

    def test_quarantine_flap(self):
        store, eng = self._engine()
        _feed(store, "tidbtpu_dcn_quarantines", ("host",), ("w1",),
              [(100.0, 0.0), (150.0, 2.0)])
        _feed(store, "tidbtpu_dcn_readmissions_total", ("host",),
              ("w1",), [(100.0, 0.0), (160.0, 2.0)])
        fs = eng.run(t_lo=50.0, t_hi=200.0)
        flap = [f for f in fs if f.rule == "quarantine-flap"]
        assert flap and flap[0].item == "w1"
        assert flap[0].severity == "critical"

    def test_undeclared_metric_read_raises_and_is_reported(self):
        from tidb_tpu.obs import inspection as insp

        store, eng = self._engine()

        @insp.rule("x-test-rogue", metrics=("tidbtpu_dcn_retries",))
        def _rogue(ctx):
            return ctx.series("tidbtpu_shuffle_retransmits")

        try:
            fs = eng.run(rules=["x-test-rogue"])
            assert fs and fs[0].severity == "critical"
            assert "undeclared metric" in fs[0].detail
        finally:
            del insp.RULES["x-test-rogue"]

    def test_rule_registry_rejects_duplicates_and_empty_metrics(self):
        from tidb_tpu.obs import inspection as insp

        with pytest.raises(ValueError, match="duplicate"):
            insp.rule("retry-storm", metrics=("tidbtpu_dcn_retries",))(
                lambda ctx: []
            )
        with pytest.raises(ValueError, match="no metrics"):
            insp.rule("x-test-empty", metrics=())(lambda ctx: [])

    def test_match_chaos_findings_window_overlap(self):
        from tidb_tpu.obs.inspection import (
            Finding,
            match_chaos_findings,
        )

        f = Finding("clock-skew", "w1", "critical", 3.0, "", "",
                    100.0, 110.0)
        assert match_chaos_findings(
            ["clock-skew"], [f], window=(105.0, 120.0)
        ) == {"clock-skew": True}
        assert match_chaos_findings(
            ["clock-skew"], [f], window=(200.0, 210.0)
        ) == {"clock-skew": False}
        # classes with no declared signature assert nothing
        assert match_chaos_findings(
            ["frame-delay"], [], window=(0.0, 1.0)
        ) == {"frame-delay": True}


# ---------------------------------------------------------------------------
# worker sample shipping (in-process half; the 2-process dryrun is in
# test_multihost.py)
# ---------------------------------------------------------------------------


class TestWorkerSampleShipping:
    def test_tsdb_ship_drains_exactly_once(self, sess):
        from tidb_tpu.server.engine_rpc import EngineServer

        srv = EngineServer(sess.catalog, port=0, ship_registry=True)
        srv.start_background()
        try:
            srv.tsdb_min_interval_s = 0.0
            first = srv._tsdb_ship()
            assert first
            srv.tsdb_min_interval_s = 3600.0
            # nothing new sampled and the buffer was drained: the same
            # batch can never ride two replies
            assert srv._tsdb_ship() is None
        finally:
            srv.shutdown()

    def test_ping_idle_flush_merges_host_history(self, sess):
        """The heartbeat idle-flush: an idle worker's samples reach
        the coordinator store via ping_endpoint, labeled by the
        worker's address, without any dispatch in flight."""
        from tidb_tpu.server.engine_pool import (
            EngineEndpoint,
            ping_endpoint,
        )
        from tidb_tpu.server.engine_rpc import EngineServer

        srv = EngineServer(sess.catalog, port=0, ship_registry=True)
        srv.start_background()
        srv.tsdb_min_interval_s = 0.0
        ep = EngineEndpoint("127.0.0.1", srv.port)
        try:
            before = {
                k for k in TSDB._series if k[1] == ep.address
            }
            assert ping_endpoint(ep) is True
            after = {k for k in TSDB._series if k[1] == ep.address}
            assert after - before  # worker-host series landed
        finally:
            srv.shutdown()

    def test_fenced_merge_never_duplicates_a_sample_batch(self, sess):
        """dcn/duplicate-redelivery: every completion is immediately
        redelivered; the ledger fences the second landing, so a
        reply's sample batch lands AT MOST ONCE — no exact-duplicate
        (metric, ts, labels, value) points for the worker host."""
        from tidb_tpu.parallel.dcn import DCNFragmentScheduler
        from tidb_tpu.parser.sqlparse import parse
        from tidb_tpu.planner.logical import build_query
        from tidb_tpu.server.engine_rpc import EngineServer
        from tidb_tpu.utils import failpoint

        srv = EngineServer(sess.catalog, port=0, ship_registry=True)
        srv.tsdb_min_interval_s = 0.0
        srv.start_background()
        failpoint.enable("dcn/duplicate-redelivery", True)
        sched = DCNFragmentScheduler(
            [("127.0.0.1", srv.port)], catalog=sess.catalog
        )
        try:
            plan = build_query(
                parse("select b, count(*) from t group by b order by b")[0],
                sess.catalog, "test", sess._scalar_subquery,
            )
            _cols, rows = sched.execute_plan(plan)
            assert rows  # parity is covered elsewhere; landing matters
            host = f"127.0.0.1:{srv.port}"
            pts = []
            for key, s in TSDB._series.items():
                if key[1] != host:
                    continue
                pts.extend(
                    (key[0], key[3], t, v) for t, v in s.raw
                )
            assert pts, "worker samples should have merged"
            assert len(pts) == len(set(pts)), (
                "duplicate-redelivered reply's sample batch merged "
                "twice"
            )
        finally:
            failpoint.disable("dcn/duplicate-redelivery")
            sched.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# racecheck stress (ISSUE 12 satellite)
# ---------------------------------------------------------------------------


@pytest.fixture()
def racecheck_on():
    racecheck.enable()
    racecheck.reset()
    try:
        yield
    finally:
        racecheck.disable()
        racecheck.reset()


class TestRacecheckStress:
    def test_metric_hammer_concurrent_with_sampling_and_eviction(
        self, racecheck_on
    ):
        """8 threads hammer labeled metrics while a sampler thread
        samples + evicts under order-tracked locks; retention bounds
        hold throughout and no lock-order inversion raises."""
        reg = Registry()
        store = TimeSeriesStore(
            retention_points=8, downsample_every=2, max_series=256
        )
        stop = threading.Event()
        errors = []

        def hammer(idx):
            fam = reg.counter(
                "tidbtpu_dcn_dispatches", labels=("host",)
            )
            h = reg.histogram("tidbtpu_flight_query_seconds")
            g = reg.gauge("tidbtpu_dcn_hosts_alive")
            i = 0
            try:
                while not stop.is_set():
                    fam.labels(host=f"w{idx}").inc()
                    h.observe(0.001 * i)
                    g.set(i % 5)
                    i += 1
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def sample_loop():
            now = 1000.0
            try:
                while not stop.is_set():
                    store.sample_registry(registry=reg, now=now)
                    now += 1.0
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(
                target=hammer, args=(i,), daemon=True,
                name=f"obs-hammer-{i}",
            )
            for i in range(8)
        ] + [
            threading.Thread(
                target=sample_loop, daemon=True, name="obs-sampler",
            )
        ]
        for t in threads:
            t.start()
        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        assert not [t for t in threads if t.is_alive()]
        # retention bounds held under the hammer: <= 2 rings per series
        assert store.point_count() <= store.series_count() * 16
        # the tsdb lock class participated in the tracked run
        assert "obs.tsdb" in racecheck.seen_classes()

    def test_query_concurrent_with_retune(self, racecheck_on):
        store = TimeSeriesStore(retention_points=64)
        reg = Registry()
        g = reg.gauge("tidbtpu_dcn_hosts_alive")
        stop = threading.Event()
        errors = []

        def writer():
            now = 0.0
            try:
                while not stop.is_set():
                    g.set(now)
                    store.sample_registry(registry=reg, now=now)
                    now += 1.0
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def retuner():
            try:
                while not stop.is_set():
                    store.retune_retention(retention_points=8)
                    store.retune_retention(retention_points=64)
                    store.query("tidbtpu_dcn_hosts_alive", t_lo=5.0)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        ts = [
            threading.Thread(
                target=writer, daemon=True, name="obs-writer"
            ),
            threading.Thread(
                target=retuner, daemon=True, name="obs-retuner"
            ),
        ]
        for t in ts:
            t.start()
        time.sleep(0.25)
        stop.set()
        for t in ts:
            t.join(timeout=10)
        assert not errors, errors


# ---------------------------------------------------------------------------
# chaos -> inspection acceptance (in-process fleet)
# ---------------------------------------------------------------------------


def test_chaos_fault_classes_surface_as_findings():
    """ISSUE 12 acceptance: a seeded chaos run with worker-crash +
    frame-drop + clock-skew episodes yields an inspection finding per
    fault class whose evidence window overlaps the episode —
    deterministic under schedule replay (re-running an episode's
    schedule reproduces its match verdict; schedule generation itself
    is seed-pure, tests/test_chaos.py)."""
    from tidb_tpu.chaos import ChaosHarness
    from tidb_tpu.chaos.schedule import Episode, Fault
    from tidb_tpu.obs.inspection import (
        match_chaos_findings,
        run_inspection,
    )

    episodes = [
        Episode(0, 0, (Fault("worker-crash", "shuffle/recv", "drop",
                             n=2),)),
        Episode(1, 2, (Fault("frame-drop", "shuffle/push-lost",
                             "window-error", n=3),)),
        Episode(2, 1, (Fault("clock-skew", "engine/clock-skew",
                             "value", param=3.0),)),
        # replay of the clock-skew episode: the same schedule must
        # reproduce the same verdict
        Episode(3, 1, (Fault("clock-skew", "engine/clock-skew",
                             "value", param=3.0),)),
    ]
    verdicts = []
    with ChaosHarness(seed=12, wait_timeout_s=2.0) as h:
        for ep in episodes:
            violations, _wall = h.run_episode(ep)
            assert violations == [], violations
            t0, t1 = h.last_window
            findings = run_inspection(t_lo=t0 - 0.01, t_hi=t1 + 0.01)
            classes = tuple(f.cls for f in ep.faults)
            m = match_chaos_findings(classes, findings, window=(t0, t1))
            assert all(m.values()), (classes, m, [
                (f.rule, f.t0, f.t1) for f in findings
            ])
            verdicts.append(m)
    assert verdicts[2] == verdicts[3]  # replay determinism


# ---------------------------------------------------------------------------
# check_inspection_rules lint: seeded violations
# ---------------------------------------------------------------------------


LINT = os.path.join(REPO, "scripts", "check_inspection_rules.py")

_FLIGHT_STUB = 'PHASES = (\n    "parse",\n    "compile",\n)\n'

_METRICS_STUB = textwrap.dedent(
    '''
    from x import REGISTRY

    REGISTRY.counter("tidbtpu_dcn_retries", "r")
    REGISTRY.gauge("tidbtpu_link_heartbeat_age_seconds", "a")
    '''
)


def _lint_tree(tmp_path, inspection_src):
    obs = tmp_path / "tidb_tpu" / "obs"
    obs.mkdir(parents=True)
    (obs / "flight.py").write_text(_FLIGHT_STUB)
    (obs / "inspection.py").write_text(textwrap.dedent(inspection_src))
    (tmp_path / "tidb_tpu" / "engine.py").write_text(_METRICS_STUB)
    return subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )


class TestInspectionRulesLint:
    def test_clean_tree_passes(self, tmp_path):
        proc = _lint_tree(
            tmp_path,
            '''
            @rule("ok", metrics=("tidbtpu_dcn_retries",),
                  phases=("compile",))
            def _ok(ctx):
                return []
            ''',
        )
        assert proc.returncode == 0, proc.stdout

    def test_head_is_clean(self):
        proc = subprocess.run(
            [sys.executable, LINT, REPO], capture_output=True,
            text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout

    def test_bad_convention_and_undeclared_subsystem(self, tmp_path):
        proc = _lint_tree(
            tmp_path,
            '''
            @rule("bad", metrics=("tidb_tpu-wrong",))
            def _bad(ctx):
                return []

            @rule("bad2", metrics=("tidbtpu_nosuchsub_x",))
            def _bad2(ctx):
                return []
            ''',
        )
        assert proc.returncode == 1
        assert "violating the tidbtpu_<subsystem>_<name>" in proc.stdout
        assert "undeclared subsystem 'nosuchsub'" in proc.stdout

    def test_dead_metric_declaration_fails(self, tmp_path):
        proc = _lint_tree(
            tmp_path,
            '''
            @rule("dead", metrics=("tidbtpu_dcn_never_registered",))
            def _dead(ctx):
                return []
            ''',
        )
        assert proc.returncode == 1
        assert "dead rule declaration" in proc.stdout

    def test_undeclared_phase_and_empty_metrics_fail(self, tmp_path):
        proc = _lint_tree(
            tmp_path,
            '''
            @rule("p", metrics=("tidbtpu_dcn_retries",),
                  phases=("warp-drive",))
            def _p(ctx):
                return []

            @rule("empty", metrics=())
            def _empty(ctx):
                return []
            ''',
        )
        assert proc.returncode == 1
        assert "undeclared flight phase 'warp-drive'" in proc.stdout
        assert "declares no metrics" in proc.stdout

    def test_duplicate_rule_names_fail(self, tmp_path):
        proc = _lint_tree(
            tmp_path,
            '''
            @rule("twice", metrics=("tidbtpu_dcn_retries",))
            def _a(ctx):
                return []

            @rule("twice", metrics=("tidbtpu_dcn_retries",))
            def _b(ctx):
                return []
            ''',
        )
        assert proc.returncode == 1
        assert "duplicate inspection rule 'twice'" in proc.stdout


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------


def test_http_tsdb_and_inspection_endpoints(sess):
    import json
    import urllib.request

    from tidb_tpu.server.http_status import StatusServer

    SAMPLER.sample_once()
    http = StatusServer(sess.catalog, port=0)
    http.start_background()
    try:
        base = f"http://127.0.0.1:{http.port}"
        tsdb = json.loads(
            urllib.request.urlopen(f"{base}/tsdb", timeout=10)
            .read().decode()
        )
        assert tsdb["series"] > 0 and tsdb["points"] > 0
        assert (
            "tidbtpu_session_statements_total" in tsdb["families"]
        )
        one = json.loads(
            urllib.request.urlopen(
                f"{base}/tsdb?metric="
                "tidbtpu_session_statements_total",
                timeout=10,
            ).read().decode()
        )
        assert one["points"]
        insp = json.loads(
            urllib.request.urlopen(f"{base}/inspection", timeout=10)
            .read().decode()
        )
        assert "findings" in insp
    finally:
        http.shutdown()
