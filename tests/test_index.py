"""Secondary indexes: DDL, range-scan access path, and planning.

Reference: CREATE INDEX / IndexRangeScan (pkg/executor/distsql.go
IndexLookUp, pkg/util/ranger predicate->range). TPU-native structure:
immutable versions make the index a lazily cached argsort permutation
(storage/table._sorted_index); a bounded predicate range becomes a
host searchsorted + gather that feeds the device a compact batch.
"""

import pytest

from tidb_tpu.session.session import Session


@pytest.fixture()
def s():
    s = Session()
    s.execute(
        "create table t (id int primary key, v int, ts date, c varchar(8), "
        "index iv (v))"
    )
    rows = []
    for i in range(2000):
        rows.append(f"({i},{(i * 37) % 500},'2024-01-{1 + i % 28:02d}','s{i % 7}')")
    s.execute("insert into t values " + ",".join(rows))
    return s


def test_inline_index_registered(s):
    t = s.catalog.table("test", "t")
    assert t.indexes == {"iv": ["v"]}


def test_index_range_matches_full_scan(s):
    fast = s.execute("select count(*), sum(id) from t where v between 100 and 110")
    slow = s.execute(
        "select count(*), sum(id) from t where v + 0 between 100 and 110"
    )
    assert fast.rows == slow.rows


def test_explain_shows_access_path(s):
    r = s.execute("explain select id from t where v between 7 and 9")
    txt = "\n".join(row[0] for row in r.rows)
    assert "IndexRangeScan(v in [7, 9])" in txt


def test_point_get_via_pk_still_preferred(s):
    # PK eq gives a width-0 range; the narrowest range wins
    r = s.execute("explain select v from t where id = 42 and v >= 0")
    txt = "\n".join(row[0] for row in r.rows)
    assert "IndexRangeScan(id in [42, 42])" in txt


def test_create_drop_index_statements(s):
    s.execute("create index its on t (ts)")
    assert "its" in s.catalog.table("test", "t").indexes
    r = s.execute("explain select id from t where ts = '2024-01-03'")
    assert "IndexRangeScan(ts" in "\n".join(row[0] for row in r.rows)
    s.execute("drop index its on t")
    assert "its" not in s.catalog.table("test", "t").indexes
    with pytest.raises(ValueError):
        s.execute("drop index its on t")
    s.execute("drop index if exists its on t")  # no error


def test_create_index_if_not_exists(s):
    s.execute("create index iv2 on t (v)")
    with pytest.raises(ValueError):
        s.execute("create index iv2 on t (v)")
    s.execute("create index if not exists iv2 on t (v)")


def test_index_correct_after_dml(s):
    s.execute("update t set v = 9999 where id = 7")
    r = s.execute("select id from t where v = 9999")
    assert r.rows == [(7,)]
    s.execute("delete from t where v = 9999")
    assert s.execute("select count(*) from t where v = 9999").rows == [(0,)]


def test_information_schema_statistics(s):
    r = s.execute(
        "select index_name, column_name from information_schema.statistics "
        "where table_name = 't' order by index_name"
    )
    assert ("iv", "v") in r.rows and ("primary", "id") in r.rows


def test_multi_column_index_leading_col(s):
    s.execute("create index ic on t (ts, v)")
    r = s.execute("explain select id from t where ts = '2024-01-05'")
    assert "IndexRangeScan(ts" in "\n".join(row[0] for row in r.rows)


def test_index_survives_persistence(tmp_path, s):
    from tidb_tpu.storage.persist import load_catalog, save_catalog

    save_catalog(s.catalog, str(tmp_path / "snap"))
    cat2 = load_catalog(str(tmp_path / "snap"))
    assert cat2.table("test", "t").indexes == {"iv": ["v"]}


def test_unique_index_enforced():
    s = Session()
    s.execute("create table u (a int, b int)")
    s.execute("insert into u values (1,1),(2,2)")
    s.execute("create unique index ua on u (a)")
    with pytest.raises(ValueError):
        s.execute("insert into u values (1, 9)")
    s.execute("insert into u values (3, 9)")
    # NULLs never collide (MySQL unique semantics)
    s.execute("insert into u values (null, 0),(null, 0)")
    # existing duplicates block creation
    with pytest.raises(ValueError):
        s.execute("create unique index ub on u (b)")
    # enforcement inside explicit transactions too
    s.execute("begin")
    with pytest.raises(ValueError):
        s.execute("insert into u values (1, 100)")
    s.execute("rollback")


def test_column_named_key_still_parses():
    s = Session()
    s.execute("create table k (key int, a int)")
    s.execute("insert into k values (1, 2)")
    assert s.execute("select key from k").rows == [(1,)]


def test_if_not_exists_table_keeps_indexes_intact():
    s = Session()
    s.execute("create table t (a int)")
    s.execute("create table if not exists t (a int, index ix (nosuch))")
    assert s.catalog.table("test", "t").indexes == {}


def test_unnamed_index_names_deduped():
    s = Session()
    s.execute("create table dd (a int, index (a), index (a))")
    assert sorted(s.catalog.table("test", "dd").indexes) == ["idx_a", "idx_a_2"]


def test_datetime_index_range():
    s = Session()
    s.execute("create table ev (id int, ts datetime, index its (ts))")
    s.execute(
        "insert into ev values (1,'2024-01-01 10:00:00'),"
        "(2,'2024-01-01 11:30:00'),(3,'2024-01-02 00:00:00')"
    )
    r = s.execute(
        "explain select id from ev where ts between '2024-01-01 10:30:00' "
        "and '2024-01-01 23:59:59'"
    )
    assert "IndexRangeScan(ts" in "\n".join(row[0] for row in r.rows)
    assert s.execute(
        "select id from ev where ts between '2024-01-01 10:30:00' "
        "and '2024-01-01 23:59:59'"
    ).rows == [(2,)]
