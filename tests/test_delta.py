"""HTAP delta tier (storage/delta.py): fleet-replicated writes,
snapshot-isolated delta-merge reads, background compaction.

Fleet shape here: in-process EngineServers over SEPARATE catalogs
loaded with identical data (the deterministic-load model of
dcn_worker) and delta_replica=True — coordinator DML reaches them only
through delta-sync frames. The 2-process dryrun lives in
test_multihost.py; these tests keep the whole protocol observable in
one process."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from tidb_tpu.parallel.dcn import DCNFragmentScheduler
from tidb_tpu.server.engine_rpc import DropConnection, EngineServer
from tidb_tpu.session.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.metrics import REGISTRY


def _counter_total(prefix: str) -> float:
    return sum(
        v for n, _k, v in REGISTRY.rows() if n.startswith(prefix)
    )


SEED_ROWS = ",".join(
    f"({i},{i * 10},'s{i % 3}')" for i in range(1, 21)
)


def _mk_catalog():
    cat = Catalog()
    s = Session(cat, db="test")
    s.execute(
        "create table t (a int primary key, b int, c varchar(8))"
    )
    s.execute(f"insert into t values {SEED_ROWS}")
    return cat, s


@pytest.fixture()
def fleet():
    """(coordinator session, scheduler, [servers], [worker catalogs])
    — 2 delta-replica servers over independent identical catalogs."""
    cat, sess = _mk_catalog()
    wcats = [_mk_catalog()[0] for _ in range(2)]
    servers = [
        EngineServer(wc, port=0, delta_replica=True) for wc in wcats
    ]
    for srv in servers:
        srv.start_background()
    sched = DCNFragmentScheduler(
        [("127.0.0.1", srv.port) for srv in servers], catalog=cat,
        # folds only when tests ask (compact_now): deterministic
        # depth/merge assertions
        retry_backoff_s=0.0,
    )
    sess.attach_dcn_scheduler(sched)
    # tests drive compaction explicitly
    if sched._compactor is not None:
        sched._compactor.stop()
    yield sess, sched, servers, wcats
    sess.attach_dcn_scheduler(None)
    sched.close()
    for srv in servers:
        srv.shutdown()


PARITY_QUERIES = (
    "select c, count(*), sum(b) from t group by c order by c",
    "select count(*), sum(b), min(a), max(b) from t",
    "select c, count(distinct a) from t group by c order by c",
)


_FRESH_SESSIONS: dict = {}


def _assert_parity(sess, cat, queries=(PARITY_QUERIES[1],)):
    """Every parity query agrees EXACTLY with a full reload (a fresh
    local session over the coordinator base), actually routed, with
    zero local fallbacks. The reload session is cached per catalog —
    its executor's plan cache amortizes the local compiles across a
    test's parity sweeps."""
    fb0 = _counter_total("tidbtpu_session_dcn_route_fallbacks")
    key = id(cat)
    fresh = _FRESH_SESSIONS.get(key)
    if fresh is None:
        fresh = _FRESH_SESSIONS[key] = Session(cat, db="test")
        if len(_FRESH_SESSIONS) > 4:
            _FRESH_SESSIONS.pop(next(iter(_FRESH_SESSIONS)))
    for q in queries:
        got = sess.execute(q)
        exp = fresh.execute(q)
        assert got.rows == exp.rows, (q, got.rows, exp.rows)
        assert sess._last_dcn_routed, q
    assert _counter_total(
        "tidbtpu_session_dcn_route_fallbacks"
    ) == fb0


# -- capture ---------------------------------------------------------------


def test_capture_kinds_per_dml_path():
    """The Table mutation primitives capture typed logical deltas:
    INSERT -> insert block, DELETE by int PK -> delete keys, the
    UPDATE rewrite path -> reload + insert, TRUNCATE -> reload."""
    from tidb_tpu.storage.delta import DeltaStore

    cat, sess = _mk_catalog()
    store = DeltaStore.attach(cat)
    sess.execute("insert into t values (21, 210, 'x')")
    assert [e.kind for e in store.entries] == ["insert"]
    assert store.entries[-1].block.nrows == 1
    sess.execute("delete from t where a in (2, 4)")
    assert store.entries[-1].kind == "delete"
    assert sorted(store.entries[-1].keys.tolist()) == [2, 4]
    assert store.entries[-1].key_col == "a"
    sess.execute("update t set c = 'zz' where a = 1")
    kinds = [e.kind for e in store.entries]
    assert "reload" in kinds  # rewrite paths resync the whole base
    n = len(store.entries)
    sess.execute("truncate table t")
    assert store.entries[n:][-1].kind == "reload"
    assert store.entries[-1].blocks == []


def test_capture_string_pk_deletes_resync():
    """A dictionary-coded (string) PK cannot ship delete keys as bare
    ints (codes shift as the dictionary grows) — those tables resync
    via reload markers instead of silently mis-keying."""
    from tidb_tpu.storage.delta import DeltaStore

    cat = Catalog()
    sess = Session(cat, db="test")
    sess.execute("create table s (k varchar(8) primary key, v int)")
    sess.execute("insert into s values ('a', 1), ('b', 2)")
    store = DeltaStore.attach(cat)
    sess.execute("delete from s where k = 'a'")
    assert store.entries[-1].kind == "reload"


# -- wire roundtrip --------------------------------------------------------


def test_entry_frames_roundtrip_binary():
    """Delta entries encode as binary columnar frames (no JSON on the
    data plane) and decode back value-exactly — NULLs and string
    dictionaries included."""
    from tidb_tpu.parallel import wire
    from tidb_tpu.storage.delta import DeltaStore, encode_entry_frames

    cat = Catalog()
    sess = Session(cat, db="test")
    sess.execute("create table r (a int primary key, b int, c text)")
    store = DeltaStore.attach(cat)
    sess.execute(
        "insert into r values (1, null, 'x'), (2, 20, null)"
    )
    t = cat.table("test", "r")
    [entry] = store.entries
    frames = encode_entry_frames(entry, t)
    assert len(frames) == 1 and wire.is_binary_frame(frames[0])
    assert wire.peek_sid(frames[0]) == "delta://test/r/insert"
    pkt = wire.decode_frame(frames[0])
    blk = pkt["block"]
    assert blk.nrows == 2
    assert blk.columns["a"].data.tolist() == [1, 2]
    assert blk.columns["b"].valid.tolist() == [False, True]
    c = blk.columns["c"]
    assert [
        str(c.dictionary[v]) if ok else None
        for v, ok in zip(c.data, c.valid)
    ] == ["x", None]
    # encode caches on the immutable entry
    assert encode_entry_frames(entry, t) is frames


# -- merge parity ----------------------------------------------------------


def test_delta_merge_parity_insert_delete(fleet):
    sess, sched, _servers, _wcats = fleet
    cat = sess.catalog
    sess.execute("insert into t values (21,210,'s0'),(22,220,'s1')")
    sess.execute("delete from t where a in (3, 7, 21)")
    _assert_parity(sess, cat, queries=PARITY_QUERIES)
    # merged plans report their delta stats (the EXPLAIN ANALYZE
    # DeltaMerge row rides the fragment replies) — read them off a
    # fragment-cut query's snapshot
    sess.execute(PARITY_QUERIES[0])
    d = sess._last_dcn_snapshot.get("delta")
    assert d is not None and d["depth"] >= 1


def test_delta_merge_parity_update_on_dup_null_autoinc(fleet):
    """The full DML matrix of the parity audit: UPDATE (both the
    columnar scatter and the rewrite path), REPLACE, INSERT ... ON
    DUPLICATE KEY UPDATE, NULL values, and AUTO_INCREMENT fill."""
    sess, sched, _servers, _wcats = fleet
    cat = sess.catalog
    one = (PARITY_QUERIES[1],)
    sess.execute("update t set b = b + 5 where a <= 4")
    _assert_parity(sess, cat, queries=one)
    sess.execute("update t set c = 'sx' where a = 9")
    _assert_parity(sess, cat, queries=one)
    sess.execute("replace into t values (1, -1, 'rp'), (30, 300, 'rp')")
    _assert_parity(sess, cat, queries=one)
    sess.execute(
        "insert into t values (2, 0, null) "
        "on duplicate key update b = b * 100"
    )
    sess.execute("insert into t values (31, null, null)")
    _assert_parity(sess, cat, queries=PARITY_QUERIES[:2])
    # autoinc: ids allocated coordinator-side replicate as plain rows
    sess.execute(
        "create table ai (id int primary key auto_increment, v int)"
    )
    sess.execute("insert into ai (v) values (7), (8), (9)")
    got = sess.execute("select count(*), max(id) from ai")
    assert got.rows == [(3, 3)]


def test_delta_merge_shuffle_cut_parity(fleet):
    """Writes merge under the worker-to-worker shuffle cut too: the
    producer sides resolve the same routed snapshot (ShuffleWorker
    _apply_snap), so a repartition join sees the delta."""
    sess, sched, _servers, _wcats = fleet
    sess.execute("create table j (k int primary key, c varchar(8))")
    sess.execute(
        "insert into j values " + ",".join(
            f"({i},'s{i % 3}')" for i in range(1, 15)
        )
    )
    sched.shuffle_mode = "always"
    try:
        sess.execute("insert into j values (15,'s0'),(16,'s1')")
        sess.execute("delete from j where k = 2")
        q = (
            "select t.c, count(*) from t join j on t.a = j.k "
            "group by t.c order by t.c"
        )
        got = sess.execute(q)
        exp = Session(sess.catalog, db="test").execute(q)
        assert got.rows == exp.rows, (got.rows, exp.rows)
        assert sess._last_dcn_routed
    finally:
        sched.shuffle_mode = "auto"


# -- freshness (+ new-table replication, + sync-loss retransmit) -----------


def test_freshness_read_your_writes_vs_bounded(fleet):
    sess, sched, _servers, wcats = fleet
    base = sess.execute("select count(*) from t").rows[0][0]
    # bounded staleness: nothing shipped since the write -> the
    # replicas serve their acked floor (stale), with zero wait
    sess.execute("set tidb_tpu_read_freshness = 'bounded'")
    sess.execute("insert into t values (40, 400, 's0')")
    w0 = _counter_total("tidbtpu_delta_ryw_wait_seconds")
    stale = sess.execute("select count(*) from t")
    assert stale.rows == [(base,)] and sess._last_dcn_routed
    assert _counter_total("tidbtpu_delta_ryw_wait_seconds") == w0
    # read-your-writes: ships + blocks on the session's high-water
    sess.execute("set tidb_tpu_read_freshness = 'read_your_writes'")
    fresh = sess.execute("select count(*) from t")
    assert fresh.rows == [(base + 1,)] and sess._last_dcn_routed
    # the floor advanced with the acks: bounded now sees the write
    sess.execute("set tidb_tpu_read_freshness = 'bounded'")
    again = sess.execute("select count(*) from t")
    assert again.rows == [(base + 1,)]
    sess.execute("set tidb_tpu_read_freshness = 'read_your_writes'")

    # CREATE TABLE after attach + INSERT: the replicas materialize the
    # table from the sync frames' wire schema (_ensure_table), so
    # routed reads of a table the workers never loaded still serve
    sess.execute("create table fresh (k bigint primary key, v bigint)")
    sess.execute("insert into fresh values (1, 100), (2, 200)")
    got = sess.execute("select count(*), sum(v) from fresh")
    assert got.rows == [(2, 300)] and sess._last_dcn_routed
    for wc in wcats:
        assert "fresh" in wc.tables("test")

    # delta/sync-loss drops the ACK after the replica applied a
    # frame: the replicator retransmits over a fresh connection and
    # the worker's seq fence drops the duplicate — exactly once
    rt0 = _counter_total("tidbtpu_delta_sync_retransmits")
    failpoint.enable(
        "delta/sync-loss", failpoint.after_n(1, DropConnection("chaos"))
    )
    try:
        sess.execute("insert into t values (50, 500, 's1')")
        _assert_parity(sess, sess.catalog)
    finally:
        failpoint.disable("delta/sync-loss")
    assert _counter_total("tidbtpu_delta_sync_retransmits") > rt0

    # a transaction COMMIT (install_commit -> reload capture) moves
    # the read-your-writes high-water exactly like autocommit DML
    sess.execute("begin")
    sess.execute("insert into t values (51, 510, 's2')")
    sess.execute("commit")
    _assert_parity(sess, sess.catalog)


# -- snapshot pinning (the unpinned routed-read regression) ----------------


def test_routed_snapshot_survives_concurrent_write_and_gc():
    """Routed dispatches used to read Table.blocks() unpinned: a
    concurrent write + version GC between two fragment executions of
    ONE query mutated its input mid-flight. Now the coordinator pins
    the snapshot version for the whole dispatch and ships it, so
    every fragment reads the SAME pre-write base even while a writer
    publishes (and GC collects) versions under it."""
    cat, sess = _mk_catalog()
    servers = [EngineServer(cat, port=0) for _ in range(2)]
    for srv in servers:
        srv.start_background()
    sched = DCNFragmentScheduler(
        [("127.0.0.1", srv.port) for srv in servers], catalog=cat,
    )
    sess.attach_dcn_scheduler(sched)
    writer = Session(cat, db="test")
    expected = Session(cat, db="test").execute(
        "select count(*), sum(b) from t"
    ).rows
    fired = []

    def concurrent_write():
        # first fragment execution: land TWO writes (two version
        # bumps, so unpinned snapshots would be GC'd) before any
        # fragment scans
        if not fired:
            fired.append(1)
            writer.execute("insert into t values (97, 1000, 'w')")
            writer.execute("insert into t values (98, 1000, 'w')")

    failpoint.enable("dcn/fragment-execute", concurrent_write)
    try:
        got = sess.execute("select count(*), sum(b) from t")
    finally:
        failpoint.disable("dcn/fragment-execute")
        sess.attach_dcn_scheduler(None)
        sched.close()
        for srv in servers:
            srv.shutdown()
    assert sess._last_dcn_routed
    # snapshot isolation: the routed query read the PRE-write base on
    # every fragment — not a torn mix, not the post-write state
    assert got.rows == expected, (got.rows, expected)


# -- compaction ------------------------------------------------------------


def test_compactor_folds_into_base_and_trims(fleet):
    sess, sched, _servers, wcats = fleet
    cat = sess.catalog
    one = (PARITY_QUERIES[1],)
    sess.execute("analyze table t")
    rc0 = cat.table("test", "t").stats["a"].row_count
    w0 = [wc.table("test", "t") for wc in wcats]
    v0 = [t.version for t in w0]
    n0 = [t.nrows for t in w0]
    sess.execute("insert into t values (60,600,'s0'),(61,610,'s1')")
    sess.execute("delete from t where a = 1")
    _assert_parity(sess, cat, queries=one)  # ships
    store = cat.delta_store
    assert store.status()["entries"] >= 2
    assert sched.delta.compact_now(catalog=cat)
    # the fold ran through the ordinary columnar write path: replica
    # bases advanced and now hold the post-DML row counts
    for t, v_before, n_before in zip(w0, v0, n0):
        assert t.version > v_before
        assert t.nrows == n_before + 2 - 1
    # log trimmed; the completed fold boundary advanced
    st = store.status()
    assert st["entries"] == 0 and st["completed_fold_seq"] >= 2
    # incremental stats feed: row_count followed the net delta without
    # waiting for a full re-analyze
    assert cat.table("test", "t").stats["a"].row_count == rc0 + 1
    assert _counter_total("tidbtpu_delta_compactions_total") >= 1
    # reads after the fold merge nothing and still agree
    _assert_parity(sess, cat, queries=one)


def test_depth_threshold_triggers_background_compactor(fleet):
    from tidb_tpu.storage.delta import DeltaCompactor

    sess, sched, _servers, _wcats = fleet
    compactor = DeltaCompactor(
        sched.delta, sess.catalog, interval_s=0.0, depth_threshold=4
    )
    for i in range(3):
        sess.execute(f"insert into t values ({70 + i}, 1, 's0')")
    sess.execute("select count(*) from t")  # ship via RYW
    assert compactor.tick() is False  # depth 3 < 4
    sess.execute("insert into t values (79, 1, 's0')")
    sess.execute("select count(*) from t")
    assert compactor.tick() is True
    assert sess.catalog.delta_store.status()["entries"] == 0
    # the delta metric subsystem is live (scripts/check_metric_names
    # declares it; these are the dashboard series)
    names = {n for n, _k, _v in REGISTRY.rows()}
    for want in (
        "tidbtpu_delta_depth",
        "tidbtpu_delta_batches_total",
        "tidbtpu_delta_sync_frames_total",
        "tidbtpu_delta_sync_lag_entries",
        "tidbtpu_delta_compactions_total",
    ):
        assert any(n.startswith(want) for n in names), want


def test_worker_killed_mid_compaction_recovers(fleet):
    """The chaos episode of the tentpole: one replica DIES exactly as
    the fold barrier lands (listener closed, no reply frame, nothing
    folded — the failpoint sits before the mutation). The replicator
    quarantines it, the barrier completes on the survivor set, routed
    reads keep exact parity with zero local fallbacks, and the
    connection-leak invariants hold."""
    sess, sched, servers, _wcats = fleet
    cat = sess.catalog
    one = (PARITY_QUERIES[1],)
    sess.execute("insert into t values (80,800,'s2'),(81,810,'s0')")
    sess.execute("delete from t where a = 5")
    _assert_parity(sess, cat)  # entries shipped + buffered fleet-wide
    fold0 = cat.delta_store.completed_fold_seq

    def die_mid_fold():
        servers[0].shutdown()
        raise DropConnection("chaos: die mid-fold")

    failpoint.enable(
        "delta/compact-apply", failpoint.after_n(1, die_mid_fold)
    )
    try:
        assert sched.delta.compact_now(catalog=cat, timeout_s=5.0)
    finally:
        failpoint.disable("delta/compact-apply")
    # the dead worker quarantined; the barrier landed on the survivor
    assert len(sched.alive_endpoints()) == 1
    assert cat.delta_store.completed_fold_seq > fold0
    # the survivor keeps serving with exact parity (its fold history
    # pins the superseded base for any in-flight snapshot)
    _assert_parity(sess, cat, queries=one)
    sess.execute("insert into t values (82, 820, 's1')")
    _assert_parity(sess, cat, queries=one)
    # drained invariants (the chaos harness's leak checks): no leased
    # control connections after the dust settles
    assert all(v == 0 for v in sched.pool_leased().values())
    # the NEXT barrier also completes on the survivor set
    assert sched.delta.compact_now(catalog=cat)
    _assert_parity(sess, cat, queries=one)


# -- observability ---------------------------------------------------------


def test_explain_analyze_delta_merge_row(fleet):
    sess, sched, _servers, _wcats = fleet
    sess.execute("insert into t values (90, 900, 's1')")
    sess.execute("delete from t where a = 2")
    r = sess.execute(
        "explain analyze select c, count(*) from t group by c order by c"
    )
    text = "\n".join(row[0] for row in r.rows)
    assert "DeltaMerge depth=" in text
    assert "ins_rows=1" in text and "delete_keys=1" in text


def test_delta_store_disabled_by_sysvar():
    """tidb_tpu_delta_store = OFF restores the static-snapshot attach
    contract: no capture, no replication."""
    cat, sess = _mk_catalog()
    cat.global_sysvars["tidb_tpu_delta_store"] = False
    servers = [EngineServer(cat, port=0)]
    servers[0].start_background()
    sched = DCNFragmentScheduler(
        [("127.0.0.1", servers[0].port)], catalog=cat
    )
    try:
        sess.attach_dcn_scheduler(sched)
        assert getattr(cat, "delta_store", None) is None
        assert sched.delta is None
    finally:
        sess.attach_dcn_scheduler(None)
        sched.close()
        servers[0].shutdown()


def test_replica_seq_fence_is_at_most_once():
    """A duplicate (retransmitted) frame must not double-buffer."""
    from tidb_tpu.parallel import wire
    from tidb_tpu.storage.delta import (
        DeltaReplicaState,
        DeltaStore,
        encode_entry_frames,
    )

    cat, sess = _mk_catalog()
    store = DeltaStore.attach(cat)
    sess.execute("insert into t values (99, 990, 's0')")
    [entry] = store.entries
    wcat, _ = _mk_catalog()
    state = DeltaReplicaState(wcat)
    [frame] = encode_entry_frames(entry, cat.table("test", "t"))
    pkt = wire.decode_frame(frame)
    assert state.apply_frame(pkt) == entry.seq
    assert state.apply_frame(wire.decode_frame(frame)) == entry.seq
    rec = state._rec("test", "t")
    assert len(rec.buffered) == 1
    # merge view nets it exactly once
    ins, alive, dk, _kc, depth = state.merge_view("test", "t", 0, entry.seq)
    assert depth == 1 and sum(b.nrows for b in ins) == 1
    assert dk is None
    # delete of a pending insert nets it out
    sess.execute("delete from t where a = 99")
    e2 = store.entries[-1]
    [f2] = encode_entry_frames(e2, cat.table("test", "t"))
    state.apply_frame(wire.decode_frame(f2))
    ins, alive, dk, kc, depth = state.merge_view("test", "t", 0, e2.seq)
    assert depth == 2 and kc == "a"
    assert dk.tolist() == [99]
    assert int(sum(m.sum() for m in alive)) == 0  # netted out


def test_resync_covers_every_table(fleet):
    """A replica whose acked seq fell behind the trimmed log takes a
    FULL resync — one reload per tracked table at a distinct fresh
    seq (same-seq reloads would hit the worker's duplicate fence and
    silently skip every table after the first), and reads after it
    resolve at-or-past the resync folds."""
    sess, sched, _servers, _wcats = fleet
    cat = sess.catalog
    sess.execute("create table u (k int primary key, v int)")
    sess.execute("insert into u values (1, 5)")
    sess.execute("insert into t values (55, 550, 's0')")
    _assert_parity(sess, cat)  # ship everything
    assert sched.delta.compact_now(catalog=cat)  # fold + trim
    assert cat.delta_store.trim_floor > 0
    # simulate a re-admitted replica that lost its ack history
    ep = sched.endpoints[0]
    sched.delta.acked[ep.address] = 0
    sess.execute("insert into u values (2, 6)")
    got = sess.execute("select count(*), sum(v) from u")
    assert got.rows == [(2, 11)] and sess._last_dcn_routed
    _assert_parity(sess, cat)
    # BOTH tables resynced (the same-seq fence bug dropped the second)
    got = sess.execute("select count(*), sum(b) from t")
    exp = Session(cat, db="test").execute("select count(*), sum(b) from t")
    assert got.rows == exp.rows and sess._last_dcn_routed
