"""Unique-side join narrowing at prune time.

Reference: pkg/planner/core/rule_join_elimination.go (outer-join
elimination when the inner side is unique on the join key and unused)
and the semi-join side of rule_semi_join_rewrite.go. The columnar
analog (logical._try_join_narrow): an inner join whose unique side
contributes nothing beyond its equi-key columns becomes a SEMI join
(one existence pass instead of a row table + gathers), with parent
references to the dropped key columns substituted by the kept side's
equal keys; a left join in the same shape disappears entirely.

The physical half (physical.py fn_semi_lookup + join.lookup_build_rows):
multi-key semi/anti with a provably-unique build pair run as a
probe-aligned 1:1 lookup verifying the demoted equalities — not the
expand + row-id re-join fallback.
"""

import pytest

from tidb_tpu.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create database jn")
    s.execute("use jn")
    s.execute("create table dim (pk int primary key, grp int, pad int)")
    s.execute(
        "insert into dim values (1, 10, 0), (2, 20, 0), (3, 30, 0), "
        "(5, 50, 0)"
    )
    s.execute("create table fact (k int, v int)")
    s.execute(
        "insert into fact values (1, 100), (1, 101), (2, 200), (4, 400), "
        "(NULL, 999)"
    )
    return s


def _plan(sess, sql):
    return "\n".join(r[0] for r in sess.execute("explain " + sql).rows)


class TestInnerToSemi:
    def test_filter_only_join_becomes_semi(self, sess):
        sql = "select sum(v) from fact join dim on fact.k = dim.pk"
        assert "kind=semi" in _plan(sess, sql)
        assert sess.execute(sql).rows == [(401,)]

    def test_dropped_key_substituted(self, sess):
        # parent consumes dim.pk — equal to fact.k on surviving rows
        sql = (
            "select dim.pk, sum(v) from fact join dim on fact.k = dim.pk "
            "group by dim.pk order by dim.pk"
        )
        assert "kind=semi" in _plan(sess, sql)
        assert sess.execute(sql).rows == [(1, 201), (2, 200)]

    def test_used_column_blocks_rewrite(self, sess):
        sql = (
            "select dim.grp, sum(v) from fact join dim on fact.k = dim.pk "
            "group by dim.grp order by dim.grp"
        )
        assert "kind=semi" not in _plan(sess, sql)
        assert sess.execute(sql).rows == [(10, 201), (20, 200)]

    def test_non_unique_side_blocks_rewrite(self, sess):
        # joining fact to itself on the non-unique key must keep the
        # duplicating inner join ((1,100) matches two fact rows)
        sql = (
            "select sum(a.v) from fact a join fact b on a.k = b.k"
        )
        assert "kind=semi" not in _plan(sess, sql)
        # k=1 pairs: (100+101) emitted twice = 402; plus 200 + 400
        assert sess.execute(sql).rows == [(1002,)]


class TestLeftJoinElimination:
    def test_unused_unique_inner_side_disappears(self, sess):
        sql = "select sum(v) from fact left join dim on fact.k = dim.pk"
        assert "JoinPlan" not in _plan(sess, sql)
        assert sess.execute(sql).rows == [(1800,)]

    def test_consumed_inner_side_keeps_join(self, sess):
        sql = (
            "select fact.k, dim.pk from fact left join dim "
            "on fact.k = dim.pk order by fact.k, dim.pk"
        )
        assert "kind=left" in _plan(sess, sql)
        rows = sess.execute(sql).rows
        assert rows == [
            (None, None), (1, 1), (1, 1), (2, 2), (4, None)
        ]


class TestMultiKeySemiLookup:
    def test_demoted_pair_verified(self, sess):
        # dim unique on pk; (pk, grp) pair: grp equality demoted to
        # the verify mask in the lookup path. dim yields (1,100),
        # (2,200), (3,300), (5,500).
        sql = (
            "select fact.k, fact.v from fact "
            "where (fact.k, fact.v) in (select pk, grp * 10 from dim) "
            "order by fact.k"
        )
        assert sess.execute(sql).rows == [(1, 100), (2, 200)]

    def test_anti_multi_key(self, sess):
        sql = (
            "select fact.k, fact.v from fact "
            "where not exists (select 1 from dim "
            "where dim.pk = fact.k and dim.grp * 10 = fact.v) "
            "order by fact.v"
        )
        assert sess.execute(sql).rows == [
            (1, 101), (4, 400), (None, 999)
        ]

    def test_correlated_exists_residual(self, sess):
        # single-key EXISTS with an extra non-equi condition: the
        # residual evaluates on the looked-up unique build row
        sql = (
            "select fact.k, fact.v from fact "
            "where exists (select 1 from dim "
            "where dim.pk = fact.k and dim.grp < fact.v) "
            "order by fact.v"
        )
        assert sess.execute(sql).rows == [(1, 100), (1, 101), (2, 200)]
