"""Engine watch (obs/engine_watch.py): jit-compilation and retrace
accounting, host<->device transfer bytes, device-memory high-water, and
the information_schema.TPU_ENGINE surface.

The retrace test is the point: a *shape-polymorphic* query (same plan
signature, growing input tile) must show up as tidbtpu_engine_retraces —
the silent recompile that dominates accelerator latency when unobserved.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.utils.metrics import REGISTRY


@pytest.fixture()
def sess():
    return Session(Catalog())


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


def test_jit_compilations_counted(sess):
    sess.execute("create table ew1 (a bigint)")
    sess.execute("insert into ew1 values (1),(2),(3)")
    before = _counter("tidbtpu_engine_jit_compilations")
    sess.execute("select sum(a) from ew1 where a > 1")
    assert _counter("tidbtpu_engine_jit_compilations") > before
    # a repeat at the same shape reuses the steady program: no new jit
    again = _counter("tidbtpu_engine_jit_compilations")
    sess.execute("select sum(a) from ew1 where a > 1")
    assert _counter("tidbtpu_engine_jit_compilations") == again


def test_retrace_counted_for_shape_polymorphic_query(sess):
    sess.execute("create table ew2 (a bigint)")
    sess.execute(
        "insert into ew2 values " + ",".join(f"({i})" for i in range(10))
    )
    sess.execute("select sum(a) from ew2")  # first compile at tile 0
    retraces0 = _counter("tidbtpu_engine_retraces")
    # grow the table past the padded capacity tile: the SAME plan
    # signature now traces at a bigger input shape
    for lo in range(0, 9000, 1000):
        sess.execute(
            "insert into ew2 values "
            + ",".join(f"({i})" for i in range(lo, lo + 1000))
        )
    r = sess.must_query("select sum(a) from ew2")
    assert r.rows[0][0] == sum(range(10)) + sum(range(9000))
    assert _counter("tidbtpu_engine_retraces") > retraces0


def test_transfer_bytes_and_device_mem(sess):
    sess.execute("create table ew3 (a bigint, b bigint)")
    sess.execute("insert into ew3 values (1, 2),(3, 4)")
    h2d0 = _counter("tidbtpu_engine_h2d_bytes")
    d2h0 = _counter("tidbtpu_engine_d2h_bytes")
    sess.execute("select a + b from ew3 where a > 0")
    assert _counter("tidbtpu_engine_h2d_bytes") > h2d0
    assert _counter("tidbtpu_engine_d2h_bytes") > d2h0
    assert REGISTRY.gauge(
        "tidbtpu_engine_device_mem_highwater_bytes"
    ).value > 0


def test_tpu_engine_virtual_table(sess):
    sess.execute("create table ew4 (a bigint)")
    sess.execute("insert into ew4 values (41),(42)")
    sess.execute("select max(a) from ew4 where a > 40")
    r = sess.must_query(
        "select query, jit_compilations, h2d_bytes, device_mem_peak_bytes "
        "from information_schema.tpu_engine "
        "where query like '%max(a) from ew4%'"
    )
    assert r.rows, "the statement's engine record is missing"
    _q, jits, h2d, mem = r.rows[-1]
    assert jits >= 1 and h2d > 0 and mem > 0
