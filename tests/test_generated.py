"""Generated columns (stored + virtual).

Reference: pkg/ddl/generated_column.go:125 (dependency validation),
pkg/table/tables.go (stored-generated evaluation on the write path).
Both flavors materialize on write here — generated expressions are
required deterministic, so eager evaluation is observationally
identical; VIRTUAL/STORED is kept for SHOW CREATE fidelity.
"""

import pytest

from tidb_tpu.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create database gentest")
    s.execute("use gentest")
    return s


class TestCreateInsert:
    def test_stored_computes_on_insert(self, sess):
        sess.execute(
            "create table t (a int, b int, "
            "c int generated always as (a + b) stored)"
        )
        sess.execute("insert into t (a, b) values (1, 2), (10, 20)")
        assert sess.execute("select c from t order by a").rows == [
            (3,), (30,)
        ]

    def test_virtual_computes_on_insert(self, sess):
        sess.execute(
            "create table t (a int, b int, c int as (a * b) virtual)"
        )
        sess.execute("insert into t (a, b) values (3, 4)")
        assert sess.execute("select c from t").rows == [(12,)]

    def test_string_expr(self, sess):
        sess.execute(
            "create table p (first varchar(8), last varchar(8), "
            "fullname varchar(20) as (concat(first, ' ', last)) stored)"
        )
        sess.execute("insert into p (first, last) values ('Ada', 'Byron')")
        assert sess.execute("select fullname from p").rows == [("Ada Byron",)]

    def test_case_expr_and_chained_gen(self, sess):
        sess.execute(
            "create table t (a int, "
            "b int as (a * 2) stored, "
            "big varchar(4) as (case when b > 10 then 'yes' else 'no' end)"
            " stored)"
        )
        sess.execute("insert into t (a) values (3), (30)")
        assert sess.execute("select big from t order by a").rows == [
            ("no",), ("yes",)
        ]

    def test_null_propagation(self, sess):
        sess.execute(
            "create table t (a int, b int, c int as (a + b) stored)"
        )
        sess.execute("insert into t (a, b) values (1, null)")
        assert sess.execute("select c from t").rows == [(None,)]

    def test_explicit_value_rejected(self, sess):
        sess.execute("create table t (a int, c int as (a + 1) stored)")
        with pytest.raises(ValueError, match="generated column"):
            sess.execute("insert into t (a, c) values (1, 99)")
        # NULL placeholder means "compute"
        sess.execute("insert into t values (1, null)")
        assert sess.execute("select c from t").rows == [(2,)]

    def test_insert_select_computes(self, sess):
        sess.execute("create table src (x int)")
        sess.execute("insert into src values (5), (6)")
        sess.execute("create table t (a int, c int as (a * 10) stored)")
        sess.execute("insert into t (a) select x from src")
        assert sess.execute("select sum(c) from t").rows == [(110,)]


class TestDDLValidation:
    def test_unknown_dep_rejected(self, sess):
        with pytest.raises(ValueError, match="unknown or later"):
            sess.execute("create table t (a int, c int as (zz + 1) stored)")

    def test_later_generated_dep_rejected(self, sess):
        with pytest.raises(ValueError, match="unknown or later"):
            sess.execute(
                "create table t (a int, c int as (d + 1) stored, "
                "d int as (a + 1) stored)"
            )

    def test_autoinc_dep_rejected(self, sess):
        with pytest.raises(ValueError, match="AUTO_INCREMENT"):
            sess.execute(
                "create table t (id int primary key auto_increment, "
                "c int as (id + 1) stored)"
            )

    def test_default_on_generated_rejected(self, sess):
        with pytest.raises(ValueError, match="DEFAULT"):
            sess.execute(
                "create table t (a int, c int as (a + 1) stored default 5)"
            )

    def test_unsupported_function_rejected_at_ddl(self, sess):
        with pytest.raises(ValueError, match="unsupported function"):
            sess.execute(
                "create table t (a int, c double as (rand() + a) stored)"
            )

    def test_virtual_pk_rejected(self, sess):
        with pytest.raises(ValueError, match="STORED"):
            sess.execute(
                "create table t (a int, "
                "c int as (a + 1) virtual, primary key (c))"
            )


class TestDML:
    def test_update_recomputes(self, sess):
        sess.execute(
            "create table t (a int, b int, c int as (a + b) stored)"
        )
        sess.execute("insert into t (a, b) values (1, 2)")
        sess.execute("update t set a = 100 where b = 2")
        assert sess.execute("select c from t").rows == [(102,)]

    def test_set_generated_rejected(self, sess):
        sess.execute("create table t (a int, c int as (a + 1) stored)")
        sess.execute("insert into t (a) values (1)")
        with pytest.raises(ValueError, match="generated"):
            sess.execute("update t set c = 5")

    def test_on_duplicate_recomputes(self, sess):
        sess.execute(
            "create table t (a int primary key, b int, "
            "c int as (a + b) stored)"
        )
        sess.execute("insert into t (a, b) values (1, 10)")
        sess.execute(
            "insert into t (a, b) values (1, 99) "
            "on duplicate key update b = 20"
        )
        assert sess.execute("select c from t").rows == [(21,)]

    def test_txn_insert_commit(self, sess):
        sess.execute("create table t (a int, c int as (a * 3) stored)")
        sess.execute("begin")
        sess.execute("insert into t (a) values (7)")
        assert sess.execute("select c from t").rows == [(21,)]
        sess.execute("commit")
        assert sess.execute("select c from t").rows == [(21,)]

    def test_where_on_generated(self, sess):
        sess.execute("create table t (a int, c int as (a * 2) stored)")
        sess.execute("insert into t (a) values (1), (5), (9)")
        assert sess.execute(
            "select a from t where c >= 10 order by a"
        ).rows == [(5,), (9,)]

    def test_index_on_generated(self, sess):
        sess.execute("create table t (a int, c int as (a * 2) stored)")
        sess.execute("create index ic on t (c)")
        sess.execute("insert into t (a) values (1), (5), (9)")
        assert sess.execute(
            "select a from t where c = 10"
        ).rows == [(5,)]


class TestAlter:
    def test_alter_add_generated_backfills(self, sess):
        sess.execute("create table t (a int, b int)")
        sess.execute("insert into t values (1, 2), (3, 4)")
        sess.execute(
            "alter table t add column s int "
            "generated always as (a + b) stored"
        )
        assert sess.execute("select s from t order by a").rows == [
            (3,), (7,)
        ]
        # new writes keep computing
        sess.execute("insert into t (a, b) values (10, 20)")
        assert sess.execute("select s from t where a = 10").rows == [(30,)]

    def test_modify_dep_recomputes(self, sess):
        sess.execute(
            "create table t (a varchar(8), c varchar(16) "
            "as (concat(a, '!')) stored)"
        )
        sess.execute("insert into t (a) values ('7'), ('8')")
        # convert a string->int: the stored generated column recomputes
        # through the reorg over converted values
        sess.execute("alter table t modify column a int")
        assert sess.execute("select c from t order by a").rows == [
            ("7!",), ("8!",)
        ]

    def test_drop_dep_blocked(self, sess):
        sess.execute("create table t (a int, c int as (a + 1) stored)")
        with pytest.raises(ValueError, match="generated column"):
            sess.execute("alter table t drop column a")

    def test_drop_generated_col_ok(self, sess):
        sess.execute("create table t (a int, c int as (a + 1) stored)")
        sess.execute("insert into t (a) values (1)")
        sess.execute("alter table t drop column c")
        sess.execute("insert into t values (2)")
        assert sess.execute("select a from t order by a").rows == [
            (1,), (2,)
        ]

    def test_rename_dep_blocked(self, sess):
        sess.execute("create table t (a int, c int as (a + 1) stored)")
        with pytest.raises(ValueError, match="generated column"):
            sess.execute("alter table t rename column a to z")

    def test_change_rename_dep_blocked_on_conversion_path(self, sess):
        # CHANGE with a LOSSY conversion + rename of a generated dep
        # must reject BEFORE publishing anything (review finding r5)
        sess.execute(
            "create table t (a varchar(10), "
            "g int as (char_length(a)) stored)"
        )
        sess.execute("insert into t (a) values ('123')")
        with pytest.raises(ValueError, match="generated column"):
            sess.execute("alter table t change a b int")
        # table must be untouched and still writable
        sess.execute("insert into t (a) values ('4567')")
        assert sess.execute("select g from t order by g").rows == [
            (3,), (4,)
        ]

    def test_modify_to_generated_rejected(self, sess):
        sess.execute("create table t (a int, c int)")
        with pytest.raises(ValueError, match="GENERATED"):
            sess.execute("alter table t modify c int as (a + 1) stored")

    def test_alter_add_generated_with_default_rejected(self, sess):
        sess.execute("create table t (a int)")
        with pytest.raises(ValueError, match="DEFAULT"):
            sess.execute(
                "alter table t add column g int default 9 as (a * 2) stored"
            )

    def test_show_create_contains_clause(self, sess):
        sess.execute(
            "create table t (a int, c int generated always as (a + 1) "
            "virtual)"
        )
        ddl = sess.execute("show create table t").rows[0][1].lower()
        assert "generated always as (a + 1) virtual" in ddl
