"""Race detection analog: lock-order inversion checking + stress.

Reference: `make race` (ut --race, Makefile:192-194) + the unistore
wait-for deadlock detector (unistore/tikv/detector.go). Python's GIL
removes torn reads; the surviving race class is lock-order inversion
between engine mutexes. utils/racecheck.py wraps the engine's real
locks (table / catalog / commit / CDC / log-backup / sequence / DXF)
when enabled and raises on any order that could deadlock two threads.
"""

import threading

import pytest

from tidb_tpu.utils import racecheck
from tidb_tpu.utils.racecheck import LockOrderError, TrackedLock


@pytest.fixture()
def racecheck_on():
    racecheck.enable()
    racecheck.reset()
    try:
        yield
    finally:
        racecheck.disable()
        racecheck.reset()


class TestDetector:
    def test_inversion_detected(self, racecheck_on):
        a, b = TrackedLock("A"), TrackedLock("B")
        with a:
            with b:
                pass  # records A -> B
        with pytest.raises(LockOrderError, match="inversion"):
            with b:
                with a:  # B -> A reverses it
                    pass

    def test_consistent_order_is_silent(self, racecheck_on):
        a, b, c = TrackedLock("A"), TrackedLock("B"), TrackedLock("C")
        for _ in range(3):
            with a, b, c:
                pass
        assert racecheck.edge_graph()["A"] == {"B", "C"}

    def test_self_deadlock_detected(self, racecheck_on):
        a = TrackedLock("A")
        a2 = TrackedLock("A")  # same CLASS, different instance
        with pytest.raises(LockOrderError, match="self-deadlock"):
            with a:
                with a2:
                    pass

    def test_cross_thread_inversion(self, racecheck_on):
        """Thread 1 records A->B; thread 2's B->A raises even though no
        actual deadlock happened on this run — the detector flags the
        POSSIBLE interleaving, like the Go race detector's happens-
        before analysis."""
        a, b = TrackedLock("A"), TrackedLock("B")
        t = threading.Thread(target=lambda: a.acquire() and b.acquire())
        t.start()
        t.join()
        b._lk.release()  # release thread-1's holds for the test
        a._lk.release()
        errs = []

        def inverted():
            try:
                with b:
                    with a:
                        pass
            except LockOrderError as e:
                errs.append(e)

        t2 = threading.Thread(target=inverted)
        t2.start()
        t2.join()
        assert errs, "cross-thread inversion must be detected"

    def test_disabled_returns_plain_lock(self):
        racecheck.disable()
        lk = racecheck.make_lock("x")
        assert isinstance(lk, type(threading.Lock()))


class TestEngineStress:
    def test_concurrent_subsystems_keep_consistent_lock_order(
        self, racecheck_on
    ):
        """The `make race` tier: DML commits, online DDL, GC, CDC and
        log-backup advancers, and sequence allocation hammer one
        catalog from multiple threads with every engine lock order-
        tracked. Any inversion (potential deadlock) raises."""
        from tidb_tpu.session import Session
        from tidb_tpu.storage import Catalog
        from tidb_tpu.storage.cdc import Changefeed
        from tidb_tpu.storage.logbackup import LogBackupTask

        cat = Catalog()
        s = Session(cat)
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (id int primary key, v int)")
        s.execute("create sequence sq")
        s.execute("insert into t values (0, 0)")

        feed = Changefeed(cat, "memory://race-cdc")
        feed.start()
        backup = LogBackupTask(cat, "memory://race-br")
        backup.start()

        stop = threading.Event()
        errors = []

        def guard(fn):
            def run():
                i = 0
                try:
                    while not stop.is_set() and i < 60:
                        fn(i)
                        i += 1
                except LockOrderError as e:
                    errors.append(e)
                    stop.set()
                except Exception:
                    pass  # semantic conflicts are fine; order errors not

            return run

        sess2 = Session(cat, db="d")
        sess3 = Session(cat, db="d")
        threads = [
            threading.Thread(target=guard(
                lambda i: sess2.execute(
                    f"insert into t values ({i + 1}, {i})"
                )
            )),
            threading.Thread(target=guard(
                lambda i: feed.advance()
            )),
            threading.Thread(target=guard(
                lambda i: backup.advance()
            )),
            threading.Thread(target=guard(
                lambda i: sess3.execute("select nextval(sq)")
            )),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        stop.set()
        feed.stop()
        backup.stop()
        assert not errors, f"lock-order inversion under stress: {errors[0]}"
        # the tracked graph actually observed the cross-subsystem edges
        g = racecheck.edge_graph()
        assert "table" in g or any("table" in v for v in g.values()), (
            "stress run never exercised the table lock"
        )
