"""Race detection analog: lock-order inversion checking + stress.

Reference: `make race` (ut --race, Makefile:192-194) + the unistore
wait-for deadlock detector (unistore/tikv/detector.go). Python's GIL
removes torn reads; the surviving race class is lock-order inversion
between engine mutexes. utils/racecheck.py wraps the engine's real
locks (table / catalog / commit / CDC / log-backup / sequence / DXF)
when enabled and raises on any order that could deadlock two threads.
"""

import threading
import time

import pytest

from tidb_tpu.utils import racecheck
from tidb_tpu.utils.racecheck import LockOrderError, TrackedLock


@pytest.fixture()
def racecheck_on():
    racecheck.enable()
    racecheck.reset()
    try:
        yield
    finally:
        racecheck.disable()
        racecheck.reset()


class TestDetector:
    def test_inversion_detected(self, racecheck_on):
        a, b = TrackedLock("A"), TrackedLock("B")
        with a:
            with b:
                pass  # records A -> B
        with pytest.raises(LockOrderError, match="inversion"):
            with b:
                with a:  # B -> A reverses it
                    pass

    def test_consistent_order_is_silent(self, racecheck_on):
        a, b, c = TrackedLock("A"), TrackedLock("B"), TrackedLock("C")
        for _ in range(3):
            with a, b, c:
                pass
        assert racecheck.edge_graph()["A"] == {"B", "C"}

    def test_self_deadlock_detected(self, racecheck_on):
        a = TrackedLock("A")
        a2 = TrackedLock("A")  # same CLASS, different instance
        with pytest.raises(LockOrderError, match="self-deadlock"):
            with a:
                with a2:
                    pass

    def test_cross_thread_inversion(self, racecheck_on):
        """Thread 1 records A->B; thread 2's B->A raises even though no
        actual deadlock happened on this run — the detector flags the
        POSSIBLE interleaving, like the Go race detector's happens-
        before analysis."""
        a, b = TrackedLock("A"), TrackedLock("B")
        t = threading.Thread(target=lambda: a.acquire() and b.acquire())
        t.start()
        t.join()
        b._lk.release()  # release thread-1's holds for the test
        a._lk.release()
        errs = []

        def inverted():
            try:
                with b:
                    with a:
                        pass
            except LockOrderError as e:
                errs.append(e)

        t2 = threading.Thread(target=inverted)
        t2.start()
        t2.join()
        assert errs, "cross-thread inversion must be detected"

    def test_disabled_returns_plain_lock(self):
        racecheck.disable()
        lk = racecheck.make_lock("table")
        assert isinstance(lk, type(threading.Lock()))

    def test_undeclared_class_rejected(self):
        """make_lock names are an API (the failpoint-SITES contract):
        an undeclared class raises even with checking disabled, so a
        typo cannot silently fork the lock hierarchy."""
        racecheck.disable()
        with pytest.raises(ValueError, match="undeclared lock class"):
            racecheck.make_lock("no-such-class")
        with pytest.raises(ValueError, match="undeclared lock class"):
            racecheck.make_rlock("no-such-class")
        with pytest.raises(ValueError, match="undeclared lock class"):
            racecheck.make_condition("no-such-class")


class TestEngineStress:
    def test_concurrent_subsystems_keep_consistent_lock_order(
        self, racecheck_on
    ):
        """The `make race` tier: DML commits, online DDL, GC, CDC and
        log-backup advancers, and sequence allocation hammer one
        catalog from multiple threads with every engine lock order-
        tracked. Any inversion (potential deadlock) raises."""
        from tidb_tpu.session import Session
        from tidb_tpu.storage import Catalog
        from tidb_tpu.storage.cdc import Changefeed
        from tidb_tpu.storage.logbackup import LogBackupTask

        cat = Catalog()
        s = Session(cat)
        s.execute("create database d")
        s.execute("use d")
        s.execute("create table t (id int primary key, v int)")
        s.execute("create sequence sq")
        s.execute("insert into t values (0, 0)")

        feed = Changefeed(cat, "memory://race-cdc")
        feed.start()
        backup = LogBackupTask(cat, "memory://race-br")
        backup.start()

        stop = threading.Event()
        errors = []

        def guard(fn):
            def run():
                i = 0
                try:
                    while not stop.is_set() and i < 60:
                        fn(i)
                        i += 1
                except LockOrderError as e:
                    errors.append(e)
                    stop.set()
                except Exception:
                    pass  # semantic conflicts are fine; order errors not

            return run

        sess2 = Session(cat, db="d")
        sess3 = Session(cat, db="d")
        threads = [
            threading.Thread(target=guard(
                lambda i: sess2.execute(
                    f"insert into t values ({i + 1}, {i})"
                )
            )),
            threading.Thread(target=guard(
                lambda i: feed.advance()
            )),
            threading.Thread(target=guard(
                lambda i: backup.advance()
            )),
            threading.Thread(target=guard(
                lambda i: sess3.execute("select nextval(sq)")
            )),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        stop.set()
        feed.stop()
        backup.stop()
        assert not errors, f"lock-order inversion under stress: {errors[0]}"
        # the tracked graph actually observed the cross-subsystem edges
        g = racecheck.edge_graph()
        assert "table" in g or any("table" in v for v in g.values()), (
            "stress run never exercised the table lock"
        )


class TestMakers:
    """make_rlock / make_condition — the PR 7 wrapper growth."""

    def test_rlock_reentry_same_instance_ok(self, racecheck_on):
        lk = racecheck.make_rlock("shuffle.exec")
        with lk:
            with lk:  # reentrant on the SAME instance: legal
                pass
        with lk:
            pass

    def test_rlock_same_class_other_instance_is_self_deadlock(
        self, racecheck_on
    ):
        lk1 = racecheck.make_rlock("shuffle.exec")
        lk2 = racecheck.make_rlock("shuffle.exec")
        with pytest.raises(LockOrderError, match="self-deadlock"):
            with lk1:
                with lk2:
                    pass

    def test_rlock_inversion_detected(self, racecheck_on):
        a = racecheck.make_rlock("shuffle.exec")
        b = racecheck.make_lock("table")
        with a:
            with b:
                pass
        with pytest.raises(LockOrderError, match="inversion"):
            with b:
                with a:
                    pass

    def test_condition_wait_notify_roundtrip(self, racecheck_on):
        cv = racecheck.make_condition("shuffle.store")
        hits = []

        def consumer():
            with cv:
                while not hits:
                    cv.wait(0.5)
                hits.append("consumed")

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        time.sleep(0.05)
        with cv:
            hits.append("produced")
            cv.notify_all()
        t.join(timeout=5)
        assert hits == ["produced", "consumed"]

    def test_condition_inversion_detected(self, racecheck_on):
        cv = racecheck.make_condition("shuffle.store")
        lk = racecheck.make_lock("table")
        with cv:
            with lk:
                pass
        with pytest.raises(LockOrderError, match="inversion"):
            with lk:
                with cv:
                    pass

    def test_condition_self_deadlock_detected(self, racecheck_on):
        cv1 = racecheck.make_condition("shuffle.store")
        cv2 = racecheck.make_condition("shuffle.store")
        with pytest.raises(LockOrderError, match="self-deadlock"):
            with cv1:
                with cv2:
                    pass

    def test_disabled_returns_plain_primitives(self):
        racecheck.disable()
        assert isinstance(
            racecheck.make_rlock("shuffle.exec"),
            type(threading.RLock()),
        )
        assert isinstance(
            racecheck.make_condition("shuffle.store"),
            threading.Condition,
        )


class TestEdgeOrigin:
    """Satellite: _record_edge must report the acquisition CALL SITE
    (the caller's `with` line), not an arbitrary ancestor frame from a
    fixed extract_stack slice."""

    def test_origin_is_the_callers_with_line(self, racecheck_on):
        a, b = TrackedLock("A"), TrackedLock("B")

        def nest_deeply():
            # extra frames between the test and the acquisition, so a
            # fixed-limit stack slice lands on the WRONG frame
            def lvl1():
                def lvl2():
                    with a:
                        with b:  # <- the A->B edge records HERE
                            pass
                lvl2()
            lvl1()

        nest_deeply()
        origin = racecheck.edge_origins()[("A", "B")]
        fname, lineno = origin.rsplit(":", 1)
        assert fname.endswith("test_race.py"), origin
        src = open(__file__, encoding="utf-8").readlines()
        assert "with b:" in src[int(lineno) - 1], (
            f"origin {origin} is not the inner `with b:` line: "
            f"{src[int(lineno) - 1]!r}"
        )

    def test_origin_never_points_into_racecheck(self, racecheck_on):
        a, b = TrackedLock("A2"), TrackedLock("B2")
        with a:
            with b:
                pass
        for (h, acq), origin in racecheck.edge_origins().items():
            assert "racecheck.py" not in origin, (h, acq, origin)


class TestMPPTierStress:
    """The PR 7 `make race` tier: the MPP data plane, metrics and
    flight-recorder locks swept onto racecheck classes, hammered from
    many threads with order tracking on. Any inversion raises."""

    def test_shuffle_store_metrics_flight_hammer(self, racecheck_on):
        """8 threads interleave ShuffleStore push/admits/wait_side,
        labeled metric updates, StmtSummary/SlowLog records, flight
        begin/note/finish and LinkRegistry notes — every lock class of
        the serving tier participates in one edge graph."""
        from tidb_tpu.obs.flight import FlightRecorder, LinkRegistry
        from tidb_tpu.parallel.shuffle import ShuffleStore
        from tidb_tpu.utils.metrics import (
            Registry,
            SlowLog,
            StmtSummary,
        )

        store = ShuffleStore()
        reg = Registry()
        flight = FlightRecorder(capacity=32)
        links = LinkRegistry()
        summary = StmtSummary(capacity=64)
        slowlog = SlowLog(capacity=64)
        errors = []
        stop = threading.Event()

        ok: list = []  # (hammer name) per fully-successful iteration

        def guard(fn):
            def run():
                for i in range(200):
                    if stop.is_set():
                        return
                    try:
                        fn(i)
                    except LockOrderError as e:
                        errors.append(e)
                        stop.set()
                        return
                    except Exception:
                        # benign cross-thread races (push vs discard)
                        # may fail ONE iteration; keep hammering — a
                        # broken API that fails every iteration is
                        # caught by the per-hammer success assert below
                        continue
                    ok.append(fn.__name__)

            return run

        def pusher(i):
            sid = f"s{i % 4}"
            store.open(sid, attempt=1, m=2)
            store.push(sid, 1, 2, side=0, sender=i % 2, seq=i, payload=[(i,)])
            store.admits(sid, 1, 0, i % 2, i)
            if i % 16 == 0:
                store.discard(sid)

        def metrics_hammer(i):
            reg.counter("tidbtpu_shuffle_bytes_total", "x",
                        labels=("src", "dst")).labels("a", "b").inc(i)
            reg.gauge("tidbtpu_link_rtt_seconds", "x").set(i * 0.1)
            reg.histogram("tidbtpu_flight_query_seconds", "x").observe(
                0.001 * i
            )
            reg.render()

        def flight_hammer(i):
            flight.begin(f"select {i}", conn_id=i)
            flight.note_phase("execute", 0.001)
            flight.note_phase("shuffle-wait", 0.002, nbytes=10)
            rec = flight.finish(0.01)
            summary.record(f"select {i % 8}", 0.01, flight=rec)
            if i % 8 == 0:
                slowlog.record(f"select {i}", 0.5, digest="d")
                summary.rows_full()
            flight.rows()

        def links_hammer(i):
            links.note_handshake(f"h{i % 3}", rtt_s=0.001, offset_s=0.0)
            links.note_heartbeat(f"h{i % 3}", ok=bool(i % 2))
            links.note_tunnel("a", f"h{i % 3}", {"bytes": i, "frames": 1})
            if i % 10 == 0:
                links.rows()

        threads = [
            threading.Thread(target=guard(fn), daemon=True)
            for fn in (
                pusher, pusher, metrics_hammer, metrics_hammer,
                flight_hammer, flight_hammer, links_hammer, links_hammer,
            )
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop.set()
        # a timed-out join leaves the thread alive — a genuine deadlock
        # (the failure class this test exists for) must FAIL, not pass
        # with every hammer silently stuck
        hung = [t.name for t in threads if t.is_alive()]
        assert not hung, f"hammer threads deadlocked (join timed out): {hung}"
        assert not errors, f"lock-order inversion under stress: {errors[0]}"
        # every hammer completed iterations — an API break that kills a
        # hammer on its first call must fail the test, not degrade the
        # stress to a near no-op
        ran = set(ok)
        for fn_name in ("pusher", "metrics_hammer", "flight_hammer",
                        "links_hammer"):
            assert fn_name in ran, (
                f"{fn_name} never completed one iteration — "
                "the stress exercised nothing for that subsystem"
            )
        # participation is asserted on seen_classes(): every tracked
        # acquisition counts, whether or not it happened to NEST
        # (edge_graph() records only held->acquiring pairs)
        seen = racecheck.seen_classes()
        for expected in (
            "shuffle.store", "metrics.metric", "metrics.registry",
            "flight.links", "metrics.stmt_summary",
        ):
            assert expected in seen, (
                f"{expected} never participated in the run: {seen}"
            )

    def test_in_process_shuffle_stage_under_racecheck(self, racecheck_on):
        """The existing in-process shuffle stage (two EngineServers,
        repartition join + fragment-sliced GROUP BY through real
        tunnels), re-run with every swept lock order-tracked — the
        one-`--race`-run-guards-the-tier contract. Everything is
        constructed AFTER enable() so instance locks are tracked."""
        from tidb_tpu.parallel.dcn import DCNFragmentScheduler
        from tidb_tpu.parser.sqlparse import parse
        from tidb_tpu.planner.logical import build_query
        from tidb_tpu.server.engine_rpc import EngineServer
        from tidb_tpu.session.session import Session

        sess = Session()
        sess.execute("create table t (a int, b varchar(8))")
        sess.execute(
            "insert into t values (1,'x'),(2,'y'),(3,'x'),(4,null),"
            "(2,'x'),(7,'y')"
        )
        sess.execute("create table u (k int, v int)")
        sess.execute(
            "insert into u values (1,10),(2,20),(3,30),(4,40),(1,11)"
        )
        q = (
            "select b, count(*), sum(v) from t join u on a = k "
            "group by b order by b"
        )
        exp = sess.must_query(q).rows
        servers = [
            EngineServer(sess.catalog, port=0) for _ in range(2)
        ]
        for s in servers:
            s.start_background()
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in servers],
            catalog=sess.catalog, shuffle_mode="always",
        )
        try:
            plan = build_query(
                parse(q)[0], sess.catalog, "test", sess._scalar_subquery
            )
            _cols, got = sched.execute_plan(plan)
            assert got == exp
        finally:
            sched.close()
            for s in servers:
                s.shutdown()
        # participation via seen_classes(): nested-pair edges are a
        # bonus, not the signal. Metrics classes are deliberately NOT
        # asserted here — the data plane's counters live in the global
        # REGISTRY and may predate enable() in a full-suite run (plain
        # locks); the hammer test above proves metrics participation
        # with a locally-constructed Registry instead.
        seen = racecheck.seen_classes()
        for expected in (
            "shuffle.store", "shuffle.exec", "shuffle.tunnel",
            "dcn.scheduler", "dcn.ledger", "dcn.pool",
        ):
            assert expected in seen, (
                f"{expected} never participated in the run: {seen}"
            )

    def test_concurrent_queries_one_fleet_under_racecheck(
        self, racecheck_on
    ):
        """PR 8 serving-tier hammer: K DISTINCT queries run
        CONCURRENTLY (several rounds each) through ONE in-process
        2-server fleet with every swept lock order-tracked. Asserts
        per-query row parity on every round (a frame cross-admitted
        into another query's shuffle stage, or a ledger token reused
        across qids, would corrupt a result) and ZERO cross-query
        frame fences tripping (stale/duplicate drop counters do not
        move in a loss-free concurrent run — each query's stage is
        sid-isolated via the strictly-unique qid allocator)."""
        from tidb_tpu.parallel.dcn import DCNFragmentScheduler
        from tidb_tpu.parser.sqlparse import parse
        from tidb_tpu.planner.logical import build_query
        from tidb_tpu.server.engine_rpc import EngineServer
        from tidb_tpu.session.session import Session
        from tidb_tpu.utils.metrics import REGISTRY

        def reg_total(prefix):
            return sum(
                v for n, _k, v in REGISTRY.rows() if n.startswith(prefix)
            )

        sess = Session()
        sess.execute("create table t (a int, b varchar(8), c int)")
        sess.execute(
            "insert into t values (1,'x',5),(2,'y',6),(3,'x',7),"
            "(4,null,8),(2,'x',9),(7,'y',1),(1,'y',2),(3,'z',3)"
        )
        sess.execute("create table u (k int, v int)")
        sess.execute(
            "insert into u values (1,10),(2,20),(3,30),(4,40),(1,11),"
            "(7,70),(3,31)"
        )
        queries = [
            "select b, count(*), sum(v) from t join u on a = k "
            "group by b order by b",
            "select b, count(distinct a) from t group by b order by b",
            "select a, count(*), sum(c) from t join u on a = k "
            "group by a order by a",
            "select b, max(c), min(c) from t group by b order by b",
        ]
        expected = [sess.must_query(q).rows for q in queries]
        servers = [EngineServer(sess.catalog, port=0) for _ in range(2)]
        for s in servers:
            s.start_background()
        sched = DCNFragmentScheduler(
            [("127.0.0.1", s.port) for s in servers],
            catalog=sess.catalog, shuffle_mode="always",
        )
        stale0 = reg_total("tidbtpu_shuffle_stale_dropped")
        dups0 = reg_total("tidbtpu_shuffle_duplicates_dropped")
        ledger_dups0 = reg_total("tidbtpu_dcn_duplicates_dropped")
        plans = [
            build_query(
                parse(q)[0], sess.catalog, "test", sess._scalar_subquery
            )
            for q in queries
        ]
        errors = []
        done = []

        def runner(i):
            try:
                for _round in range(3):
                    _cols, got = sched.execute_plan(plans[i])
                    assert got == expected[i], (
                        f"query {i} round {_round}: cross-query "
                        f"corruption?\n got={got}\n exp={expected[i]}"
                    )
                done.append(i)
            except Exception as e:
                errors.append((i, e))

        threads = [
            threading.Thread(target=runner, args=(i,), daemon=True)
            for i in range(len(queries))
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            hung = [t.name for t in threads if t.is_alive()]
            assert not hung, f"query threads deadlocked: {hung}"
            assert not errors, f"concurrent query failed: {errors[0]}"
            assert sorted(done) == list(range(len(queries)))
            # zero cross-query frame admits: no fence ever fired — the
            # sid isolation means no frame was ever even CANDIDATE for
            # another query's stage (loss-free run: retries are the
            # only legitimate source of stale/dup drops)
            assert reg_total("tidbtpu_shuffle_stale_dropped") == stale0
            assert reg_total("tidbtpu_shuffle_duplicates_dropped") == dups0
            assert reg_total("tidbtpu_dcn_duplicates_dropped") == ledger_dups0
        finally:
            sched.close()
            for s in servers:
                s.shutdown()
        # dcn.py's module-level allocators were constructed at import
        # time (racecheck off -> untracked plain locks), so stress a
        # freshly-built allocator under the live detector: serving.qid
        # participates in the edge graph AND uniqueness holds under
        # the same contention the fleet run just produced
        from tidb_tpu.parallel.serving import QidAllocator

        alloc = QidAllocator(start=1)
        buckets = [[] for _ in range(8)]

        def grab(bucket):
            for _ in range(250):
                bucket.append(alloc.next())

        hammers = [
            threading.Thread(target=grab, args=(b,), daemon=True)
            for b in buckets
        ]
        for h in hammers:
            h.start()
        for h in hammers:
            h.join(timeout=60)
        ids = [q for b in buckets for q in b]
        assert sorted(ids) == list(range(1, 8 * 250 + 1))
        seen = racecheck.seen_classes()
        for expected_cls in ("dcn.pool", "serving.qid", "shuffle.store"):
            assert expected_cls in seen, (
                f"{expected_cls} never participated: {seen}"
            )
