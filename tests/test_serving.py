"""Serving-tier unit tests: admission controller semantics, the
strictly-unique qid allocator under thread stress, the cross-session
shared compiled-plan cache, and statement-priority mapping.

Reference: TiDB resource control's priority queueing and the MinTSO
scheduler's memory-gated MPP admission; the end-to-end serving proof
lives in tests/test_multihost.py (2-process fleet, 8 session threads)
and bench.py --serve-load (64+ MySQL-protocol sessions).
"""

import threading
import time

import pytest

from tidb_tpu.parallel.serving import (
    OUTCOMES,
    AdmissionController,
    AdmissionRejected,
    QidAllocator,
)
from tidb_tpu.utils import racecheck


def _ctl(**kw):
    kw.setdefault("budget_bytes", 100)
    kw.setdefault("default_estimate_bytes", 40)
    kw.setdefault("queue_timeout_s", 5.0)
    return AdmissionController(**kw)


class TestAdmission:
    def test_admit_within_budget(self):
        a = _ctl()
        t1 = a.admit("q1")
        t2 = a.admit("q2")
        st = a.status()
        assert st["running"] == 2 and st["inuse_bytes"] == 80
        t1.release()
        t2.release()
        st = a.status()
        assert st["running"] == 0 and st["inuse_bytes"] == 0
        assert st["outcomes"]["admit"] == 2
        assert st["outcomes"]["queue"] == 0

    def test_oversized_query_runs_alone(self):
        a = _ctl(budget_bytes=10)
        t = a.admit("huge")  # nothing running: admitted despite size
        assert a.status()["running"] == 1
        t.release()

    def test_queue_then_admit_on_release(self):
        a = _ctl()
        t1, t2 = a.admit("q1"), a.admit("q2")
        admitted = []

        def late():
            t3 = a.admit("q3")
            admitted.append(time.monotonic())
            t3.release()

        th = threading.Thread(target=late, daemon=True)
        th.start()
        time.sleep(0.1)
        assert a.status()["queued"] == 1
        t_rel = time.monotonic()
        t1.release()
        t2.release()
        th.join(timeout=5)
        assert admitted and admitted[0] >= t_rel
        assert a.status()["outcomes"]["queue"] == 1

    def test_full_queue_rejects_with_errno(self):
        a = _ctl(budget_bytes=10, max_queue=0)
        hold = a.admit("hold")
        with pytest.raises(AdmissionRejected) as ei:
            a.admit("next")
        assert ei.value.admission_outcome == "reject"
        assert ei.value.mysql_errno == 8252
        assert a.status()["outcomes"]["reject"] == 1
        hold.release()

    def test_queue_wait_timeout(self):
        a = _ctl(budget_bytes=10, queue_timeout_s=0.2)
        hold = a.admit("hold")
        t0 = time.monotonic()
        with pytest.raises(AdmissionRejected) as ei:
            a.admit("next")
        assert ei.value.admission_outcome == "timeout"
        assert ei.value.mysql_errno == 8253
        assert time.monotonic() - t0 >= 0.2
        # the slot is intact: releasing the holder admits a new query
        hold.release()
        a.admit("after").release()

    def test_kill_check_reaches_queued_statement(self):
        class Killed(RuntimeError):
            pass

        def kc():
            raise Killed()

        a = _ctl(budget_bytes=10)
        hold = a.admit("hold")
        with pytest.raises(Killed):
            a.admit("next", kill_check=kc)
        st = a.status()
        assert st["queued"] == 0  # waiter cleaned up
        # the killed statement's wait still counted as "queue" but
        # got NO terminal admit/reject/timeout outcome — the kill is
        # the statement's verdict, not an admission decision
        assert st["outcomes"]["queue"] == 1
        assert st["outcomes"]["reject"] == 0
        assert st["outcomes"]["timeout"] == 0
        assert st["outcomes"]["admit"] == 1  # the holder only
        hold.release()

    def test_priority_order_and_aging(self):
        """A queued HIGH query admits before an earlier-queued LOW one;
        once the LOW one has starved past starvation_s it admits even
        though fresher HIGH arrivals keep coming (aging promotes it and
        the starving head blocks leapfrogging)."""
        a = _ctl(budget_bytes=40, starvation_s=0.4, queue_timeout_s=30.0)
        hold = a.admit("hold")  # occupies the whole budget
        order = []

        def waiter(name, prio):
            t = a.admit(name, priority=prio)
            order.append(name)
            time.sleep(0.03)
            t.release()

        low = threading.Thread(
            target=waiter, args=("low", "low"), daemon=True
        )
        low.start()
        time.sleep(0.1)  # low is queued first
        high = threading.Thread(
            target=waiter, args=("high", "high"), daemon=True
        )
        high.start()
        time.sleep(0.1)
        hold.release()  # budget frees: high should beat low
        high.join(timeout=5)
        low.join(timeout=5)
        assert order == ["high", "low"], order

    def test_estimates_learn_from_release(self):
        a = _ctl(default_estimate_bytes=7)
        assert a.estimate("q") == 7
        t = a.admit("q")
        t.release(observed_bytes=123)
        assert a.estimate("q") == 123
        # and the next admission of the same shape gates on 123
        t2 = a.admit("q")
        assert a.status()["inuse_bytes"] == 123
        t2.release()

    def test_release_idempotent(self):
        a = _ctl()
        t = a.admit("q")
        t.release()
        t.release()
        assert a.status()["running"] == 0

    def test_undeclared_outcome_rejected(self):
        a = _ctl()
        with pytest.raises(ValueError, match="undeclared admission"):
            a._note_outcome("oops")
        assert set(OUTCOMES) == {"admit", "queue", "reject", "timeout"}

    def test_queue_wait_phase_charged_to_flight(self):
        from tidb_tpu.obs.flight import FLIGHT

        FLIGHT.begin("select 1", conn_id=1)
        a = _ctl()
        a.admit("q").release()
        rec = FLIGHT.current()
        assert rec is not None and "queue-wait" in rec.phases
        FLIGHT.discard()


class TestQidAllocator:
    def test_strictly_unique_under_thread_stress(self):
        """16 threads x 500 allocations: every id unique, none skipped
        (the satellite's racecheck-stressed allocator contract — qid
        collisions would let two queries' shuffle frames admit into
        one stage)."""
        racecheck.enable()
        racecheck.reset()
        try:
            alloc = QidAllocator(start=1)
            got = [[] for _ in range(16)]

            def grab(bucket):
                for _ in range(500):
                    bucket.append(alloc.next())

            threads = [
                threading.Thread(target=grab, args=(b,), daemon=True)
                for b in got
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            allids = [q for b in got for q in b]
            assert len(allids) == 16 * 500
            assert len(set(allids)) == len(allids), "duplicate qid"
            assert sorted(allids) == list(range(1, 16 * 500 + 1))
            # each thread's view is strictly increasing (monotone)
            for b in got:
                assert b == sorted(b)
        finally:
            racecheck.disable()
            racecheck.reset()

    def test_dcn_allocators_are_locked(self):
        from tidb_tpu.parallel import dcn

        assert isinstance(dcn._QUERY_ID, QidAllocator)
        assert isinstance(dcn._STAGED_NONCE, QidAllocator)


class TestSharedPlanCache:
    def test_cross_session_reuse_no_recompile(self):
        """Two sessions over one catalog: the second session's first
        run of a shape the first already compiled must hit the shared
        cache (cross-session counter moves) and add ZERO jit
        compilations."""
        from tidb_tpu.session import Session
        from tidb_tpu.storage import Catalog
        from tidb_tpu.utils.metrics import REGISTRY

        def tot(p):
            return sum(
                v for n, _k, v in REGISTRY.rows() if n.startswith(p)
            )

        cat = Catalog()
        s1 = Session(cat)
        s1.execute("create table spc (a int, b int)")
        s1.execute("insert into spc values (1,2),(3,4),(5,6),(1,8)")
        q = "select a, sum(b), count(*) from spc group by a order by a"
        exp = s1.must_query(q).rows
        x0 = tot(
            "tidbtpu_executor_shared_plan_cache_cross_session_hits_total"
        )
        j0 = tot("tidbtpu_engine_jit_compilations")
        s2 = Session(cat)
        assert s2.must_query(q).rows == exp
        assert tot(
            "tidbtpu_executor_shared_plan_cache_cross_session_hits_total"
        ) > x0
        assert tot("tidbtpu_engine_jit_compilations") == j0, (
            "second session recompiled a shared plan"
        )

    def test_weak_entries_die_with_their_executors(self):
        """The shared cache must not pin dead catalogs: once every
        executor holding a compiled plan is gone, the entry is gone."""
        import gc

        from tidb_tpu.planner.physical import SHARED_PLAN_CACHE
        from tidb_tpu.session import Session
        from tidb_tpu.storage import Catalog

        cat = Catalog()
        s = Session(cat)
        s.execute("create table wk (a int)")
        s.execute("insert into wk values (1),(2)")
        s.execute("select a, count(*) from wk group by a")
        keys_with = len(SHARED_PLAN_CACHE._map)
        assert keys_with >= 1
        del s, cat
        gc.collect()
        # entries for the dead catalog's tables are gone (other tests'
        # live sessions may keep their own entries; count must drop)
        assert len(SHARED_PLAN_CACHE._map) < keys_with

    def test_distinct_catalogs_do_not_collide(self):
        """Same DDL + same SQL over two catalogs must not share
        compiled programs (table uids key the cache): dictionaries
        baked for one catalog's data would corrupt the other's."""
        from tidb_tpu.session import Session
        from tidb_tpu.storage import Catalog

        out = []
        for vals in ("('x'),('y'),('x')", "('p'),('q'),('q')"):
            cat = Catalog()
            s = Session(cat)
            s.execute("create table dd (v varchar(4))")
            s.execute(f"insert into dd values {vals}")
            out.append(
                s.must_query(
                    "select v, count(*) from dd group by v order by v"
                ).rows
            )
        assert out[0] == [("x", 2), ("y", 1)]
        assert out[1] == [("p", 1), ("q", 2)]


class TestRejectionSurfaces:
    def test_rejected_statement_errno_and_summary_row(self):
        """Satellite: an admission verdict must surface as a proper
        MySQL error (8252 queue-full / 8253 timeout) — never as a
        local-execution fallback — with the statements_summary row
        still recorded, its phase breakdown showing the queue-wait
        that led to the verdict."""
        from tidb_tpu.session import Session
        from tidb_tpu.utils.metrics import STMT_SUMMARY, sql_digest

        class StubSched:
            """Only what the session touches BEFORE the admission
            gate: the cut choice and the controller itself. A rejected
            statement must never reach execute_plan."""

            def __init__(self, admission):
                self.admission = admission

            def _choose_cut(self, plan, digest=None):
                return ("frag", None)

            def execute_plan(self, plan, cut_hint=None):
                raise AssertionError(
                    "rejected statement reached the fleet"
                )

        a = AdmissionController(
            budget_bytes=10, default_estimate_bytes=64,
            max_queue=0, queue_timeout_s=0.2,
        )
        hold = a.admit("hold")  # saturate; max_queue=0 -> reject
        s = Session()
        s.execute("create table rejt (a int, b int)")
        s.execute("insert into rejt values (1,2),(3,4),(1,6)")
        s.dcn_scheduler = StubSched(a)
        sql = "select a, count(*), sum(b) from rejt group by a order by a"
        with pytest.raises(AdmissionRejected) as ei:
            s.execute(sql)
        assert ei.value.mysql_errno == 8252
        assert ei.value.admission_outcome == "reject"
        # the summary row landed anyway, queue-wait phase attached
        row = next(
            r for r in STMT_SUMMARY.rows_full()
            if r["digest_text"] == sql_digest(sql)
        )
        assert row["exec_count"] >= 1
        assert "queue-wait" in row["phases"]
        hold.release()
        # fleet healthy again: the same statement round-trips (local
        # parity reference — StubSched would fail a real dispatch, so
        # detach first)
        s.dcn_scheduler = None
        assert s.must_query(sql).rows == [(1, 2, 8), (3, 1, 4)]


class TestPriorityMapping:
    def test_select_modifiers_parse(self):
        from tidb_tpu.parser.sqlparse import parse

        assert parse("select high_priority a from t")[0].priority == "high"
        assert parse("select low_priority a from t")[0].priority == "low"
        assert (
            parse("select distinct high_priority a from t")[0].priority
            == "high"
        )
        assert parse("select high_priority * from t")[0].priority == "high"
        assert parse("select a from t")[0].priority is None

    def test_column_named_high_priority_still_works(self):
        """This dialect does NOT reserve high_priority/low_priority
        (the DDL side accepts them as column names), so the modifier
        must only consume the identifier when what follows can begin a
        select item — a column reference keeps working."""
        from tidb_tpu.parser.sqlparse import parse
        from tidb_tpu.session import Session

        for sql in (
            "select high_priority from t",
            "select high_priority, 1 from t",
            "select low_priority + 1 from t",
            "select high_priority * 2 from t",
        ):
            assert parse(sql)[0].priority is None, sql
        s = Session()
        s.execute("create table prio_col (high_priority int)")
        s.execute("insert into prio_col values (7),(3)")
        assert s.must_query(
            "select high_priority from prio_col order by high_priority"
        ).rows == [(3,), (7,)]
        assert s.must_query(
            "select high_priority * 2 from prio_col order by 1"
        ).rows == [(6,), (14,)]

    def test_force_priority_sysvar_maps_in(self):
        from tidb_tpu.parser.sqlparse import parse
        from tidb_tpu.session import Session

        s = Session()
        sel = parse("select 1")[0]
        assert s._priority_for(sel) == "medium"
        s.execute("set tidb_force_priority = 'LOW_PRIORITY'")
        assert s._priority_for(sel) == "low"
        s.execute("set tidb_force_priority = 'HIGH_PRIORITY'")
        assert s._priority_for(sel) == "high"
        # the statement's own modifier beats the sysvar
        assert (
            s._priority_for(parse("select low_priority 1")[0]) == "low"
        )

    def test_statement_executes_with_modifier(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table pm (a int)")
        s.execute("insert into pm values (1),(2)")
        assert s.must_query(
            "select high_priority a from pm order by a"
        ).rows == [(1,), (2,)]
