"""TRUNCATE, DESCRIBE / SHOW COLUMNS, INSERT IGNORE, and
INSERT ... ON DUPLICATE KEY UPDATE.

Reference: TRUNCATE in the DDL layer (pkg/ddl), IGNORE + ON DUPLICATE
KEY in the insert executor (pkg/executor/insert.go onDuplicateUpdate).
"""

import pytest

from tidb_tpu.session import Session


@pytest.fixture()
def sess():
    return Session()


class TestTruncate:
    def test_truncate_resets_autoinc(self, sess):
        sess.execute(
            "create table t (id int primary key auto_increment, v int)"
        )
        sess.execute("insert into t (v) values (1), (2)")
        sess.execute("truncate table t")
        assert sess.execute("select count(*) from t").rows == [(0,)]
        sess.execute("insert into t (v) values (9)")
        assert sess.execute("select id from t").rows == [(1,)]

    def test_truncate_without_table_kw(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        sess.execute("truncate t")
        assert sess.execute("select count(*) from t").rows == [(0,)]

    def test_truncate_fk_parent_blocked(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute("insert into p values (1)")
        sess.execute("create table c (x int references p (id))")
        sess.execute("insert into c values (1)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            sess.execute("truncate table p")
        sess.execute("truncate table c")
        sess.execute("truncate table p")

    def test_truncate_requires_drop_priv(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("create user u identified by ''")
        sess.execute("grant select on test.t to u")
        s2 = Session(sess.catalog, user="u")
        with pytest.raises(PermissionError):
            s2.execute("truncate table t")


class TestDescribe:
    def test_describe(self, sess):
        sess.execute(
            "create table t (id int primary key, v int default 5, "
            "s varchar(10), unique index us (s), index iv (v))"
        )
        rows = sess.execute("describe t").rows
        assert [r[0] for r in rows] == ["id", "v", "s"]
        by = {r[0]: r for r in rows}
        assert by["id"][3] == "PRI"
        assert by["s"][3] == "UNI"
        assert by["v"][3] == "MUL"
        assert by["v"][4] == "5"
        assert sess.execute("desc t").rows == rows
        assert sess.execute("show columns from t").rows == rows


class TestPrimaryKeyUniqueness:
    def test_duplicate_pk_rejected(self, sess):
        sess.execute("create table t (id int primary key, v int)")
        sess.execute("insert into t values (1, 10)")
        with pytest.raises(ValueError, match="primary key"):
            sess.execute("insert into t values (1, 20)")
        with pytest.raises(ValueError, match="primary key"):
            sess.execute("insert into t values (2, 1), (2, 2)")
        assert sess.execute("select count(*) from t").rows == [(1,)]

    def test_string_pk(self, sess):
        sess.execute("create table t (k varchar(10) primary key, v int)")
        sess.execute("insert into t values ('a', 1)")
        with pytest.raises(ValueError, match="primary key"):
            sess.execute("insert into t values ('a', 2)")
        sess.execute("insert into t values ('b', 2)")

    def test_replace_and_upsert_still_allowed(self, sess):
        sess.execute("create table t (id int primary key, v int)")
        sess.execute("insert into t values (1, 10)")
        sess.execute("replace into t values (1, 20)")
        sess.execute(
            "insert into t values (1, 0) on duplicate key update v = 30"
        )
        assert sess.execute("select v from t").rows == [(30,)]

    def test_insert_select_checked(self, sess):
        sess.execute("create table t (id int primary key, v int)")
        sess.execute("create table src (id int, v int)")
        sess.execute("insert into src values (5, 1), (5, 2)")
        with pytest.raises(ValueError, match="primary key"):
            sess.execute("insert into t select id, v from src")

    def test_encoded_domain_batch_dups(self, sess):
        # distinct Python floats that round to the same stored decimal
        # must collide (the check runs in the encoded domain)
        sess.execute("create table d (id decimal(10,2) primary key, v int)")
        with pytest.raises(ValueError, match="primary key"):
            sess.execute("insert into d values (1.001, 1), (1.002, 2)")

    def test_update_creating_pk_dup_rolls_back(self, sess):
        sess.execute("create table t (id int primary key, v int)")
        sess.execute("insert into t values (1, 10), (2, 20)")
        with pytest.raises(ValueError, match="primary key"):
            sess.execute("update t set id = 9")
        # with WHERE (the columnar fast path's home turf) too
        with pytest.raises(ValueError, match="primary key"):
            sess.execute("update t set id = 9 where v > 0")
        assert sess.execute("select id, v from t order by id").rows == [
            (1, 10), (2, 20)
        ]
        sess.execute("update t set id = 9 where v = 10")  # unique new key
        assert sess.execute("select id from t order by id").rows == [
            (2,), (9,)
        ]


class TestInsertIgnore:
    def test_ignore_duplicates(self, sess):
        sess.execute("create table t (id int primary key, v int)")
        sess.execute("insert into t values (1, 10)")
        r = sess.execute("insert ignore into t values (1, 99), (2, 20)")
        assert r.affected == 1
        assert sess.execute("select id, v from t order by id").rows == [
            (1, 10), (2, 20)
        ]

    def test_ignore_batch_internal_dup(self, sess):
        sess.execute("create table t (id int primary key, v int)")
        sess.execute("insert ignore into t values (1, 10), (1, 20)")
        assert sess.execute("select v from t").rows == [(10,)]

    def test_ignore_check_and_fk(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute("insert into p values (1)")
        sess.execute(
            "create table t (a int check (a > 0), pid int references p (id))"
        )
        r = sess.execute(
            "insert ignore into t values (1, 1), (-5, 1), (2, 99)"
        )
        assert r.affected == 1
        assert sess.execute("select a, pid from t").rows == [(1, 1)]


class TestIgnoreOnDupInterplay:
    def test_ignore_with_on_dup_updates(self, sess):
        # IGNORE must not swallow the update path: dup keys go to
        # ON DUPLICATE KEY UPDATE, not to the ignore filter
        sess.execute("create table t (a int primary key, b varchar(10))")
        sess.execute("insert into t values (1, 'old')")
        sess.execute(
            "insert ignore into t values (1, 'new') "
            "on duplicate key update b = values(b)"
        )
        assert sess.execute("select a, b from t").rows == [(1, "new")]

    def test_ignore_self_fk_in_batch(self, sess):
        sess.execute(
            "create table emp (id int primary key, mgr int, "
            "foreign key (mgr) references emp (id))"
        )
        r = sess.execute(
            "insert ignore into emp values (3, null), (4, 3), (5, 99)"
        )
        assert r.affected == 2
        assert sess.execute("select id from emp order by id").rows == [
            (3,), (4,)
        ]

    def test_truncate_autoinc_reset_survives_txn(self, sess):
        sess.execute(
            "create table t (id int primary key auto_increment, v int)"
        )
        sess.execute("insert into t (v) values (1), (2), (3)")
        sess.execute("begin")
        sess.execute("truncate table t")
        sess.execute("commit")
        sess.execute("insert into t (v) values (9)")
        assert sess.execute("select id from t").rows == [(1,)]


class TestOnDuplicateKeyUpdate:
    def test_basic_upsert(self, sess):
        sess.execute("create table t (id int primary key, cnt int)")
        sess.execute("insert into t values (1, 5)")
        r = sess.execute(
            "insert into t values (1, 0), (2, 7) "
            "on duplicate key update cnt = cnt + 1"
        )
        assert r.affected == 3  # 1 insert + 2 for the update
        assert sess.execute("select id, cnt from t order by id").rows == [
            (1, 6), (2, 7)
        ]

    def test_values_function(self, sess):
        sess.execute("create table t (id int primary key, v int)")
        sess.execute("insert into t values (1, 10)")
        sess.execute(
            "insert into t values (1, 42) "
            "on duplicate key update v = values(v)"
        )
        assert sess.execute("select v from t").rows == [(42,)]

    def test_unique_index_conflict(self, sess):
        sess.execute(
            "create table t (id int primary key, email varchar(20), "
            "hits int, unique index ue (email))"
        )
        sess.execute("insert into t values (1, 'a@x', 0)")
        sess.execute(
            "insert into t values (2, 'a@x', 0) "
            "on duplicate key update hits = hits + 1"
        )
        rows = sess.execute("select id, email, hits from t").rows
        assert rows == [(1, "a@x", 1)]  # id stays, hits bumped

    def test_batch_internal_chain(self, sess):
        sess.execute("create table t (id int primary key, n int)")
        r = sess.execute(
            "insert into t values (1, 1), (1, 1), (1, 1) "
            "on duplicate key update n = n + 1"
        )
        assert sess.execute("select n from t").rows == [(3,)]
        assert r.affected == 5  # 1 insert + 2 updates x 2

    def test_string_func_in_on_dup(self, sess):
        # concat and friends run through the shared host evaluator
        # (checkeval._SCALAR, added with generated columns)
        sess.execute("create table t (id int primary key, b varchar(10))")
        sess.execute("insert into t values (1, 'x')")
        sess.execute(
            "insert into t values (1, 'y') "
            "on duplicate key update b = concat(b, '!')"
        )
        assert sess.execute("select b from t").rows == [("x!",)]

    def test_unsupported_expr_clear_error(self, sess):
        sess.execute("create table t (id int primary key, b varchar(10))")
        sess.execute("insert into t values (1, 'x')")
        with pytest.raises(ValueError, match="ON DUPLICATE KEY UPDATE"):
            sess.execute(
                "insert into t values (1, 'y') "
                "on duplicate key update b = md5(b)"
            )

    def test_upsert_respects_check(self, sess):
        sess.execute(
            "create table t (id int primary key, v int, check (v < 100))"
        )
        sess.execute("insert into t values (1, 99)")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute(
                "insert into t values (1, 0) "
                "on duplicate key update v = v + 10"
            )
        assert sess.execute("select v from t").rows == [(99,)]


class TestColumnarStringUpdate:
    """UPDATE SET <string col> = '<existing value>' stays columnar
    (dictionary-code scatter, no whole-table rewrite); an unseen value
    falls back to the rewrite path (dictionary remap). Reference: the
    per-key delta write path, pkg/executor/update.go."""

    def test_existing_value_scatter(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table t (id int, status varchar(10))")
        s.execute(
            "insert into t values (1, 'open'), (2, 'open'), (3, 'done')"
        )
        t = s.catalog.table("test", "t")
        blocks_before = [b.uid for b in t.blocks()]
        r = s.execute("update t set status = 'done' where id = 1")
        assert r.affected == 1
        assert s.execute(
            "select id, status from t order by id"
        ).rows == [(1, "done"), (2, "open"), (3, "done")]
        # columnar path: the untouched-block structure survives (the
        # rewrite path would collapse everything into one fresh block)
        assert len(t.blocks()) == len(blocks_before)

    def test_unseen_value_falls_back(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table t (id int, status varchar(10))")
        s.execute("insert into t values (1, 'open'), (2, 'open')")
        r = s.execute("update t set status = 'closed' where id = 2")
        assert r.affected == 1
        assert s.execute(
            "select id, status from t order by id"
        ).rows == [(1, "open"), (2, "closed")]

    def test_mixed_string_and_numeric_set(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table t (id int, n int, status varchar(10))")
        s.execute("insert into t values (1, 10, 'open'), (2, 20, 'done')")
        s.execute("update t set status = 'done', n = n + 5 where id = 1")
        assert s.execute(
            "select id, n, status from t order by id"
        ).rows == [(1, 15, "done"), (2, 20, "done")]


class TestDMLOrderLimit:
    """Single-table DELETE/UPDATE ... [ORDER BY] LIMIT (MySQL batch-DML
    form; reference: buildDelete/buildUpdate accept order-by + limit) —
    the batch-purge loop shape (DELETE ... LIMIT 1000 until 0 rows)."""

    @pytest.fixture()
    def s(self):
        sess = Session()
        sess.execute("create database bl")
        sess.execute("use bl")
        sess.execute("create table t (a int primary key, v int)")
        sess.execute(
            "insert into t values " + ", ".join(
                f"({i}, {i % 7})" for i in range(1, 101)
            )
        )
        return sess

    def test_batch_purge_loop(self, s):
        total = 0
        while True:
            n = s.execute("delete from t where v = 3 limit 4").affected
            total += n
            if n == 0:
                break
        assert total == 14
        assert s.execute(
            "select count(*) from t where v = 3"
        ).rows == [(0,)]

    def test_delete_order_by_limit(self, s):
        s.execute("delete from t order by a desc limit 3")
        assert s.execute("select max(a) from t").rows == [(97,)]
        s.execute("delete from t order by v desc, a asc limit 2")
        # v=6 rows: a in (6,13,...); two smallest a with v=6 removed
        assert s.execute(
            "select count(*) from t where v = 6"
        ).rows == [(12,)]

    def test_update_order_by_limit(self, s):
        s.execute("update t set v = -1 order by a desc limit 2")
        assert s.execute(
            "select a from t where v = -1 order by a"
        ).rows == [(99,), (100,)]
        with pytest.raises(Exception, match="ORDER BY supports plain"):
            s.execute("delete from t order by a + 1 limit 1")

    def test_txn_and_fk_paths_still_apply(self, s):
        s.execute(
            "create table c (id int, r int, "
            "foreign key (r) references t (a) on delete cascade)"
        )
        s.execute("insert into c values (1, 100), (2, 50)")
        s.execute("delete from t order by a desc limit 1")  # a=100
        assert s.execute("select id from c").rows == [(2,)]
        s.execute("begin")
        s.execute("delete from t order by a desc limit 5")
        s.execute("rollback")
        assert s.execute("select count(*) from t").rows == [(99,)]

    def test_desc_nulls_last_and_no_pk_unbound_limit(self, s):
        s.execute("create table n (a int primary key, v int)")
        s.execute("insert into n values (1, 5), (2, NULL), (3, 9)")
        # MySQL: NULLs sort LAST descending — v=9 goes first
        s.execute("delete from n order by v desc limit 1")
        assert s.execute("select a from n order by a").rows == [
            (1,), (2,)
        ]
        # and FIRST ascending
        s.execute("delete from n order by v asc limit 1")
        assert s.execute("select a from n order by a").rows == [(1,)]
        # a LIMIT that doesn't bind works without any PRIMARY KEY
        s.execute("create table nk (x int, y int)")
        s.execute("insert into nk values (1, 1), (2, 2)")
        assert s.execute("update nk set y = 0 limit 10").affected == 2
        assert s.execute("select sum(y) from nk").rows == [(0,)]
