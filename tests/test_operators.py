"""Operator tests (reference model: pkg/executor/aggregate_test.go,
sortexec tests, join tests — run against numpy-computed golden values)."""

import numpy as np

from tidb_tpu import DECIMAL, FLOAT64, INT64, STRING
from tidb_tpu.chunk import Batch, HostBlock, block_to_batch, column_from_values
from tidb_tpu.executor import (
    AggDesc,
    equi_join,
    filter_batch,
    group_aggregate,
    limit_op,
    order_by,
    top_n,
)


def make_batch(cols, types):
    block = HostBlock.from_columns(
        {k: column_from_values(v, types[k]) for k, v in cols.items()}
    )
    return block_to_batch(block), block.nrows


def colfn(name):
    return lambda b: b.cols[name]


def compact(batch, names):
    rv = np.asarray(batch.row_valid)
    idx = np.nonzero(rv)[0]
    out = []
    for n in names:
        c = batch.cols[n]
        d, v = np.asarray(c.data)[idx], np.asarray(c.valid)[idx]
        out.append([d[i] if v[i] else None for i in range(len(idx))])
    return list(zip(*out)) if names else []


class TestGroupAggregate:
    def test_basic_sum_count_avg(self):
        batch, n = make_batch(
            {"g": [1, 2, 1, 2, 1, None], "v": [10, 20, 30, None, 50, 70]},
            {"g": INT64, "v": INT64},
        )
        out, ngroups = group_aggregate(
            batch,
            [colfn("g")],
            [
                AggDesc("sum", colfn("v"), "s"),
                AggDesc("count", colfn("v"), "c"),
                AggDesc("count", None, "star"),
                AggDesc("avg", colfn("v"), "a"),
                AggDesc("min", colfn("v"), "mn"),
                AggDesc("max", colfn("v"), "mx"),
            ],
            group_capacity=16,
        )
        assert int(ngroups) == 3
        rows = {r[0]: r[1:] for r in compact(out, ["k0", "s", "c", "star", "a", "mn", "mx"])}
        assert rows[1] == (90, 3, 3, 30.0, 10, 50)
        assert rows[2] == (20, 1, 2, 20.0, 20, 20)
        assert rows[None] == (70, 1, 1, 70.0, 70, 70)

    def test_sum_empty_group_is_null(self):
        batch, _ = make_batch(
            {"g": [1], "v": [None]}, {"g": INT64, "v": INT64}
        )
        out, ng = group_aggregate(
            batch, [colfn("g")], [AggDesc("sum", colfn("v"), "s")], 8
        )
        rows = compact(out, ["k0", "s"])
        assert rows == [(1, None)]

    def test_multi_key(self):
        batch, _ = make_batch(
            {"a": [1, 1, 2, 1], "b": [1, 2, 1, 1], "v": [5, 6, 7, 8]},
            {"a": INT64, "b": INT64, "v": INT64},
        )
        out, ng = group_aggregate(
            batch,
            [colfn("a"), colfn("b")],
            [AggDesc("sum", colfn("v"), "s")],
            8,
            key_names=["a", "b"],
        )
        assert int(ng) == 3
        rows = {(r[0], r[1]): r[2] for r in compact(out, ["a", "b", "s"])}
        assert rows == {(1, 1): 13, (1, 2): 6, (2, 1): 7}

    def test_no_groups(self):
        # scalar aggregation: no keys -> one group
        batch, _ = make_batch({"v": [1, 2, 3]}, {"v": INT64})
        out, ng = group_aggregate(batch, [], [AggDesc("sum", colfn("v"), "s")], 4)
        assert int(ng) == 1
        assert compact(out, ["s"]) == [(6,)]


class TestSort:
    def test_order_desc_with_nulls(self):
        batch, _ = make_batch({"a": [3, None, 1, 2]}, {"a": INT64})
        out = order_by(batch, [colfn("a")], [True])
        assert [r[0] for r in compact(out, ["a"])] == [3, 2, 1, None]
        out = order_by(batch, [colfn("a")], [False])
        # MySQL ASC: NULLs first
        assert [r[0] for r in compact(out, ["a"])] == [None, 1, 2, 3]

    def test_top_n_and_limit_offset(self):
        batch, _ = make_batch({"a": [5, 1, 4, 2, 3]}, {"a": INT64})
        out = top_n(batch, [colfn("a")], [False], 2)
        assert [r[0] for r in compact(out, ["a"])] == [1, 2]
        out = top_n(batch, [colfn("a")], [False], 2, offset=1)
        assert [r[0] for r in compact(out, ["a"])] == [2, 3]
        out = limit_op(batch, 3)
        assert [r[0] for r in compact(out, ["a"])] == [5, 1, 4]

    def test_multi_key_directions(self):
        batch, _ = make_batch(
            {"a": [1, 2, 1, 2], "b": [9, 8, 7, 6]}, {"a": INT64, "b": INT64}
        )
        out = order_by(batch, [colfn("a"), colfn("b")], [False, True])
        assert compact(out, ["a", "b"]) == [(1, 9), (1, 7), (2, 8), (2, 6)]


class TestJoin:
    def test_inner_one_to_many(self):
        build, _ = make_batch(
            {"k": [1, 2, 2], "name": [10, 20, 21]}, {"k": INT64, "name": INT64}
        )
        probe, _ = make_batch(
            {"k": [2, 1, 3, None], "v": [100, 200, 300, 400]},
            {"k": INT64, "v": INT64},
        )
        out, total = equi_join(
            build, probe, colfn("k"), colfn("k"),
            out_capacity=16, join_type="inner",
            build_prefix="b_", probe_prefix="p_",
        )
        assert int(total) == 3
        rows = sorted(compact(out, ["p_v", "b_name"]))
        assert rows == [(100, 20), (100, 21), (200, 10)]

    def test_left_outer(self):
        build, _ = make_batch({"k": [1], "name": [10]}, {"k": INT64, "name": INT64})
        probe, _ = make_batch(
            {"k": [1, 3], "v": [100, 300]}, {"k": INT64, "v": INT64}
        )
        out, total = equi_join(
            build, probe, colfn("k"), colfn("k"),
            out_capacity=8, join_type="left",
            build_prefix="b_", probe_prefix="p_",
        )
        assert int(total) == 2
        rows = sorted(compact(out, ["p_v", "b_name"]), key=lambda r: r[0])
        assert rows == [(100, 10), (300, None)]

    def test_semi_anti(self):
        build, _ = make_batch({"k": [1, 1, 2]}, {"k": INT64})
        probe, _ = make_batch({"k": [1, 2, 3, None]}, {"k": INT64})
        out, total = equi_join(build, probe, colfn("k"), colfn("k"), 8, "semi")
        assert int(total) == 2
        assert sorted(r[0] for r in compact(out, ["k"])) == [1, 2]
        out, total = equi_join(build, probe, colfn("k"), colfn("k"), 8, "anti")
        # anti keeps non-matching rows; NULL-key row kept (NOT EXISTS style)
        vals = [r[0] for r in compact(out, ["k"])]
        assert 3 in vals and None in vals and 1 not in vals

    def test_overflow_detection(self):
        build, _ = make_batch({"k": [1, 1, 1, 1]}, {"k": INT64})
        probe, _ = make_batch({"k": [1, 1]}, {"k": INT64})
        out, total = equi_join(build, probe, colfn("k"), colfn("k"), 4, "inner")
        assert int(total) == 8  # true size reported; caller retries bigger


class TestFilter:
    def test_filter_masks(self):
        batch, _ = make_batch({"a": [1, 2, None, 4]}, {"a": INT64})

        def pred(b):
            from tidb_tpu.chunk import DevCol
            c = b.cols["a"]
            return DevCol(c.data > 1, c.valid)

        out = filter_batch(batch, pred)
        assert [r[0] for r in compact(out, ["a"])] == [2, 4]
