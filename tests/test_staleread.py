"""Stale reads (AS OF TIMESTAMP, tidb_read_staleness) and the
READ-COMMITTED isolation provider.

Reference: TiDB staleness clause + sessiontxn staleness providers
(pkg/sessiontxn/staleread), tidb_gc_life_time retention, and the RC
isolation provider (pkg/sessiontxn/isolation/readcommitted.go). The
columnar analog resolves a timestamp to the newest table version
published at-or-before it; versions inside the GC life window survive
collection (storage/table.py version_ts / GC_LIFE_S).
"""

import time

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.storage import table as table_mod


@pytest.fixture()
def sess():
    s = Session()
    s.execute("set global tidb_gc_life_time = 600")
    yield s
    s.execute("set global tidb_gc_life_time = 0")
    table_mod.set_gc_life(0)


class TestAsOfTimestamp:
    def test_as_of_sees_history(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(0.02)
        ts_mid = time.time()
        time.sleep(0.02)
        sess.execute("insert into t values (2)")
        assert sess.execute("select count(*) from t").rows == [(1 + 1,)]
        r = sess.execute(f"select count(*) from t as of timestamp {ts_mid}")
        assert r.rows == [(1,)]
        # joins: each ref resolves independently of current data
        r2 = sess.execute(
            f"select a from t as of timestamp {ts_mid} order by a"
        )
        assert r2.rows == [(1,)]

    def test_as_of_before_creation_errors(self, sess):
        sess.execute("create table t (a int)")
        with pytest.raises(ValueError, match="GC safepoint|before table"):
            sess.execute("select * from t as of timestamp 1.0")

    def test_as_of_inside_txn_rejected(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        ts = time.time()
        sess.execute("begin")
        try:
            with pytest.raises(ValueError, match="not allowed"):
                sess.execute(f"select * from t as of timestamp {ts}")
        finally:
            sess.execute("rollback")


class TestReadStaleness:
    def test_staleness_resolves_old_version(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(1.1)
        sess.execute("insert into t values (2)")
        sess.execute("set tidb_read_staleness = -1")
        try:
            # now-1s predates the second insert
            assert sess.execute("select count(*) from t").rows == [(1,)]
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert sess.execute("select count(*) from t").rows == [(2,)]

    def test_staleness_not_applied_to_dml_reads(self, sess):
        sess.execute("create table src (a int)")
        sess.execute("create table dst (a int)")
        sess.execute("insert into src values (1), (2)")
        sess.execute("set tidb_read_staleness = -1")
        try:
            # the SELECT half of INSERT..SELECT reads FRESH data even
            # though a plain SELECT would be stale
            sess.execute("insert into dst select a from src")
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert sess.execute("select count(*) from dst").rows == [(2,)]


class TestReadCommitted:
    def test_rc_sees_concurrent_commits(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s1.execute("create table t (a int)")
        s1.execute("insert into t values (1)")
        s1.execute("set transaction_isolation = 'READ-COMMITTED'")
        s1.execute("begin")
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s2.execute("insert into t values (2)")
        # RC: the next statement sees s2's commit mid-transaction
        assert s1.execute("select count(*) from t").rows == [(2,)]
        s1.execute("rollback")

    def test_rr_keeps_snapshot(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s1.execute("create table t (a int)")
        s1.execute("insert into t values (1)")
        s1.execute("begin")
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s2.execute("insert into t values (2)")
        # REPEATABLE-READ (default): snapshot pinned at first read
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s1.execute("rollback")


class TestStalenessEdges:
    def test_infoschema_immune_to_staleness(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("set tidb_read_staleness = -1")
        try:
            rows = sess.execute(
                "select table_name from information_schema.tables"
            ).rows
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert any(r[0] == "t" for r in rows)

    def test_tx_isolation_alias_mirrors(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s1.execute("create table t (a int)")
        s1.execute("insert into t values (1)")
        # the LEGACY alias must drive the RC provider too
        s1.execute("set tx_isolation = 'READ-COMMITTED'")
        s1.execute("begin")
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s2.execute("insert into t values (2)")
        assert s1.execute("select count(*) from t").rows == [(2,)]
        s1.execute("rollback")

    def test_staleness_clamps_young_table(self, sess):
        # a table created inside the staleness window reads its earliest
        # retained state instead of erroring (usable-timestamp rule)
        sess.execute("create table fresh (a int)")
        sess.execute("insert into fresh values (1)")
        sess.execute("set tidb_read_staleness = -3600")
        try:
            rows = sess.execute("select count(*) from fresh").rows
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert rows[0][0] in (0, 1)  # oldest retained state, no error


class TestPreparedStaleRead:
    """Advisor r3 (medium): EXECUTE is the top-level statement, so the
    depth-1 AS OF collection used to see only the EXECUTE node and
    prepared stale reads silently returned CURRENT data."""

    def test_prepared_as_of_sees_history(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(0.02)
        ts_mid = time.time()
        time.sleep(0.02)
        sess.execute("insert into t values (2)")
        sess.execute(
            f"prepare p from 'select count(*) from t as of timestamp {ts_mid}'"
        )
        # repeated EXECUTEs: the first plans, later ones may hit the
        # compiled fast path — both must resolve the historical version
        for _ in range(3):
            assert sess.execute("execute p").rows == [(1,)]
        sess.execute("insert into t values (3)")
        for _ in range(2):
            assert sess.execute("execute p").rows == [(1,)]
        assert sess.execute("select count(*) from t").rows == [(3,)]

    def test_prepared_read_staleness_applies(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        t = sess.catalog.table(sess.db, "t")
        old = t.version
        sess.execute("insert into t values (2)")
        # deterministic window: backdate every version at-or-before the
        # first insert so `now - 60` resolves exactly to it, regardless
        # of host timing (a timing-guarded assert would pass vacuously
        # on a slow host)
        for v in list(t.version_ts):
            if v <= old:
                t.version_ts[v] = time.time() - 120
        assert t.version_at(time.time() - 60, clamp_oldest=True) == old
        sess.execute("prepare p from 'select count(*) from t'")
        sess.execute("set tidb_read_staleness = -60")
        try:
            assert sess.execute("execute p").rows == [(1,)]
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert sess.execute("execute p").rows == [(2,)]

    def test_prepared_dml_as_of_rejected(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        ts = time.time()
        sess.execute(
            "prepare p from "
            f"'insert into t select a from t as of timestamp {ts}'"
        )
        with pytest.raises(ValueError, match="read-only"):
            sess.execute("execute p")

    def test_prepared_as_of_param_rebinds(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(0.02)
        ts1 = time.time()
        time.sleep(0.02)
        sess.execute("insert into t values (2)")
        time.sleep(0.02)
        ts2 = time.time()
        sess.execute("prepare p from 'select count(*) from t as of timestamp ?'")
        sess.user_vars["a"] = ts1
        sess.user_vars["b"] = ts2
        r1 = sess.execute("execute p using @a").rows
        r2 = sess.execute("execute p using @b").rows
        assert (r1, r2) == ([(1,)], [(2,)])

    def test_prepared_as_of_rebinds_after_use(self, sess):
        # a USE between EXECUTEs must replan: unqualified refs resolve
        # against the CURRENT db, and the (db, table)-keyed as-of map
        # must follow (code-review r4 finding)
        sess.execute("create database d2")
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(0.02)
        ts = time.time()
        time.sleep(0.02)
        sess.execute("insert into t values (2)")
        sess.execute("create table d2.t (a int)")
        sess.execute("insert into d2.t values (10), (20), (30)")
        sess.execute(
            f"prepare p from 'select count(*) from t as of timestamp {ts} "
            "where a > ?'"
        )
        sess.user_vars["z"] = 0
        assert sess.execute("execute p using @z").rows == [(1,)]
        db0 = sess.db
        sess.execute("use d2")
        try:
            with pytest.raises(ValueError):
                # d2.t was created after ts: resolving it at ts errors —
                # proof the re-bound db (not the stale d1 plan) is read
                sess.execute("execute p using @z")
        finally:
            sess.execute(f"use {db0}")
        assert sess.execute("execute p using @z").rows == [(1,)]


class TestSessionTimeZone:
    def test_naive_literal_uses_session_offset(self, sess):
        import datetime as dt

        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(0.02)
        ts_mid = time.time()
        time.sleep(0.02)
        sess.execute("insert into t values (2)")
        # express ts_mid as a naive literal in +02:00 — with the session
        # tz honored it resolves back to the same instant
        lit = dt.datetime.fromtimestamp(
            ts_mid, dt.timezone(dt.timedelta(hours=2))
        ).replace(tzinfo=None).isoformat()
        sess.execute("set time_zone = '+02:00'")
        try:
            r = sess.execute(
                f"select count(*) from t as of timestamp '{lit}'"
            )
        finally:
            sess.execute("set time_zone = 'UTC'")
        assert r.rows == [(1,)]

    def test_unknown_time_zone_raises(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        sess.execute("set time_zone = 'No/Such_Zone'")
        try:
            with pytest.raises(ValueError, match="time zone"):
                sess.execute(
                    "select * from t as of timestamp '2026-01-01 00:00:00'"
                )
        finally:
            sess.execute("set time_zone = 'UTC'")
