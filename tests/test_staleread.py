"""Stale reads (AS OF TIMESTAMP, tidb_read_staleness) and the
READ-COMMITTED isolation provider.

Reference: TiDB staleness clause + sessiontxn staleness providers
(pkg/sessiontxn/staleread), tidb_gc_life_time retention, and the RC
isolation provider (pkg/sessiontxn/isolation/readcommitted.go). The
columnar analog resolves a timestamp to the newest table version
published at-or-before it; versions inside the GC life window survive
collection (storage/table.py version_ts / GC_LIFE_S).
"""

import time

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.storage import table as table_mod


@pytest.fixture()
def sess():
    s = Session()
    s.execute("set global tidb_gc_life_time = 600")
    yield s
    s.execute("set global tidb_gc_life_time = 0")
    table_mod.set_gc_life(0)


class TestAsOfTimestamp:
    def test_as_of_sees_history(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(0.02)
        ts_mid = time.time()
        time.sleep(0.02)
        sess.execute("insert into t values (2)")
        assert sess.execute("select count(*) from t").rows == [(1 + 1,)]
        r = sess.execute(f"select count(*) from t as of timestamp {ts_mid}")
        assert r.rows == [(1,)]
        # joins: each ref resolves independently of current data
        r2 = sess.execute(
            f"select a from t as of timestamp {ts_mid} order by a"
        )
        assert r2.rows == [(1,)]

    def test_as_of_before_creation_errors(self, sess):
        sess.execute("create table t (a int)")
        with pytest.raises(ValueError, match="GC safepoint|before table"):
            sess.execute("select * from t as of timestamp 1.0")

    def test_as_of_inside_txn_rejected(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        ts = time.time()
        sess.execute("begin")
        try:
            with pytest.raises(ValueError, match="not allowed"):
                sess.execute(f"select * from t as of timestamp {ts}")
        finally:
            sess.execute("rollback")


class TestReadStaleness:
    def test_staleness_resolves_old_version(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1)")
        time.sleep(1.1)
        sess.execute("insert into t values (2)")
        sess.execute("set tidb_read_staleness = -1")
        try:
            # now-1s predates the second insert
            assert sess.execute("select count(*) from t").rows == [(1,)]
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert sess.execute("select count(*) from t").rows == [(2,)]

    def test_staleness_not_applied_to_dml_reads(self, sess):
        sess.execute("create table src (a int)")
        sess.execute("create table dst (a int)")
        sess.execute("insert into src values (1), (2)")
        sess.execute("set tidb_read_staleness = -1")
        try:
            # the SELECT half of INSERT..SELECT reads FRESH data even
            # though a plain SELECT would be stale
            sess.execute("insert into dst select a from src")
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert sess.execute("select count(*) from dst").rows == [(2,)]


class TestReadCommitted:
    def test_rc_sees_concurrent_commits(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s1.execute("create table t (a int)")
        s1.execute("insert into t values (1)")
        s1.execute("set transaction_isolation = 'READ-COMMITTED'")
        s1.execute("begin")
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s2.execute("insert into t values (2)")
        # RC: the next statement sees s2's commit mid-transaction
        assert s1.execute("select count(*) from t").rows == [(2,)]
        s1.execute("rollback")

    def test_rr_keeps_snapshot(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s1.execute("create table t (a int)")
        s1.execute("insert into t values (1)")
        s1.execute("begin")
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s2.execute("insert into t values (2)")
        # REPEATABLE-READ (default): snapshot pinned at first read
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s1.execute("rollback")


class TestStalenessEdges:
    def test_infoschema_immune_to_staleness(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("set tidb_read_staleness = -1")
        try:
            rows = sess.execute(
                "select table_name from information_schema.tables"
            ).rows
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert any(r[0] == "t" for r in rows)

    def test_tx_isolation_alias_mirrors(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s1.execute("create table t (a int)")
        s1.execute("insert into t values (1)")
        # the LEGACY alias must drive the RC provider too
        s1.execute("set tx_isolation = 'READ-COMMITTED'")
        s1.execute("begin")
        assert s1.execute("select count(*) from t").rows == [(1,)]
        s2.execute("insert into t values (2)")
        assert s1.execute("select count(*) from t").rows == [(2,)]
        s1.execute("rollback")

    def test_staleness_clamps_young_table(self, sess):
        # a table created inside the staleness window reads its earliest
        # retained state instead of erroring (usable-timestamp rule)
        sess.execute("create table fresh (a int)")
        sess.execute("insert into fresh values (1)")
        sess.execute("set tidb_read_staleness = -3600")
        try:
            rows = sess.execute("select count(*) from fresh").rows
        finally:
            sess.execute("set tidb_read_staleness = 0")
        assert rows[0][0] in (0, 1)  # oldest retained state, no error
