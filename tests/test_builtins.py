"""Builtin scalar function families (reference: pkg/expression
builtin_math_vec.go, builtin_string_vec.go, builtin_time_vec.go,
builtin_control_vec.go — the vectorized evaluators; here each family
compiles to device kernels or dictionary LUTs)."""

import math

import pytest

from tidb_tpu.session import Session


@pytest.fixture(scope="module")
def sess():
    s = Session()
    s.must_exec(
        "create table t (i int, f double, d decimal(10,2), s varchar(30), "
        "dt date)"
    )
    s.must_exec(
        "insert into t values "
        "(5, 2.25, 12.34, 'Hello World', '1994-03-15'), "
        "(-7, -1.5, -5.67, 'abc', '2000-12-31'), "
        "(0, 0.0, 0.00, '', '1970-01-01'), "
        "(100, 9.0, 99.99, 'MiXeD', '1999-06-01'), "
        "(null, null, null, null, null)"
    )
    return s


def col(r, i=0):
    return [row[i] for row in r.rows]


def test_math_unary(sess):
    r = sess.must_query("select abs(i), sign(i), floor(f), ceil(f) from t order by i")
    # i order: NULL sorts... use where not null
    r = sess.must_query(
        "select abs(i), sign(i), floor(f), ceil(f) from t where i is not null order by i"
    )
    assert col(r, 0) == [7, 0, 5, 100]
    assert col(r, 1) == [-1, 0, 1, 1]
    assert col(r, 2) == [-2, 0, 2, 9]
    assert col(r, 3) == [-1, 0, 3, 9]


def test_sqrt_log_null_domains(sess):
    r = sess.must_query(
        "select sqrt(f), ln(f) from t where i is not null order by i"
    )
    # f = -1.5, 0.0, 2.25, 9.0
    assert col(r, 0)[0] is None  # sqrt(-1.5) -> NULL
    assert col(r, 0)[1] == 0.0
    assert col(r, 0)[2] == 1.5
    assert col(r, 0)[3] == 3.0
    assert col(r, 1)[0] is None and col(r, 1)[1] is None  # ln(<=0) -> NULL
    assert math.isclose(col(r, 1)[2], math.log(2.25))


def test_round_truncate(sess):
    r = sess.must_query(
        "select round(d), round(d, 1), truncate(d, 1), round(i, -1) "
        "from t where i is not null order by i"
    )
    # d: -5.67, 0.00, 12.34, 99.99 ; i: -7, 0, 5, 100
    assert col(r, 0) == [-6, 0, 12, 100]
    assert col(r, 1) == [-5.7, 0.0, 12.3, 100.0]
    assert col(r, 2) == [-5.6, 0.0, 12.3, 99.9]
    assert col(r, 3) == [-10, 0, 10, 100]


def test_pow_mod_greatest_least(sess):
    r = sess.must_query(
        "select pow(i, 2), mod(i, 3), greatest(i, 0, 2), least(i, 0) "
        "from t where i is not null order by i"
    )
    assert col(r, 0) == [49.0, 0.0, 25.0, 10000.0]
    assert col(r, 1) == [-1, 0, 2, 1]  # MySQL: sign follows dividend
    assert col(r, 2) == [2, 2, 5, 100]
    assert col(r, 3) == [-7, 0, 0, 0]


def test_string_case_trim(sess):
    r = sess.must_query(
        "select upper(s), lower(s), reverse(s) from t where i = 5"
    )
    assert r.rows[0] == ("HELLO WORLD", "hello world", "dlroW olleH")
    r = sess.must_query("select trim('  x  '), ltrim('  x'), rtrim('x  ')")
    # tableless path may not support these; use the table instead
    r = sess.must_query(
        "select trim(concat(' ', s, ' ')) from t where i = -7"
    )
    assert r.rows[0][0] == "abc"


def test_substring_left_right(sess):
    r = sess.must_query(
        "select substring(s, 1, 5), substring(s, 7), left(s, 5), right(s, 5), "
        "substring(s, -5) from t where i = 5"
    )
    assert r.rows[0] == ("Hello", "World", "Hello", "World", "World")


def test_concat(sess):
    r = sess.must_query(
        "select concat(s, '!'), concat(s, '-', s), concat('n=', 7) "
        "from t where i = -7"
    )
    assert r.rows[0][0] == "abc!"
    assert r.rows[0][1] == "abc-abc"
    assert r.rows[0][2] == "n=7"
    # numeric COLUMNS can't join a dictionary product at trace time;
    # the error must be clean (reference coerces via cast-to-string,
    # which dictionary encoding cannot enumerate)
    with pytest.raises(Exception, match="CONCAT"):
        sess.execute("select concat('n=', i) from t")


def test_concat_null_propagates(sess):
    r = sess.must_query("select concat(s, null) from t where i = 5")
    assert r.rows[0][0] is None


def test_replace_pad_repeat(sess):
    r = sess.must_query(
        "select replace(s, 'l', 'L'), lpad(s, 5, '*'), rpad(s, 5, '*'), "
        "repeat(s, 2) from t where i = -7"
    )
    assert r.rows[0] == ("abc", "**abc", "abc**", "abcabc")


def test_length_ascii_locate(sess):
    r = sess.must_query(
        "select length(s), char_length(s), ascii(s), locate('World', s), "
        "instr(s, 'o') from t where i = 5"
    )
    assert r.rows[0] == (11, 11, 72, 7, 5)


def test_control_if_nullif_ifnull(sess):
    r = sess.must_query(
        "select if(i > 0, 'pos', 'nonpos'), nullif(i, 0), ifnull(i, -999) "
        "from t where i is not null order by i"
    )
    assert col(r, 0) == ["nonpos", "nonpos", "pos", "pos"]
    assert col(r, 1) == [-7, None, 5, 100]
    assert col(r, 2) == [-7, 0, 5, 100]
    r = sess.must_query("select ifnull(i, -999) from t where i is null")
    assert r.rows[0][0] == -999


def test_date_parts(sess):
    r = sess.must_query(
        "select year(dt), month(dt), day(dt), quarter(dt), dayofweek(dt), "
        "weekday(dt), dayofyear(dt) from t where i = 5"
    )
    # 1994-03-15 was a Tuesday: DAYOFWEEK=3 (Sun=1), WEEKDAY=1 (Mon=0)
    assert r.rows[0] == (1994, 3, 15, 1, 3, 1, 74)
    r = sess.must_query(
        "select dayofweek(dt), dayofyear(dt) from t where i = -7"
    )
    # 2000-12-31 was a Sunday, day 366 of the leap year
    assert r.rows[0] == (1, 366)


def test_datediff(sess):
    r = sess.must_query(
        "select datediff(dt, date '1994-01-01') from t where i = 5"
    )
    assert r.rows[0][0] == 73


def test_case_insensitive_filter_via_upper(sess):
    r = sess.must_query("select i from t where upper(s) = 'MIXED'")
    assert r.rows == [(100,)]


def test_nulls_propagate_through_builtins(sess):
    r = sess.must_query(
        "select abs(i), upper(s), year(dt), round(d) from t where i is null"
    )
    assert r.rows[0] == (None, None, None, None)


def test_datediff_string_literal(sess):
    """Date-string literals coerce in DATEDIFF (review regression)."""
    r = sess.must_query(
        "select datediff(dt, '1994-01-01') from t where i = 5"
    )
    assert r.rows[0][0] == 73


def test_cast_string_to_date(sess):
    r = sess.must_query("select dayofyear(cast('2024-03-01' as date))")
    assert r.rows[0][0] == 61
    r = sess.must_query("select quarter(cast('2024-12-31' as date))")
    assert r.rows[0][0] == 4
    r = sess.must_query(
        "select year(cast(s as date)) from t where i = 5"
    )
    assert r.rows[0][0] is None  # 'Hello World' is not a date -> NULL


def test_concat_ws_skips_nulls(sess):
    r = sess.must_query("select concat_ws(',', 'a', null, 'b')")
    assert r.rows[0][0] == "a,b"
    r = sess.must_query(
        "select concat_ws('-', s, 'x') from t order by i"
    )
    vals = [row[0] for row in r.rows]
    assert "x" in vals  # NULL s row contributes just 'x'
    assert "abc-x" in vals


def test_round_null_digits(sess):
    r = sess.must_query("select round(d, null) from t where i = 5")
    assert r.rows[0][0] is None


def test_neg_string_literal(sess):
    r = sess.must_query("select i from t where i = -'7' order by i")
    assert [t[0] for t in r.rows] == [-7]


def test_instr_null_needle(sess):
    r = sess.must_query("select instr(s, null) from t where i = 5")
    assert r.rows[0][0] is None


def test_field_function():
    from tidb_tpu.session.session import Session

    s = Session()
    s.execute("create table t (a int, b varchar(4), d decimal(10,2), dt date)")
    s.execute(
        "insert into t values (1,'y',1.50,'2024-05-01'),"
        "(2,'x',2.25,'2024-06-01'),(3,'z',3.00,'2024-07-01'),"
        "(null,null,null,null)"
    )
    # 1-based position among the values; 0 for absent AND for NULL
    assert s.execute(
        "select field(a, 2, 1), field(b, 'x') from t order by a"
    ).rows == [(0, 0), (2, 0), (1, 1), (0, 0)]
    assert s.execute(
        "select a from t order by field(b, 'y', 'x'), a"
    ).rows == [(None,), (3,), (1,), (2,)]
    # physical encodings: scaled decimals, epoch-day dates
    assert s.execute(
        "select field(d, 2.25, 1.50) from t order by a"
    ).rows == [(0,), (2,), (1,), (0,)]
    assert s.execute(
        "select field(dt, '2024-06-01') from t order by a"
    ).rows == [(0,), (0,), (1,), (0,)]
    # NULL needles never match; string needles coerce numerically
    assert s.execute("select field(b, null) from t").rows == [
        (0,), (0,), (0,), (0,)
    ]
    assert s.execute(
        "select field(a, '2') from t order by a"
    ).rows == [(0,), (0,), (1,), (0,)]
