"""Round-2 feature coverage: recursive CTEs, DISTINCT aggregates,
calendar-exact interval arithmetic, wide decimal SUM accumulation, and
the drop/recreate cache-aliasing regression."""

import math

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture()
def sess():
    return Session(Catalog())


# ---- recursive CTEs (reference: pkg/executor/cte.go:70) -------------------


def test_recursive_cte_sequence(sess):
    r = sess.must_query(
        "with recursive nums(n) as (select 1 union all "
        "select n + 1 from nums where n < 10) "
        "select sum(n), count(*), max(n) from nums"
    )
    assert r.rows == [(55, 10, 10)]


def test_recursive_cte_fib(sess):
    r = sess.must_query(
        "with recursive fib(a, b) as (select 1, 1 union all "
        "select b, a + b from fib where b < 100) select max(b) from fib"
    )
    assert r.rows == [(144,)]


def test_recursive_cte_hierarchy(sess):
    sess.execute("create table emp (id bigint, mgr bigint)")
    sess.execute(
        "insert into emp values (1, null), (2, 1), (3, 1), (4, 2), (5, 4), (6, 3)"
    )
    r = sess.must_query(
        "with recursive sub(id) as (select id from emp where id = 2 "
        "union all select e.id from emp e, sub where e.mgr = sub.id) "
        "select id from sub order by id"
    )
    assert [x[0] for x in r.rows] == [2, 4, 5]


def test_recursive_cte_union_distinct_cycle_terminates(sess):
    sess.execute("create table g (src bigint, dst bigint)")
    sess.execute("insert into g values (1,2),(2,3),(3,1),(3,4)")
    r = sess.must_query(
        "with recursive reach(node) as (select 1 union "
        "select g.dst from g, reach where g.src = reach.node) "
        "select node from reach order by node"
    )
    assert [x[0] for x in r.rows] == [1, 2, 3, 4]


def test_recursive_cte_depth_guard(sess):
    with pytest.raises(Exception, match="iterations"):
        sess.execute(
            "with recursive inf(n) as (select 1 union all "
            "select n + 1 from inf) select count(*) from inf"
        )


# ---- DISTINCT aggregates --------------------------------------------------


def test_count_distinct(sess):
    sess.execute("create table t (g varchar(8), x bigint)")
    sess.execute(
        "insert into t values ('a',1),('a',1),('a',2),('b',5),('b',null),"
        "('b',5),('c',null)"
    )
    r = sess.must_query(
        "select g, count(distinct x), count(*), sum(x) from t group by g order by g"
    )
    assert r.rows == [("a", 2, 3, 4), ("b", 1, 3, 10), ("c", 0, 1, None)]
    r = sess.must_query("select count(distinct x) from t")
    assert r.rows == [(3,)]
    r = sess.must_query("select sum(distinct x) from t")
    assert r.rows == [(8,)]
    r = sess.must_query("select avg(distinct x) from t")
    assert r.rows[0][0] == pytest.approx(8 / 3)


# ---- calendar-exact interval arithmetic -----------------------------------


def test_month_interval_exact(sess):
    sess.execute("create table d (i bigint, dt date)")
    sess.execute(
        "insert into d values (1,'1998-03-31'),(2,'1996-02-29'),(3,'1995-12-15')"
    )
    from tidb_tpu.dtypes import date_to_days

    r = sess.must_query(
        "select i, date_sub(dt, interval 1 month), "
        "date_add(dt, interval 1 year) from d order by i"
    )
    assert r.rows[0][1] == "1998-02-28"  # clamped, not -30d
    assert r.rows[0][2] == "1999-03-31"
    assert r.rows[1][1] == "1996-01-29"
    assert r.rows[1][2] == "1997-02-28"  # leap -> clamp
    assert r.rows[2][1] == "1995-11-15"
    r = sess.must_query("select date '1998-12-01' - interval 3 month")
    assert r.rows == [("1998-09-01",)]


# ---- wide decimal SUM (no int64 wraparound) -------------------------------


def test_wide_decimal_sum_no_overflow(sess):
    # scale-6 values: ~9.2e12 each scaled; 2000 rows of 9e14 scaled-6
    # would wrap int64 via the naive path at ~1e4 rows x 1e15
    sess.execute("create table w (v decimal(20, 2))")
    n = 200
    big = 92_000_000_000_000.25  # 9.2e13; scaled-6 product ~9.2e19 > 2^63
    sess.execute(
        "insert into w values " + ",".join(f"({big})" for _ in range(n))
    )
    r = sess.must_query("select sum(v * 1.0000 * 1.0000) from w")
    got = r.rows[0][0]
    assert got == pytest.approx(big * n, rel=1e-12)


# ---- drop/recreate aliasing regression ------------------------------------


def test_drop_recreate_no_stale_cache(sess):
    for i in range(6):
        sess.execute("drop table if exists r")
        sess.execute("create table r (x bigint)")
        sess.execute(f"insert into r values ({i}), ({i + 10})")
        r = sess.must_query("select sum(x) from r")
        assert r.rows == [(2 * i + 10,)], i


# ---- ROWS window frames ---------------------------------------------------


def test_rows_frame_sum_count(sess):
    sess.execute("create table wf (g varchar(4), x bigint)")
    sess.execute(
        "insert into wf values ('a',1),('a',2),('a',3),('a',4),('b',10),('b',20)"
    )
    r = sess.must_query(
        "select g, x, "
        "sum(x) over (partition by g order by x rows between 1 preceding and 1 following), "
        "count(*) over (partition by g order by x rows between 1 preceding and current row), "
        "sum(x) over (partition by g order by x rows between unbounded preceding and 1 following), "
        "sum(x) over (partition by g order by x rows 2 preceding) "
        "from wf order by g, x"
    )
    assert r.rows == [
        ("a", 1, 3, 1, 3, 1),
        ("a", 2, 6, 2, 6, 3),
        ("a", 3, 9, 2, 10, 6),
        ("a", 4, 7, 2, 10, 9),
        ("b", 10, 30, 1, 30, 10),
        ("b", 20, 30, 2, 30, 30),
    ]


def test_rows_frame_unbounded_equivalents(sess):
    sess.execute("create table wf2 (x bigint)")
    sess.execute("insert into wf2 values (1),(2),(3)")
    r = sess.must_query(
        "select x, "
        "sum(x) over (order by x rows between unbounded preceding and current row), "
        "sum(x) over (order by x rows between unbounded preceding and unbounded following) "
        "from wf2 order by x"
    )
    assert r.rows == [(1, 1, 6), (2, 3, 6), (3, 6, 6)]
