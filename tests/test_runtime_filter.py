"""Runtime filters (ISSUE 19, parallel/wire.py + dcn.py): the
bloom/in-list/min-max kernels (zero false negatives by construction,
bounded false-positive rate), the cross-host merge and its degrade
paths, filter-on/off parity end to end over an in-process 2-server
fleet (repartition join, semi join, DAG re-keyed GROUP BY, string and
NULL keys), the NDV cutover, the min-max pushdown below the exchange,
the partial-agg-skip decision, the filter-lost chaos degrade, the
worker-death retry seam, and the check_shuffle_hotpath house lint.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from tidb_tpu.parallel import aqe
from tidb_tpu.utils import failpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


def _decisions(name):
    return aqe.decision_counts().get(name, 0.0)


# -- filter kernels ---------------------------------------------------------


def _spec(bits=1 << 14, k=7, inlist_ndv=0):
    return {"bits": bits, "k": k, "inlist_ndv": inlist_ndv}


def _ints(vals):
    a = np.asarray(vals, dtype=np.int64)
    return a, np.ones(len(a), dtype=bool)


class TestFilterKernels:
    def test_bloom_zero_false_negatives(self):
        from tidb_tpu.parallel.wire import (
            bloom_geometry,
            build_runtime_filter,
            runtime_filter_test,
        )

        rng = np.random.default_rng(7)
        keys = rng.integers(-(2 ** 62), 2 ** 62, size=5000)
        nbits, k = bloom_geometry(len(keys), 10)
        ints, valid = _ints(keys)
        rf = build_runtime_filter(ints, valid, _spec(nbits, k))
        assert rf["kind"] == "bloom"
        keep = runtime_filter_test(ints, valid, rf)
        assert keep.all()  # a member NEVER tests negative

    def test_bloom_fpr_bounded(self):
        from tidb_tpu.parallel.wire import (
            bloom_geometry,
            build_runtime_filter,
            runtime_filter_test,
        )

        rng = np.random.default_rng(11)
        members = np.arange(1000, dtype=np.int64)
        nbits, k = bloom_geometry(len(members), 10)
        rf = build_runtime_filter(*_ints(members), _spec(nbits, k))
        probes = rng.integers(10 ** 6, 2 ** 62, size=20000)
        keep = runtime_filter_test(*_ints(probes), rf)
        # ~10 bits/key gives a sub-1% theoretical FPR; 3% leaves slack
        # for hash clustering without letting a regression hide
        assert keep.mean() < 0.03

    def test_inlist_cutover_on_ndv(self):
        from tidb_tpu.parallel.wire import build_runtime_filter

        ints, valid = _ints(list(range(100)) * 3)
        rf = build_runtime_filter(ints, valid, _spec(inlist_ndv=100))
        assert rf["kind"] == "inlist" and rf["ndv"] == 100
        assert sorted(rf["keys"]) == list(range(100))
        rf2 = build_runtime_filter(ints, valid, _spec(inlist_ndv=99))
        assert rf2["kind"] == "bloom"

    def test_merge_inlists_unions_keys(self):
        from tidb_tpu.parallel.wire import (
            build_runtime_filter,
            merge_runtime_filters,
            runtime_filter_test,
        )

        a = build_runtime_filter(*_ints([1, 2]), _spec(inlist_ndv=8))
        b = build_runtime_filter(*_ints([2, 9]), _spec(inlist_ndv=8))
        m = merge_runtime_filters([a, b])
        assert m["kind"] == "inlist"
        keep = runtime_filter_test(*_ints([1, 2, 9, 5]), m)
        assert keep.tolist() == [True, True, True, False]

    def test_merge_blooms_ors_bitsets(self):
        from tidb_tpu.parallel.wire import (
            build_runtime_filter,
            merge_runtime_filters,
            runtime_filter_test,
        )

        sp = _spec(1 << 10, 4)
        a = build_runtime_filter(*_ints(range(0, 50)), sp)
        b = build_runtime_filter(*_ints(range(50, 100)), sp)
        m = merge_runtime_filters([a, b])
        assert m["kind"] == "bloom"
        keep = runtime_filter_test(*_ints(range(100)), m)
        assert keep.all()  # members of EITHER host pass the merge

    def test_merge_degrades_to_none(self):
        from tidb_tpu.parallel.wire import (
            build_runtime_filter,
            merge_runtime_filters,
        )

        a = build_runtime_filter(*_ints([1]), _spec(inlist_ndv=4))
        assert merge_runtime_filters([a, None]) is None
        assert merge_runtime_filters([]) is None
        bad = build_runtime_filter(*_ints(range(64)), _spec(1 << 10, 4))
        bad["data"] = "!!!corrupt!!!"
        assert merge_runtime_filters([bad]) is None
        # geometry drift across hosts poisons the merge too
        g1 = build_runtime_filter(*_ints(range(64)), _spec(1 << 10, 4))
        g2 = build_runtime_filter(*_ints(range(64)), _spec(1 << 11, 4))
        assert merge_runtime_filters([g1, g2]) is None

    def test_minmax_bounds_and_null_keys(self):
        from tidb_tpu.parallel.wire import (
            build_runtime_filter,
            merge_runtime_filters,
            runtime_filter_test,
        )

        a = build_runtime_filter(
            *_ints([10, 20]), _spec(inlist_ndv=8), minmax=True
        )
        b = build_runtime_filter(
            *_ints([30]), _spec(inlist_ndv=8), minmax=True
        )
        m = merge_runtime_filters([a, b])
        assert (m["lo"], m["hi"]) == (10, 30)
        ints = np.asarray([5, 10, 30, 99, 20], dtype=np.int64)
        valid = np.asarray([True, True, True, True, False])
        keep = runtime_filter_test(ints, valid, m)
        # out-of-range AND null keys drop; members pass
        assert keep.tolist() == [False, True, True, False, False]

    def test_apply_block_drops_nulls_and_keeps_identity(self):
        from tidb_tpu.chunk import HostBlock, HostColumn
        from tidb_tpu.dtypes import INT64
        from tidb_tpu.parallel.wire import (
            apply_runtime_filter_block,
            build_runtime_filter,
        )

        col = HostColumn(
            INT64, np.asarray([1, 2, 3], dtype=np.int64),
            np.asarray([True, False, True]),
        )
        blk = HostBlock({"t.k": col}, 3)
        rf = build_runtime_filter(
            *_ints([1, 2, 3]), _spec(inlist_ndv=8)
        )
        out, rows_in, dropped = apply_runtime_filter_block(
            blk, "t.k", rf
        )
        assert (rows_in, dropped) == (3, 1)  # the NULL key drops
        assert out.nrows == 2
        # the no-drop case returns the SAME block object (no copy)
        col2 = HostColumn(
            INT64, np.asarray([1, 3], dtype=np.int64),
            np.ones(2, dtype=bool),
        )
        blk2 = HostBlock({"t.k": col2}, 2)
        out2, _ri, dr = apply_runtime_filter_block(blk2, "t.k", rf)
        assert dr == 0 and out2 is blk2

    def test_string_dict_keys_no_false_negatives(self):
        from tidb_tpu.chunk import HostBlock, HostColumn
        from tidb_tpu.dtypes import STRING
        from tidb_tpu.parallel.wire import (
            build_runtime_filter,
            key_ints_valid,
            runtime_filter_test,
        )

        words = np.asarray(sorted(f"w{i:03d}" for i in range(40)))
        codes = np.arange(40, dtype=np.int32)
        valid = np.ones(40, dtype=bool)
        valid[7] = False  # a NULL string key
        col = HostColumn(STRING, codes, valid, dictionary=words)
        blk = HostBlock({"t.s": col}, 40)
        ints, v = key_ints_valid(blk, "t.s")
        assert len(ints) == 40 and not v[7]
        # build from the first half's hashed image; every built key
        # passes, and the NULL never does
        rf = build_runtime_filter(
            ints[:20], v[:20], _spec(inlist_ndv=8)
        )
        assert rf["kind"] == "bloom"
        keep = runtime_filter_test(ints, v, rf)
        assert keep[:20].sum() == 19  # 20 minus the NULL at 7
        assert not keep[7]

    def test_shared_extraction_matches_partition_map(self):
        from tidb_tpu.chunk import HostBlock, HostColumn
        from tidb_tpu.dtypes import INT64
        from tidb_tpu.parallel.wire import (
            key_ints_valid,
            partition_histogram_from_ints,
            partition_map,
            partition_map_from_ints,
        )

        col = HostColumn(
            INT64, np.arange(200, dtype=np.int64) % 17,
            np.ones(200, dtype=bool),
        )
        blk = HostBlock({"t.k": col}, 200)
        ints, valid = key_ints_valid(blk, "t.k")
        pm = partition_map_from_ints(ints, valid, 4)
        assert (pm == partition_map(blk, "t.k", 4)).all()
        hist = partition_histogram_from_ints(ints, valid, 4)
        assert hist == np.bincount(pm, minlength=4).tolist()

    def test_minmax_pushdown_wraps_scan_in_selection(self):
        """Regression guard: the BETWEEN wrap must actually build (a
        broken import inside the try/except would silently disable the
        pushdown forever)."""
        from tidb_tpu.parallel.dcn import DCNFragmentScheduler
        from tidb_tpu.planner import logical as L
        from tidb_tpu.planner.fragmenter import split_plan_shuffle

        sess = _sess()
        plan = _plan(
            sess,
            "select count(*) from rft_big join rft_small "
            "on rft_big.k = rft_small.k",
        )
        sp = split_plan_shuffle(plan, sess.catalog)
        side = next(s for s in sp.sides if s.tag == 0)
        node = side.host_plan(0, 2)
        rf = {"kind": "inlist", "keys": [5, 95], "ndv": 2,
              "lo": 5, "hi": 95}
        wrapped = DCNFragmentScheduler._rf_pushdown_plan(
            node, side.key, rf
        )
        assert isinstance(wrapped, L.Selection)
        # no bounds -> untouched plan
        assert DCNFragmentScheduler._rf_pushdown_plan(
            node, side.key, {"kind": "inlist", "keys": [1], "ndv": 1}
        ) is node


# -- end to end over an in-process 2-server fleet ---------------------------


def _sess():
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog

    cat = Catalog()
    s = Session(cat, db="test")
    s.execute("create table rft_big (k int, g int, v int)")
    s.execute(
        "insert into rft_big values "
        + ",".join(f"({i % 100},{i % 7},{i})" for i in range(800))
    )
    # build-side keys 5 and 95: the in-list rejects 98% of probe rows
    # while the min-max BETWEEN alone keeps 91% — both layers observable
    s.execute("create table rft_small (k int, c int)")
    s.execute("insert into rft_small values (5,50),(95,950)")
    s.execute("create table rft_s1 (s varchar(8), v int)")
    s.execute(
        "insert into rft_s1 values "
        + ",".join(f"('s{i % 50:02d}',{i})" for i in range(300))
        + ",(null,1),(null,2)"
    )
    s.execute("create table rft_s2 (s varchar(8))")
    s.execute("insert into rft_s2 values ('s03'),('s27'),(null)")
    return s


def _plan(sess, q):
    from tidb_tpu.parser.sqlparse import parse
    from tidb_tpu.planner.logical import build_query

    return build_query(
        parse(q)[0], sess.catalog, "test", sess._scalar_subquery
    )


@pytest.fixture(scope="module")
def fleet():
    from tidb_tpu.server.engine_rpc import EngineServer

    sess = _sess()
    servers = [EngineServer(sess.catalog, port=0) for _ in range(2)]
    for s in servers:
        s.start_background()
    yield sess, servers
    for s in servers:
        s.shutdown()


def _sched(sess, servers, **kw):
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler

    kw.setdefault("shuffle_mode", "always")
    kw.setdefault("shuffle_dag", "never")
    kw.setdefault("shuffle_wait_timeout_s", 30.0)
    return DCNFragmentScheduler(
        [("127.0.0.1", s.port) for s in servers],
        catalog=sess.catalog, **kw,
    )


JOIN_Q = (
    "select count(*), sum(rft_big.v) from rft_big "
    "join rft_small on rft_big.k = rft_small.k"
)


class TestRuntimeFilterE2E:
    def test_join_parity_bytes_and_surfaces(self, fleet):
        sess, servers = fleet
        plan = _plan(sess, JOIN_Q)
        on = _sched(sess, servers, runtime_filter="always")
        off = _sched(sess, servers, runtime_filter="off")
        try:
            before = _decisions("runtime-filter")
            _c, r1 = on.execute_plan(plan)
            _c, r2 = off.execute_plan(plan)
            assert r1 == r2
            assert _decisions("runtime-filter") == before + 1
            st = on.last_query["shuffle"]
            rf = st.get("rf")
            assert rf and rf["kind"] == "inlist" and rf["tag"] == 0
            assert rf["ndv"] == 2 and rf.get("sel_obs") is not None
            assert "runtime-filter:inlist@t0" in st["adaptive"]
            # the acceptance bar: >= 2x tunnel-byte reduction on a
            # build side that rejects >= 90% of probe rows
            off_bytes = off.last_query["shuffle"]["bytes_tunneled"]
            assert st["bytes_tunneled"] * 2 <= off_bytes
            # the off arm carries no rf surface at all
            assert "rf" not in off.last_query["shuffle"]
            _c2, _r, lines = on.explain_analyze(plan)
            row = next(l for l in lines if "DCNShuffle" in l)
            assert " rf=inlist" in row
            assert "sel_pred=" in row and "sel_obs=" in row
        finally:
            on.close()
            off.close()

    def test_semi_join_parity(self, fleet):
        sess, servers = fleet
        q = (
            "select count(*) from rft_big where rft_big.k in "
            "(select k from rft_small)"
        )
        plan = _plan(sess, q)
        on = _sched(sess, servers, runtime_filter="always")
        off = _sched(sess, servers, runtime_filter="off")
        try:
            _c, r1 = on.execute_plan(plan)
            _c, r2 = off.execute_plan(plan)
            assert r1 == r2 == [(16,)]
            assert on.last_query["shuffle"].get("rf")
        finally:
            on.close()
            off.close()

    def test_string_keys_with_nulls_parity(self, fleet):
        """String-dictionary keys hash per distinct value; NULL keys
        never match an equi-join on either arm — parity must hold with
        the filter dropping them producer-side."""
        sess, servers = fleet
        q = (
            "select count(*), sum(rft_s1.v) from rft_s1 "
            "join rft_s2 on rft_s1.s = rft_s2.s"
        )
        plan = _plan(sess, q)
        on = _sched(sess, servers, runtime_filter="always")
        off = _sched(sess, servers, runtime_filter="off")
        try:
            _c, r1 = on.execute_plan(plan)
            _c, r2 = off.execute_plan(plan)
            assert r1 == r2
            st = on.last_query["shuffle"]
            rf = st.get("rf")
            assert rf and rf["kind"] == "inlist"
            # no min-max bounds for string keys -> no BETWEEN
            # pushdown, so the worker-side filter observes the drops
            assert rf["rows_in"] > 0 and rf["dropped"] > 0
            assert rf["sel_obs"] < 1.0
        finally:
            on.close()
            off.close()

    def test_ndv_cutover_to_bloom(self, fleet):
        sess, servers = fleet
        plan = _plan(sess, JOIN_Q)
        on = _sched(
            sess, servers, runtime_filter="always", rf_inlist_ndv=0
        )
        off = _sched(sess, servers, runtime_filter="off")
        try:
            _c, r1 = on.execute_plan(plan)
            _c, r2 = off.execute_plan(plan)
            assert r1 == r2
            rf = on.last_query["shuffle"]["rf"]
            assert rf["kind"] == "bloom" and rf["bits"] > 0
            _c2, _r, lines = on.explain_analyze(plan)
            row = next(l for l in lines if "DCNShuffle" in l)
            assert " rf=bloom:" in row
        finally:
            on.close()
            off.close()

    def test_dag_rekeyed_groupby_parity(self, fleet):
        """Two hash stages: the filter arms on the stage-0 join (both
        sides are Scan.frag) and must NOT touch the stage-1 re-keyed
        exchange (StageInput sides) — parity across the whole chain."""
        sess, servers = fleet
        q = (
            "select g, count(*), sum(v) from rft_big "
            "join rft_small on rft_big.k = rft_small.k "
            "group by g order by g"
        )
        plan = _plan(sess, q)
        on = _sched(
            sess, servers, shuffle_dag="always",
            runtime_filter="always",
        )
        off = _sched(
            sess, servers, shuffle_dag="always", runtime_filter="off"
        )
        try:
            kind, cut = on._choose_cut(plan)
            assert kind == "dag" and len(cut.stages) >= 2
            _c, r1 = on.execute_plan(plan)
            _c, r2 = off.execute_plan(plan)
            assert r1 == r2
            stages = on.last_query["shuffle_stages"]
            assert stages[0].get("rf")
            assert any(
                t.startswith("runtime-filter:")
                for t in (stages[0].get("adaptive") or [])
            )
            assert all(not s.get("rf") for s in stages[1:])
        finally:
            on.close()
            off.close()

    def test_partial_agg_skip_decision_and_parity(self, fleet):
        """Group NDV ~ row count on the probed side: the partial agg
        folds nothing, so the aggskip variant ships raw join rows to
        the final aggregate — declared decision, exact parity."""
        sess, servers = fleet
        q = (
            "select v, count(*) from rft_big "
            "join rft_small on rft_big.k = rft_small.k "
            "group by v order by v"
        )
        plan = _plan(sess, q)
        on = _sched(sess, servers, runtime_filter="always")
        off = _sched(sess, servers, runtime_filter="off")
        try:
            before = _decisions("partial-agg-skip")
            _c, r1 = on.execute_plan(plan)
            _c, r2 = off.execute_plan(plan)
            assert r1 == r2
            assert _decisions("partial-agg-skip") == before + 1
            toks = on.last_query["shuffle"]["adaptive"]
            assert any(
                t.startswith("partial-agg-skip:") for t in toks
            )
        finally:
            on.close()
            off.close()

    def test_filter_site_fires_on_filtered_stage(self, fleet):
        sess, servers = fleet
        plan = _plan(sess, JOIN_Q)
        on = _sched(sess, servers, runtime_filter="always")
        hits = []
        failpoint.enable("shuffle/filter", lambda: hits.append(1))
        try:
            on.execute_plan(plan)
            assert hits
        finally:
            failpoint.disable("shuffle/filter")
            on.close()

    def test_filter_lost_degrades_with_parity(self, fleet):
        """shuffle/filter-lost models a filter lost between broadcast
        and application: the side ships unfiltered (the filter is a
        bytes optimization, never a correctness dependency), the loss
        is counted, and results stay exact."""
        sess, servers = fleet
        plan = _plan(sess, JOIN_Q)
        on = _sched(sess, servers, runtime_filter="always")
        off = _sched(sess, servers, runtime_filter="off")
        try:
            _c, exp = off.execute_plan(plan)
            failpoint.enable("shuffle/filter-lost", True)
            _c, got = on.execute_plan(plan)
            assert got == exp
            rf = on.last_query["shuffle"]["rf"]
            assert rf.get("lost", 0) >= 1
            _c2, _r, lines = on.explain_analyze(plan)
            row = next(l for l in lines if "DCNShuffle" in l)
            assert "rf_lost=" in row
        finally:
            failpoint.disable("shuffle/filter-lost")
            on.close()
            off.close()

    def test_worker_death_between_broadcast_and_stage(self):
        """Retry parity: the probe round completes (filter built and
        merged), then a worker dies before the stage round. The stage
        dispatch fails, the suspect quarantines, and the retry on the
        survivor (m=1) stands the filter down — no stale rf= on the
        summary, exact results."""
        from tidb_tpu.server.engine_pool import FailedEngineProber
        from tidb_tpu.server.engine_rpc import EngineServer

        sess = _sess()
        servers = [
            EngineServer(sess.catalog, port=0) for _ in range(2)
        ]
        for s in servers:
            s.start_background()
        sched = _sched(
            sess, servers, runtime_filter="always",
            shuffle_wait_timeout_s=5.0,
            prober=FailedEngineProber(initial_backoff_s=60),
        )
        exp = sess.must_query(JOIN_Q).rows
        orig = sched._probe_stage
        killed = []

        def spy(*a, **kw):
            out = orig(*a, **kw)
            if not killed:
                killed.append(1)
                servers[1].shutdown()
            return out

        sched._probe_stage = spy
        try:
            _c, got = sched.execute_plan(_plan(sess, JOIN_Q))
            assert got == exp
            st = sched.last_query["shuffle"]
            assert st["attempts"] >= 2
            # the m=1 retry ran unfiltered: the first attempt's rf
            # must not linger on the summary
            assert "rf" not in st
        finally:
            sched.close()
            for s in servers:
                s.shutdown()


# -- the house lint ---------------------------------------------------------


class TestHotpathLint:
    def _run(self, root):
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "check_shuffle_hotpath.py"),
             root],
            capture_output=True, text=True,
        )

    def test_clean_at_head(self):
        r = self._run(REPO)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_seeded_violations(self, tmp_path):
        pkg = tmp_path / "tidb_tpu" / "parallel"
        pkg.mkdir(parents=True)
        (pkg / "shuffle.py").write_text(
            "class ShuffleWorker:\n"
            "    def _apply_side_filter(self, blk, key, rf, st, lk):\n"
            "        for k in rf['keys'].tolist():\n"
            "            pass\n"
            "        return blk\n"
        )
        (pkg / "wire.py").write_text(
            "import json\n"
            "def runtime_filter_test(ints, valid, rf):\n"
            "    return json.loads(rf['data'])\n"
        )
        r = self._run(str(tmp_path))
        assert r.returncode == 1
        assert "tolist() in 'ShuffleWorker._apply_side_filter'" in r.stdout
        assert "runtime_filter_test" in r.stdout
