"""Tier-1 gate for scripts/check_flight_phases.py: the declared flight
phase vocabulary (obs/flight.py PHASES) stays in lockstep with the
literal note_phase() call sites — statements_summary's avg_* columns,
the slow-log `# Phases` line and tidbtpu_flight_phase_seconds{phase}
all key on these names."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "check_flight_phases.py")


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, LINT, REPO], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        f"flight-phase violations:\n{proc.stdout}{proc.stderr}"
    )


def test_lint_catches_violations(tmp_path):
    obs = tmp_path / "tidb_tpu" / "obs"
    obs.mkdir(parents=True)
    (obs / "flight.py").write_text(
        'PHASES = (\n    "parse",\n    "dead-phase",\n)\n'
        'FLIGHT = None\n'
    )
    (tmp_path / "tidb_tpu" / "engine.py").write_text(
        'from tidb_tpu.obs.flight import FLIGHT\n'
        'FLIGHT.note_phase("parse", 0.1)\n'
        'FLIGHT.note_phase("typo-phase", 0.1)\n'
    )
    proc = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "typo-phase" in proc.stdout     # undeclared call site
    assert "dead-phase" in proc.stdout     # declared but never charged
    assert "'parse'" not in proc.stdout    # declared + used: clean


def test_runtime_rejects_undeclared_phase():
    """note_phase is the runtime half of the lint: an undeclared name
    raises instead of silently forking the breakdown."""
    from tidb_tpu.obs.flight import FlightRecorder

    f = FlightRecorder()
    f.begin("select 1")
    with pytest.raises(ValueError, match="undeclared flight phase"):
        f.note_phase("no-such-phase", 0.1)
    f.discard()
