"""Adaptive query execution (ISSUE 15, parallel/aqe.py): skew-salted
routing units, the salted/broadcast-switch/feedback decisions end to
end over an in-process 2-server fleet, the history-seeded cardinality
feedback store, the statements_summary est/act divergence surface, the
cardinality-drift inspection rule, the replan-crash chaos class, and
the check_aqe_decisions house lint.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from tidb_tpu.parallel import aqe
from tidb_tpu.utils import failpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_failpoints():
    yield
    failpoint.disable_all()


def _decisions(name):
    return aqe.decision_counts().get(name, 0.0)


# -- wire-level salted routing ---------------------------------------------


def _block(keys, vals=None):
    from tidb_tpu.chunk import HostBlock, HostColumn
    from tidb_tpu.dtypes import INT64

    keys = np.asarray(keys, dtype=np.int64)
    cols = {
        "t.k": HostColumn(INT64, keys, np.ones(len(keys), dtype=bool)),
    }
    if vals is not None:
        cols["t.v"] = HostColumn(
            INT64, np.asarray(vals, dtype=np.int64),
            np.ones(len(keys), dtype=bool),
        )
    return HostBlock(cols, len(keys))


class TestSaltedRouting:
    def test_partition_histogram_matches_partition_map(self):
        from tidb_tpu.parallel.wire import (
            partition_histogram,
            partition_map,
        )

        blk = _block(list(range(100)) + [7] * 40)
        hist = partition_histogram(blk, "t.k", 4)
        pmap = partition_map(blk, "t.k", 4)
        assert hist == np.bincount(pmap, minlength=4).tolist()
        assert sum(hist) == blk.nrows

    def test_hot_key_ints_ranks_by_count(self):
        from tidb_tpu.parallel.wire import column_key_ints, hot_key_ints

        blk = _block([5] * 30 + [9] * 10 + list(range(100, 110)))
        hot = hot_key_ints(blk, "t.k", top=2)
        assert len(hot) == 2
        ints = column_key_ints(blk.columns["t.k"])
        assert hot[0] == [int(ints[0]), 30]
        assert hot[1][1] == 10

    def test_split_map_scatters_only_flagged_keys(self):
        from tidb_tpu.parallel.wire import (
            column_key_ints,
            partition_map,
            salt_targets,
            salted_split_map,
        )

        m, k = 4, 2
        blk = _block([7] * 50 + list(range(40)))
        key_int = int(column_key_ints(blk.columns["t.k"])[0])
        salt = {"keys": [key_int], "k": k}
        base = partition_map(blk, "t.k", m)
        out = salted_split_map(blk, "t.k", m, salt)
        targets = set(salt_targets(key_int, m, k))
        assert len(targets) == k
        # flagged rows land ONLY in the salted target set, spread
        # across it; unflagged rows keep their hash home
        assert set(out[:50].tolist()) == targets
        assert (out[50:] == base[50:]).all()

    def test_replicate_fans_hot_rows_to_every_lane(self):
        from tidb_tpu.parallel.wire import (
            column_key_ints,
            salt_targets,
            salted_partition_assign,
        )

        m, k = 4, 3
        blk = _block([3] * 5 + [100, 101])
        key_int = int(column_key_ints(blk.columns["t.k"])[0])
        salt = {"keys": [key_int], "k": k}
        base, flagged, kk = salted_partition_assign(
            blk, "t.k", m, salt
        )
        assert kk == k and flagged[:5].all() and not flagged[5:].any()
        # the replicate fan-out: base+j (mod m) covers salt_targets
        assert sorted(
            (int(base[0]) + j) % m for j in range(kk)
        ) == sorted(salt_targets(key_int, m, k))

    def test_salt_k_clamps_to_partition_count(self):
        from tidb_tpu.parallel.wire import salted_partition_assign

        blk = _block([1] * 8)
        _b, _f, k = salted_partition_assign(
            blk, "t.k", 2, {"keys": [123], "k": 16}
        )
        assert k == 2  # a wrap past m would duplicate replicate copies

    def test_null_keys_never_flagged(self):
        from tidb_tpu.chunk import HostBlock, HostColumn
        from tidb_tpu.dtypes import INT64
        from tidb_tpu.parallel.wire import salted_partition_assign

        col = HostColumn(
            INT64, np.asarray([0, 0, 5], dtype=np.int64),
            np.asarray([False, False, True]),
        )
        blk = HostBlock({"t.k": col}, 3)
        _b, flagged, _k = salted_partition_assign(
            blk, "t.k", 4, {"keys": [0], "k": 2}
        )
        assert not flagged[:2].any()


# -- planner shapes ---------------------------------------------------------


def _sess():
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog

    cat = Catalog()
    s = Session(cat, db="test")
    s.execute("create table jl (a int, v int)")
    s.execute(
        "insert into jl values "
        + ",".join(f"({i % 20},{i})" for i in range(60))
    )
    s.execute("create table jm (a int, c int)")
    s.execute("insert into jm values (1,100),(2,200)")
    s.execute("create table jr (c int, w int)")
    s.execute(
        "insert into jr values "
        + ",".join(f"({i % 10 + 300},{i})" for i in range(80))
        + ",(100,1),(200,2)"
    )
    s.execute("create table gz (b varchar(8), a int)")
    s.execute(
        "insert into gz values "
        + ",".join(f"('h',{i})" for i in range(30))
        + ","
        + ",".join(f"('k{i}',{i})" for i in range(10))
    )
    return s


def _plan(sess, q):
    from tidb_tpu.parser.sqlparse import parse
    from tidb_tpu.planner.logical import build_query

    return build_query(
        parse(q)[0], sess.catalog, "test", sess._scalar_subquery
    )


class TestPlannerShapes:
    def test_salted_groupby_variant_decomposes(self):
        from tidb_tpu.planner import logical as L
        from tidb_tpu.planner.fragmenter import (
            split_plan_shuffle,
            split_plan_shuffle_salted,
        )

        sess = _sess()
        plan = _plan(sess, "select b, count(*), sum(a) from gz group by b")
        sp = split_plan_shuffle(plan, sess.catalog)
        assert sp is not None and sp.kind == "groupby"
        sp2 = split_plan_shuffle_salted(plan, sess.catalog)
        assert sp2 is not None
        # the salted consumer is the PARTIAL aggregate — its output
        # re-merges through the final-agg builder, so a split group
        # stays exact
        assert isinstance(sp2.consumer, L.Aggregate)
        assert sp2.sides[0].key == sp.sides[0].key
        # same producer plan => the probe's cached block is reusable
        assert sp2.sides[0].template is sp.sides[0].template

    def test_salted_variant_refuses_distinct(self):
        from tidb_tpu.planner.fragmenter import split_plan_shuffle_salted

        sess = _sess()
        plan = _plan(
            sess, "select b, count(distinct a) from gz group by b"
        )
        assert split_plan_shuffle_salted(plan, sess.catalog) is None

    def test_join_chain_dag_two_stages(self):
        from tidb_tpu.planner import logical as L
        from tidb_tpu.planner.fragmenter import split_plan_dag

        sess = _sess()
        plan = _plan(
            sess,
            "select count(*), sum(w) from jl join jm on jl.a = jm.a "
            "join jr on jm.c = jr.c",
        )
        dag = split_plan_dag(plan, sess.catalog)
        assert dag is not None and len(dag.stages) == 2
        st0, st1 = dag.stages
        assert st0.join_kind == "inner" and st1.join_kind == "inner"
        # stage 1 re-exchanges stage 0's HELD output — no re-scan
        assert isinstance(st1.sides[0].template, L.StageInput)
        assert st1.sides[0].template.stage == 0
        assert not st1.requires_key_partition
        assert dag.merge["kind"] == "plan"

    def test_choose_shuffle_modes_switches_and_resets(self):
        from tidb_tpu.planner.fragmenter import (
            choose_shuffle_modes,
            split_plan_shuffle,
        )

        sess = _sess()
        plan = _plan(
            sess, "select count(*) from jl join jm on jl.a = jm.a"
        )
        sp = split_plan_shuffle(plan, sess.catalog)
        assert sp is not None and sp.join_kind == "inner"
        # jm (2 rows) collapses under the bar; jl (60) clears ratio
        assert choose_shuffle_modes(sp, 10) == "broadcast"
        modes = sorted(s.mode for s in sp.sides)
        assert modes == ["broadcast", "local"]
        # re-planning with the bar off RESETS to hash both ways
        assert choose_shuffle_modes(sp, 0) == "hash"
        assert all(s.mode == "hash" for s in sp.sides)

    def test_groupby_cut_never_broadcasts(self):
        from tidb_tpu.planner.fragmenter import (
            choose_shuffle_modes,
            split_plan_shuffle,
        )

        sess = _sess()
        plan = _plan(sess, "select b, count(*) from gz group by b")
        sp = split_plan_shuffle(plan, sess.catalog)
        assert sp is not None and sp.kind == "groupby"
        assert choose_shuffle_modes(sp, 10 ** 9) == "hash"


# -- cardinality feedback store --------------------------------------------


class TestCardinalityFeedback:
    def test_record_and_seed_roundtrip(self):
        from tidb_tpu.planner.cardinality import CardinalityFeedback

        fb = CardinalityFeedback(capacity=4)
        fb.record("d1", est=1000.0, act=3.0, sides={"0:0": 3, "0:1": 120})
        assert fb.sides_for("d1") == {"0:0": 3, "0:1": 120}
        assert fb.est_act("d1") == (1000.0, 3.0)
        assert fb.sides_for("unknown") is None

    def test_bounded_capacity_evicts_oldest(self):
        from tidb_tpu.planner.cardinality import CardinalityFeedback

        fb = CardinalityFeedback(capacity=2)
        for i in range(4):
            fb.record(f"d{i}", sides={"0:0": i})
        assert fb.sides_for("d0") is None and fb.sides_for("d1") is None
        assert fb.sides_for("d3") == {"0:0": 3}

    def test_warm_from_history_seeds_est_act(self):
        from tidb_tpu.planner.cardinality import CardinalityFeedback
        from tidb_tpu.utils.metrics import StmtHistory, StmtSummary

        class _F:
            phases = {}
            rows_sent = 5
            plan_digest = ""
            plan_cache = ""
            jit_compilations = retraces = h2d_bytes = d2h_bytes = 0
            device_mem_peak_bytes = 0
            est_rows = 500.0
            act_rows = 5.0

        summ = StmtSummary(capacity=8)
        hist = StmtHistory(max_windows=4, refresh_interval_s=0.001)
        summ.history = hist
        summ.record("select x", 0.01, flight=_F())
        hist.rotate(summ)
        fb = CardinalityFeedback()
        assert fb.warm_from_history(hist) == 1
        est, act = fb.est_act("select x")
        assert est == 500.0 and act == 5.0


# -- statements_summary est/act surface ------------------------------------


class TestCardinalitySummary:
    def test_divergence_columns_aggregate(self):
        from tidb_tpu.obs.flight import FlightRecorder
        from tidb_tpu.utils.metrics import StmtSummary

        fl = FlightRecorder()
        fl.begin("select z", conn_id=1)
        fl.note_cardinality(1000.0, 10.0)
        rec = fl.finish(0.01)
        summ = StmtSummary(capacity=8)
        summ.record("select z", 0.01, flight=rec)
        row = summ.rows_full()[0]
        assert row["est_rows"] == 1000.0 and row["act_rows"] == 10.0
        assert row["card_divergence"] == 100.0  # symmetric, >= 1

    def test_information_schema_exposes_columns(self):
        sess = _sess()
        r = sess.must_query(
            "select est_rows, act_rows, card_divergence from "
            "information_schema.statements_summary limit 1"
        )
        assert [c.lower() for c in r.columns] == [
            "est_rows", "act_rows", "card_divergence",
        ]


# -- inspection rule --------------------------------------------------------


class TestCardinalityDriftRule:
    def _engine(self):
        from tidb_tpu.obs.inspection import InspectionEngine
        from tidb_tpu.obs.tsdb import TimeSeriesStore

        store = TimeSeriesStore()
        return store, InspectionEngine(store)

    def _feed(self, store, series):
        store.merge_remote(
            [["tidbtpu_aqe_misestimates_total", [], [], t, v,
              "counter"] for t, v in series],
            host="coordinator",
        )

    def test_fires_on_chronic_misestimates(self):
        store, eng = self._engine()
        self._feed(store, [(100.0, 0.0), (200.0, 5.0)])
        fs = [
            f for f in eng.run(t_lo=50.0, t_hi=250.0)
            if f.rule == "cardinality-drift"
        ]
        assert fs and fs[0].severity == "warning"
        assert "aqe_feedback" in fs[0].detail

    def test_quiet_below_threshold(self):
        store, eng = self._engine()
        self._feed(store, [(100.0, 0.0), (200.0, 1.0)])
        assert not [
            f for f in eng.run(t_lo=50.0, t_hi=250.0)
            if f.rule == "cardinality-drift"
        ]


# -- decision registry ------------------------------------------------------


class TestDecisionRegistry:
    def test_undeclared_decision_raises(self):
        with pytest.raises(ValueError, match="undeclared AQE decision"):
            aqe.note_decision("nope")

    def test_note_returns_token_and_counts(self):
        before = _decisions("salted")
        assert aqe.note_decision("salted", "3") == "salted:3"
        assert _decisions("salted") == before + 1


# -- chaos class ------------------------------------------------------------


class TestReplanCrashClass:
    def test_declared_and_deterministic(self):
        from tidb_tpu.chaos.schedule import (
            FAULT_CLASSES,
            ChaosSchedule,
            generate_replan_kill_specs,
        )

        assert "replan-crash" in FAULT_CLASSES
        a = ChaosSchedule.generate(11, 8, 3, classes=("replan-crash",))
        b = ChaosSchedule.generate(11, 8, 3, classes=("replan-crash",))
        assert a == b
        sites = {
            f.site for ep in a.episodes for f in ep.faults
        }
        assert sites == {"aqe/switched-stage"}
        specs = generate_replan_kill_specs(7, 2)
        assert len(specs) == 2
        assert any(
            f["site"] == "aqe/switched-stage" and f["kind"] == "exit"
            for f in specs[-1]
        )


# -- the house lint ---------------------------------------------------------


class TestAqeDecisionsLint:
    def _run(self, root):
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "check_aqe_decisions.py"),
             root],
            capture_output=True, text=True,
        )

    def test_clean_at_head(self):
        r = self._run(REPO)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_seeded_violations(self, tmp_path):
        pkg = tmp_path / "tidb_tpu" / "parallel"
        pkg.mkdir(parents=True)
        (pkg / "aqe.py").write_text(
            'AQE_DECISIONS = {"good": "x", "dead": "y"}\n'
        )
        (tmp_path / "eng.py").write_text(
            "def f(v):\n"
            '    note_decision("good")\n'
            '    note_decision("undeclared")\n'
            "    note_decision(v)\n"
        )
        r = self._run(str(tmp_path))
        assert r.returncode == 1
        assert "undeclared AQE decision 'undeclared'" in r.stdout
        assert "non-literal AQE decision" in r.stdout
        assert "declared AQE decision 'dead'" in r.stdout


# -- end to end over an in-process 2-server fleet ---------------------------


@pytest.fixture(scope="module")
def fleet():
    from tidb_tpu.server.engine_rpc import EngineServer

    sess = _sess()
    servers = [EngineServer(sess.catalog, port=0) for _ in range(2)]
    for s in servers:
        s.start_background()
    yield sess, servers
    for s in servers:
        s.shutdown()


def _sched(sess, servers, **kw):
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler

    kw.setdefault("shuffle_mode", "always")
    kw.setdefault("shuffle_dag", "never")
    kw.setdefault("shuffle_wait_timeout_s", 30.0)
    return DCNFragmentScheduler(
        [("127.0.0.1", s.port) for s in servers],
        catalog=sess.catalog, **kw,
    )


class TestAdaptiveE2E:
    def test_salted_groupby_parity_and_surfaces(self, fleet):
        sess, servers = fleet
        q = "select b, count(*), sum(a) from gz group by b order by b"
        plan = _plan(sess, q)
        salted = _sched(
            sess, servers, shuffle_skew_ratio=1.4,
            shuffle_skew_salt_k=2,
        )
        plain = _sched(sess, servers, shuffle_skew_ratio=0.0)
        try:
            before = _decisions("salted")
            _c, r1 = salted.execute_plan(plan)
            _c, r2 = plain.execute_plan(plan)
            assert r1 == r2
            st = salted.last_query["shuffle"]
            assert st["adaptive"] == ["salted:2"]
            assert st["salted"] == 2
            assert _decisions("salted") == before + 1
            # the plain arm's stage summary still carries the skew
            # ratio — detection auditable without salting
            stp = plain.last_query["shuffle"]
            assert stp.get("skew", 0) > 1.0
            assert len(stp.get("part_rows") or []) == 2
            # salting rebalanced the received rows
            assert st["skew"] < stp["skew"]
            # EXPLAIN ANALYZE renders both fields
            _c2, _r, lines = salted.explain_analyze(plan)
            row = next(l for l in lines if "DCNShuffle" in l)
            assert "adaptive=salted:2" in row and "skew=" in row
        finally:
            salted.close()
            plain.close()

    def test_broadcast_switch_on_collapsed_side(self, fleet):
        sess, servers = fleet
        # static est (jl: 60 rows) clears the 10-row bar, but the
        # a < 2 filter collapses the observed side to ~6 rows
        q = (
            "select count(*), sum(v) from jl "
            "join jr on jl.a = jr.w where jl.a < 2"
        )
        plan = _plan(sess, q)
        adaptive = _sched(
            sess, servers, shuffle_skew_ratio=1.4,
            shuffle_broadcast_rows=10,
        )
        plain = _sched(sess, servers, shuffle_skew_ratio=0.0)
        try:
            before = _decisions("broadcast-switch")
            _c, r1 = adaptive.execute_plan(plan)
            _c, r2 = plain.execute_plan(plan)
            assert r1 == r2
            st = adaptive.last_query["shuffle"]
            assert st["adaptive"] == ["broadcast-switch"]
            assert _decisions("broadcast-switch") == before + 1
            # the big side stayed local: fewer bytes than repartition
            assert (
                st["bytes_tunneled"]
                < plain.last_query["shuffle"]["bytes_tunneled"]
            )
        finally:
            adaptive.close()
            plain.close()

    def test_stage_boundary_replan_on_join_chain(self, fleet):
        sess, servers = fleet
        q = (
            "select count(*), sum(w) from jl join jm on jl.a = jm.a "
            "join jr on jm.c = jr.c"
        )
        plan = _plan(sess, q)
        adaptive = _sched(
            sess, servers, shuffle_dag="always",
            shuffle_broadcast_rows=50,
        )
        plain = _sched(sess, servers, shuffle_dag="always")
        try:
            kind, cut = adaptive._choose_cut(plan)
            assert kind == "dag" and len(cut.stages) == 2
            before = _decisions("broadcast-switch")
            _c, r1 = adaptive.execute_plan(plan)
            _c, r2 = plain.execute_plan(plan)
            assert r1 == r2
            stages = adaptive.last_query["shuffle_stages"]
            # stage 1 switched mid-query from stage 0's observed held
            # rows (6 << the 60-row static estimate)
            assert "broadcast-switch" in (stages[1].get("adaptive") or [])
            assert sorted(stages[1]["modes"]) == ["broadcast", "local"]
            assert _decisions("broadcast-switch") >= before + 1
            total = lambda lq: sum(
                s["bytes_tunneled"] for s in lq["shuffle_stages"]
            )
            assert total(adaptive.last_query) < total(plain.last_query)
        finally:
            adaptive.close()
            plain.close()

    def test_probe_skipped_when_groupby_cannot_salt(self, fleet):
        """A DISTINCT aggregate has no salted partial/final variant —
        the only adaptive action a group-by probe can feed is
        impossible, so the probe round (produce-and-cache + an RPC
        round per attempt) must not run at all."""
        sess, servers = fleet
        q = "select b, count(distinct a) from gz group by b order by b"
        plan = _plan(sess, q)
        sched = _sched(
            sess, servers, shuffle_skew_ratio=1.4,
            shuffle_skew_salt_k=2,
        )
        plain = _sched(sess, servers, shuffle_skew_ratio=0.0)
        try:
            calls = []
            orig = sched._probe_stage

            def spy(*a, **kw):
                calls.append(1)
                return orig(*a, **kw)

            sched._probe_stage = spy
            _c, r1 = sched.execute_plan(plan)
            _c, r2 = plain.execute_plan(plan)
            assert r1 == r2
            assert not calls
            # a decomposable aggregate on the same shape still probes
            plan2 = _plan(
                sess, "select b, count(*) from gz group by b order by b"
            )
            _c, _r = sched.execute_plan(plan2)
            assert calls
        finally:
            sched.close()
            plain.close()

    def test_replan_token_persists_across_retry_attempts(self, fleet):
        """A retried DAG attempt re-derives the SAME flipped modes
        from the stage's already-mutated sides — no NEW decision is
        taken, but the stashed token must still render on the rebuilt
        stage summary (adaptive= has to agree with the modes the
        workers actually ran) and the counter must move exactly
        once."""
        from tidb_tpu.planner import logical as L

        sess, servers = fleet
        q = (
            "select count(*), sum(w) from jl join jm on jl.a = jm.a "
            "join jr on jm.c = jr.c"
        )
        plan = _plan(sess, q)
        sched = _sched(
            sess, servers, shuffle_dag="always",
            shuffle_broadcast_rows=50,
        )
        try:
            kind, cut = sched._choose_cut(plan)
            assert kind == "dag" and len(cut.stages) == 2
            stg = cut.stages[1]
            held_stage = next(
                s.template.stage for s in stg.sides
                if isinstance(s.template, L.StageInput)
            )
            infos = [{"stage": held_stage, "held_rows": 3}]
            before = _decisions("broadcast-switch")
            t1 = sched._stage_replan(stg, infos)
            assert t1 == ["broadcast-switch"]
            assert _decisions("broadcast-switch") == before + 1
            # attempt 2: same observations, modes already flipped
            t2 = sched._stage_replan(stg, infos)
            assert t2 == ["broadcast-switch"]
            assert _decisions("broadcast-switch") == before + 1
        finally:
            sched.close()

    def test_feedback_changes_choice_on_second_run(self, fleet):
        from tidb_tpu.planner.cardinality import CARD_FEEDBACK
        from tidb_tpu.utils.metrics import sql_digest

        sess, servers = fleet
        q = "select count(*) from jl join jr on jl.a = jr.w where jl.a < 2"
        digest = sql_digest(q)
        CARD_FEEDBACK.reset()
        plan = _plan(sess, q)
        sched = _sched(
            sess, servers, aqe_feedback=True, shuffle_broadcast_rows=10,
        )
        try:
            before = _decisions("feedback")
            kind, cut = sched._choose_cut(plan, digest=digest)
            assert [s.mode for s in cut.sides] == ["hash", "hash"]
            _c, r1 = sched.execute_plan(
                plan, cut_hint=(kind, cut), digest=digest
            )
            # the observed side rows were recorded for this digest
            assert CARD_FEEDBACK.sides_for(digest)
            kind2, cut2 = sched._choose_cut(plan, digest=digest)
            assert sorted(s.mode for s in cut2.sides) == [
                "broadcast", "local",
            ]
            assert getattr(cut2, "_aqe_tokens", None) == ["feedback"]
            assert _decisions("feedback") == before + 1
            _c, r2 = sched.execute_plan(
                plan, cut_hint=(kind2, cut2), digest=digest
            )
            assert r1 == r2
            assert sched.last_query["shuffle"]["adaptive"] == ["feedback"]
        finally:
            sched.close()

    def test_partition_rows_histogram_moves(self, fleet):
        from tidb_tpu.utils.metrics import REGISTRY

        sess, servers = fleet

        def count():
            return sum(
                v for n, _k, v in REGISTRY.rows()
                if n.startswith("tidbtpu_shuffle_partition_rows_count")
            )

        sched = _sched(sess, servers)
        try:
            c0 = count()
            sched.execute_plan(
                _plan(sess, "select b, count(*) from gz group by b")
            )
            assert count() >= c0 + 2  # one observation per partition
        finally:
            sched.close()

    def test_routed_statement_records_est_act(self, fleet):
        from tidb_tpu.utils.metrics import STMT_SUMMARY, sql_digest

        sess, servers = fleet
        sched = _sched(sess, servers)
        sess.attach_dcn_scheduler(sched)
        try:
            q = "select b, count(*) from gz group by b order by b"
            sess.execute(q)
            ent = next(
                e for e in STMT_SUMMARY.rows_full()
                if e["digest_text"] == sql_digest(q)
            )
            assert ent["act_rows"] == 11.0
            assert ent["est_rows"] > 0
            assert ent["card_divergence"] >= 1.0
        finally:
            sess.attach_dcn_scheduler(None)
            sched.close()

    def test_sysvars_resolve_and_retune_live(self, fleet):
        sess, servers = fleet
        sess.execute("set global tidb_tpu_shuffle_skew_ratio = 2.5")
        sess.execute("set global tidb_tpu_aqe_feedback = ON")
        try:
            sched = _sched(sess, servers)
            try:
                # ctor resolves unset args from the globals
                assert sched.shuffle_skew_ratio == 2.5
                assert sched.aqe_feedback is True
                # live SET re-tunes an ATTACHED scheduler
                sess.attach_dcn_scheduler(sched)
                sess.execute(
                    "set global tidb_tpu_shuffle_skew_ratio = 3.5"
                )
                sess.execute(
                    "set global tidb_tpu_shuffle_skew_salt_k = 8"
                )
                sess.execute("set global tidb_tpu_aqe_feedback = OFF")
                sess.execute(
                    "set global tidb_tpu_aqe_replan_ratio = 9.0"
                )
                assert sched.shuffle_skew_ratio == 3.5
                assert sched.shuffle_skew_salt_k == 8
                assert sched.aqe_feedback is False
                assert sched.aqe_replan_ratio == 9.0
                # session-scoped SET errors loudly (GLOBAL-only)
                with pytest.raises(Exception):
                    sess.execute("set tidb_tpu_aqe_feedback = ON")
            finally:
                sess.attach_dcn_scheduler(None)
                sched.close()
        finally:
            sess.execute("set global tidb_tpu_shuffle_skew_ratio = 0.0")
            sess.execute("set global tidb_tpu_aqe_feedback = OFF")
            sess.execute("set global tidb_tpu_shuffle_skew_salt_k = 4")
            sess.execute("set global tidb_tpu_aqe_replan_ratio = 4.0")

    def test_salted_stage_survives_worker_loss(self, fleet):
        """replan-crash, in-process: the salted task's reply is lost
        on its first dispatch (drop at aqe/switched-stage); the
        coordinator verifies the suspect (alive: in-process drop is a
        transport loss, not a death), retries the WHOLE stage — probe
        round included — and reaches parity with salting re-decided."""
        from tidb_tpu.server.engine_rpc import DropConnection

        sess, servers = fleet
        q = "select b, count(*), sum(a) from gz group by b order by b"
        plan = _plan(sess, q)
        plain = _sched(sess, servers, shuffle_skew_ratio=0.0)
        # the dropped task never produces, so the healthy partition's
        # consumer detects the loss only by wait expiry — a short
        # loopback budget keeps the fault path from idling 30s
        salted = _sched(
            sess, servers, shuffle_skew_ratio=1.4,
            shuffle_skew_salt_k=2, shuffle_wait_timeout_s=5.0,
        )
        try:
            exp = plain.execute_plan(plan)[1]
            failpoint.enable(
                "aqe/switched-stage",
                failpoint.after_n(1, DropConnection("chaos")),
            )
            _c, got = salted.execute_plan(plan)
            assert got == exp
            st = salted.last_query["shuffle"]
            assert st["attempts"] >= 2
            assert st["adaptive"] == ["salted:2"]
        finally:
            failpoint.disable("aqe/switched-stage")
            plain.close()
            salted.close()
