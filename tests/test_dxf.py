"""DXF: distributed background task framework.

Reference: pkg/disttask/framework — scheduler/executor state machines
(proto/task.go:44, proto/step.go), system-table persistence
(framework/storage), subtask rebalance on executor death, and the
import/add-index pipelines built on it (pkg/disttask/importinto,
pkg/ddl/backfilling_dist_*).
"""

import json
import time

import pytest

import tidb_tpu.dxf.tasks  # noqa: F401  (registers built-in task types)
from tidb_tpu.dxf import (
    SubtaskState,
    TaskExecutor,
    TaskManager,
    TaskState,
    register_task_type,
)
from tidb_tpu.dxf.framework import HEARTBEAT_TTL_S
from tidb_tpu.session.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create table t (a int, b varchar(8))")
    s.execute(
        "insert into t values "
        + ",".join(f"({i % 9},'v{i % 4}')" for i in range(500))
    )
    return s


def test_distributed_analyze(sess):
    m = TaskManager(sess.catalog)
    tid = m.submit("analyze", {"db": "test", "table": "t"})
    assert m.run_to_completion(tid, executors=3) == "succeed"
    t = sess.catalog.table("test", "t")
    assert sorted(t.stats) == ["a", "b"] and t.stats["a"].ndv == 9


def test_chunked_import_exact(sess, tmp_path):
    path = str(tmp_path / "data.tsv")
    with open(path, "w") as f:
        for i in range(5000):
            f.write(f"{i}\tx{i % 7}\n")
    sess.execute("create table imp (a int, b varchar(8))")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "imp", "path": path, "chunk_bytes": 8192},
    )
    assert m.run_to_completion(tid, executors=4) == "succeed"
    assert sess.execute("select count(*), sum(a) from imp").rows == [
        (5000, sum(range(5000)))
    ]


def test_index_backfill(sess):
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "index_backfill",
        {"db": "test", "table": "t", "column": "a", "index": "ia"},
    )
    assert m.run_to_completion(tid) == "succeed"
    assert sess.catalog.table("test", "t").indexes == {"ia": ["a"]}


def test_owner_failover_resume(sess, tmp_path):
    path = str(tmp_path / "data.tsv")
    with open(path, "w") as f:
        for i in range(3000):
            f.write(f"{i}\ty\n")
    sess.execute("create table imp2 (a int, b varchar(8))")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "imp2", "path": path, "chunk_bytes": 8192},
    )
    m.schedule_once()  # plan subtasks
    TaskExecutor(m, "solo").run_one()  # partially execute, then "crash"
    m2 = TaskManager(sess.catalog)  # new owner over the same store
    assert m2.task_state(tid) == TaskState.RUNNING.value
    assert m2.run_to_completion(tid, executors=2) == "succeed"
    assert sess.execute("select count(*) from imp2").rows == [(3000,)]


def test_failed_subtask_fails_task(sess):
    def bad_run(meta, catalog):
        raise RuntimeError("boom")

    register_task_type(
        "always_fails", lambda m, c: [{"i": 1}, {"i": 2}], bad_run
    )
    m = TaskManager(sess.catalog)
    tid = m.submit("always_fails", {})
    assert m.run_to_completion(tid) == "failed"
    assert "boom" in m.tasks[tid]["error"]


def test_dead_executor_rebalance(sess, monkeypatch):
    """A claimed-but-silent subtask goes back to the pool once the
    heartbeat expires (scheduler-side failure detection)."""
    monkeypatch.setattr("tidb_tpu.dxf.framework.HEARTBEAT_TTL_S", 0.05)
    done = []
    register_task_type(
        "rebal",
        lambda m, c: [{"i": 0}],
        lambda m, c: (done.append(m["i"]), {"ok": 1})[1],
    )
    m = TaskManager(sess.catalog)
    tid = m.submit("rebal", {})
    m.schedule_once()
    # dead executor claims the subtask and never reports
    claimed = m.claim_subtask("dead-node")
    assert claimed is not None
    time.sleep(0.1)
    m.schedule_once()  # heartbeat expired -> back to pending
    sid = claimed["id"]
    assert m.subtasks[sid]["state"] == SubtaskState.PENDING.value
    assert m.run_to_completion(tid) == "succeed"
    assert done == [0]


def test_system_tables_queryable(sess):
    m = TaskManager(sess.catalog)
    tid = m.submit("analyze", {"db": "test", "table": "t"})
    m.run_to_completion(tid, executors=2)
    rows = sess.execute(
        "select type, state from mysql.tidb_global_task"
    ).rows
    assert ("analyze", "succeed") in rows
    sub = sess.execute(
        "select count(*) from mysql.tidb_background_subtask "
        "where state = 'succeed'"
    ).rows
    assert sub[0][0] >= 2  # one per column


def test_bad_planner_fails_task_not_scheduler(sess):
    m = TaskManager(sess.catalog)
    bad = m.submit(
        "import", {"db": "test", "table": "t", "path": "/no/such/file"}
    )
    good = m.submit("analyze", {"db": "test", "table": "t"})
    assert m.run_to_completion(good, executors=2) == "succeed"
    assert m.task_state(bad) == TaskState.FAILED.value
    assert "planner" in m.tasks[bad]["error"]


def test_empty_import_succeeds(sess, tmp_path):
    path = tmp_path / "empty.tsv"
    path.write_text("")
    sess.execute("create table emp (a int)")
    m = TaskManager(sess.catalog)
    tid = m.submit("import", {"db": "test", "table": "emp", "path": str(path)})
    assert m.run_to_completion(tid) == "succeed"


def test_multibyte_chunk_boundaries(sess, tmp_path):
    path = tmp_path / "uni.tsv"
    with open(path, "w", encoding="utf-8") as f:
        for i in range(2000):
            f.write(f"{i}\té中{i % 5}\n")  # multi-byte strings
    sess.execute("create table uni (a int, b varchar(16))")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "uni", "path": str(path), "chunk_bytes": 4096},
    )
    assert m.run_to_completion(tid, executors=3) == "succeed"
    assert sess.execute("select count(*), sum(a) from uni").rows == [
        (2000, sum(range(2000)))
    ]


def test_slow_subtask_not_double_executed(sess, monkeypatch):
    """The heartbeat ticker keeps long runners alive past the TTL, and
    fencing drops a late report from a rebalanced executor."""
    monkeypatch.setattr("tidb_tpu.dxf.framework.HEARTBEAT_TTL_S", 0.2)
    runs = []

    def slow_run(meta, catalog):
        runs.append(meta["i"])
        time.sleep(0.6)  # 3x the TTL
        return {"ok": 1}

    register_task_type("slow", lambda m, c: [{"i": 0}], slow_run)
    m = TaskManager(sess.catalog)
    tid = m.submit("slow", {})
    assert m.run_to_completion(tid, executors=2, timeout_s=30) == "succeed"
    assert runs == [0]  # ran exactly once despite TTL << runtime


def test_backfill_merges_subtask_runs(sess, tmp_path):
    """The per-block sorted runs are REAL work: the finalizer k-way
    merges them into the installed derived-index cache, byte-identical
    to a fresh argsort (ADMIN CHECK cross-validates the same way)."""
    import numpy as np

    sess.execute("create table bf (k int, v int)")
    # several appends -> several blocks, interleaved values + NULLs
    for lo in (300, 0, 600):
        sess.execute(
            "insert into bf values "
            + ", ".join(f"({(lo + i) % 701}, {i})" for i in range(250))
        )
    sess.execute("insert into bf values (null, 1), (null, 2)")
    t = sess.catalog.table("test", "bf")
    assert len(t.blocks()) >= 4
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "index_backfill",
        {"db": "test", "table": "bf", "column": "k", "index": "ik",
         "spill_dir": str(tmp_path)},
    )
    assert m.run_to_completion(tid, executors=3) == "succeed"
    t = sess.catalog.table("test", "bf")
    assert t.indexes["ik"] == ["k"] and t.index_state("ik") == "public"
    # the merged install must be present for the CURRENT version and
    # agree exactly with a fresh recompute
    ent = t._idx_cache.get((t.version, "k"))
    assert ent is not None, "merge did not install the index cache"
    svals, perm, nvalid = ent
    data = np.concatenate([b.columns["k"].data for b in t.blocks()])
    valid = np.concatenate([b.columns["k"].valid for b in t.blocks()])
    fresh = np.lexsort((data, np.where(valid, 0, 1)))
    assert nvalid == int(valid.sum())
    assert np.array_equal(data[fresh], svals)
    assert np.array_equal(data[perm], svals)  # perm consistent too
    sess.execute("admin check index bf ik")  # bookkeeping cross-check
    # and the index actually serves queries
    assert sess.execute("select v from bf where k = 700").rows != []


def test_import_ingests_sorted_index(sess, tmp_path):
    """IMPORT INTO with an existing index: subtask runs merge into an
    installed cache — sorted-index-ready with no post-hoc argsort."""
    import numpy as np

    path = str(tmp_path / "d.tsv")
    rows = [(i * 37) % 9991 for i in range(6000)]
    with open(path, "w") as f:
        for i, k in enumerate(rows):
            f.write(f"{k}\t{i}\n")
    sess.execute("create table si (k int, v int)")
    sess.execute("create index ik on si (k)")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "si", "path": path, "chunk_bytes": 8192,
         "spill_dir": str(tmp_path)},
    )
    assert m.run_to_completion(tid, executors=4) == "succeed"
    t = sess.catalog.table("test", "si")
    assert sess.execute("select count(*) from si").rows == [(6000,)]
    ent = t._idx_cache.get((t.version, "k"))
    assert ent is not None, "import did not ingest the sorted index"
    svals, perm, nvalid = ent
    data = np.concatenate([b.columns["k"].data for b in t.blocks()])
    assert nvalid == 6000 and np.array_equal(np.sort(data), svals)
    sess.execute("admin check index si ik")
    assert sess.execute("select count(*) from si where k = 37").rows[0][0] >= 1


def test_extsort_merge_matches_lexsort():
    """Unit: k-way merge == one global lexsort, ties + NULLs included."""
    import numpy as np

    from tidb_tpu.dxf import extsort

    rng = np.random.default_rng(7)
    chunks = []
    off = 0
    all_data, all_valid = [], []
    for n in (17, 1, 64, 33):
        data = rng.integers(0, 9, n)
        valid = rng.random(n) > 0.2
        chunks.append(extsort.sort_run(data, valid, off))
        all_data.append(data)
        all_valid.append(valid)
        off += n
    merged = extsort.merge_runs(chunks)
    svals, rank, rows = merged
    data = np.concatenate(all_data)
    valid = np.concatenate(all_valid)
    ref = np.lexsort((data, np.where(valid, 0, 1)))
    assert np.array_equal(rows, ref)  # STABLE: exact permutation match
    assert np.array_equal(svals, data[ref])


def test_backfill_unknown_column_fails_cleanly(sess):
    sess.execute("create table bfc (a int)")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "index_backfill",
        {"db": "test", "table": "bfc", "column": "nope", "index": "ix"},
    )
    state = m.run_to_completion(tid, executors=1)
    assert state in ("failed", "reverted")
    t = sess.catalog.table("test", "bfc")
    assert "ix" not in t.indexes  # no phantom write_only registration


def test_backfill_existing_index_refused(sess):
    sess.execute("create table bfe (a int)")
    sess.execute("create index ia on bfe (a)")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "index_backfill",
        {"db": "test", "table": "bfe", "column": "a", "index": "ia"},
    )
    assert m.run_to_completion(tid, executors=1) in ("failed", "reverted")
    t = sess.catalog.table("test", "bfe")
    # the pre-existing PUBLIC index is untouched
    assert t.indexes["ia"] == ["a"] and t.index_state("ia") == "public"


def test_backfill_failed_subtask_reverts_registration(sess, tmp_path):
    from tidb_tpu.utils import failpoint

    sess.execute("create table bff (a int)")
    sess.execute("insert into bff values (1), (2)")
    m = TaskManager(sess.catalog)

    # make every run raise: the reverter must clear the registration
    import tidb_tpu.dxf.tasks as tasks_mod

    orig = tasks_mod._backfill_run

    def bad_run(meta, catalog):
        raise OSError("disk full")

    register_task_type(
        "index_backfill", tasks_mod._backfill_plan, bad_run,
        tasks_mod._backfill_finalize, reverter=tasks_mod._backfill_revert,
    )
    try:
        tid = m.submit(
            "index_backfill",
            {"db": "test", "table": "bff", "column": "a", "index": "iz",
             "spill_dir": str(tmp_path)},
        )
        assert m.run_to_completion(tid, executors=1) in ("failed", "reverted")
    finally:
        register_task_type(
            "index_backfill", tasks_mod._backfill_plan, orig,
            tasks_mod._backfill_finalize,
            reverter=tasks_mod._backfill_revert,
        )
    t = sess.catalog.table("test", "bff")
    assert "iz" not in t.indexes and "iz" not in t.index_states


def test_import_ingests_string_index(sess, tmp_path):
    """Round-5 widening: dict-coded (string) runs remap monotonically to
    the aligned table dictionary — no post-hoc argsort."""
    import numpy as np

    path = str(tmp_path / "s.tsv")
    with open(path, "w") as f:
        for i in range(4000):
            f.write(f"w{(i * 13) % 997:04d}\t{i}\n")
    sess.execute("create table ss (s varchar(10), v int)")
    sess.execute("create index isx on ss (s)")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "ss", "path": path, "chunk_bytes": 8192,
         "spill_dir": str(tmp_path)},
    )
    assert m.run_to_completion(tid, executors=4) == "succeed"
    t = sess.catalog.table("test", "ss")
    ent = t._idx_cache.get((t.version, "s"))
    assert ent is not None, "string-index runs were not ingested"
    svals, _perm, nvalid = ent
    data = np.concatenate([b.columns["s"].data for b in t.blocks()])
    assert nvalid == 4000 and np.array_equal(np.sort(data), svals)
    assert sess.execute(
        "select count(*) from ss where s = 'w0013'"
    ).rows[0][0] >= 1


def test_import_ingests_partitioned_composite_string_index(sess, tmp_path):
    """The TB-scale shape the pipeline exists for (VERDICT r4 item #6):
    IMPORT INTO a partitioned table with a composite string index
    installs merged indexes with no post-hoc argsort (asserted via the
    derived caches being warm at the landed version)."""
    import numpy as np

    path = str(tmp_path / "p.tsv")
    with open(path, "w") as f:
        for i in range(5000):
            f.write(f"{i % 1000}\tk{(i * 7) % 313:03d}\t{i}\n")
    sess.execute(
        "create table pc (r int, s varchar(8), v int) "
        "partition by range (r) ("
        "partition p0 values less than (300), "
        "partition p1 values less than (700), "
        "partition p2 values less than maxvalue)"
    )
    sess.execute("create index ic on pc (s, v)")
    sess.execute("create index ir on pc (v)")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "pc", "path": path, "chunk_bytes": 16384,
         "spill_dir": str(tmp_path)},
    )
    assert m.run_to_completion(tid, executors=4) == "succeed"
    t = sess.catalog.table("test", "pc")
    assert sess.execute("select count(*) from pc").rows == [(5000,)]
    # single-col index ingested across the partition split
    ent = t._idx_cache.get((t.version, "v"))
    assert ent is not None, "partitioned single-col runs not ingested"
    svals, _perm, nvalid = ent
    data = np.concatenate([b.columns["v"].data for b in t.blocks()])
    assert nvalid == 5000 and np.array_equal(np.sort(data), svals)
    # composite (string, int) cache installed and correct
    comp = getattr(t, "_comp_cache", {}).get(("s", "v"))
    assert comp is not None, "composite runs not ingested"
    uids, view = comp
    blocks = [
        b for b in t.blocks() if all(c in b.columns for c in ("s", "v"))
    ]
    assert uids == tuple(b.uid for b in blocks)
    mats = [
        m2 for b in blocks
        if len(m2 := t._key_matrix(b.columns, ("s", "v")))
    ]
    want = np.sort(t._rows_view(np.concatenate(mats)))
    assert np.array_equal(view, want)
    # and the composite uniqueness path consumes the warm cache
    assert sess.execute(
        "select count(*) from pc where s = 'k007'"
    ).rows[0][0] >= 1


def test_import_string_index_into_prepopulated_table(sess, tmp_path):
    """Mixed ingest path: staged (remapped) runs merge with delta-sorted
    runs over PRE-EXISTING dict-coded blocks, across a mid-import
    dictionary merge."""
    import numpy as np

    sess.execute("create table pp (s varchar(10), v int)")
    sess.execute("create index ip on pp (s)")
    sess.execute(
        "insert into pp values ('zz', -1), ('mm', -2), ('aa', -3)"
    )
    path = str(tmp_path / "pp.tsv")
    with open(path, "w") as f:
        for i in range(3000):
            f.write(f"b{(i * 11) % 577:03d}\t{i}\n")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "pp", "path": path, "chunk_bytes": 8192,
         "spill_dir": str(tmp_path)},
    )
    assert m.run_to_completion(tid, executors=4) == "succeed"
    t = sess.catalog.table("test", "pp")
    assert sess.execute("select count(*) from pp").rows == [(3003,)]
    ent = t._idx_cache.get((t.version, "s"))
    assert ent is not None, "mixed-path ingest did not install"
    svals, _perm, nvalid = ent
    data = np.concatenate([b.columns["s"].data for b in t.blocks()])
    assert nvalid == 3003 and np.array_equal(np.sort(data), svals)
    assert sess.execute(
        "select v from pp where s = 'zz'"
    ).rows == [(-1,)]


def test_import_list_partition_null_routing(sess, tmp_path):
    """LIST tables route NULL keys to the NULL-listing partition; the
    stage-time run split must mirror that or staged runs pair with the
    wrong landed blocks (round-5 review finding)."""
    import numpy as np

    path = str(tmp_path / "l.tsv")
    with open(path, "w") as f:
        for i in range(300):
            r = ["1", "2", "\\N"][i % 3]
            f.write(f"{r}\t{i}\n")
    sess.execute(
        "create table lt (r int, v int) partition by list (r) ("
        "partition a values in (1), "
        "partition b values in (2), "
        "partition nulls values in (null))"
    )
    sess.execute("create index iv on lt (v)")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "lt", "path": path,
         "chunk_bytes": 2048, "spill_dir": str(tmp_path)},
    )
    assert m.run_to_completion(tid, executors=2) == "succeed"
    t = sess.catalog.table("test", "lt")
    assert sess.execute("select count(*) from lt").rows == [(300,)]
    assert sess.execute(
        "select count(*) from lt where r is null"
    ).rows == [(100,)]
    # any ingested index must order the REAL rows (wrong-block pairing
    # would install a permutation of the wrong values)
    ent = t._idx_cache.get((t.version, "v"))
    if ent is not None:
        svals, _perm, nvalid = ent
        data = np.concatenate([b.columns["v"].data for b in t.blocks()])
        assert nvalid == 300 and np.array_equal(np.sort(data), svals)
    sess.execute("admin check table lt")  # raises on any corruption
