"""DXF: distributed background task framework.

Reference: pkg/disttask/framework — scheduler/executor state machines
(proto/task.go:44, proto/step.go), system-table persistence
(framework/storage), subtask rebalance on executor death, and the
import/add-index pipelines built on it (pkg/disttask/importinto,
pkg/ddl/backfilling_dist_*).
"""

import json
import time

import pytest

import tidb_tpu.dxf.tasks  # noqa: F401  (registers built-in task types)
from tidb_tpu.dxf import (
    SubtaskState,
    TaskExecutor,
    TaskManager,
    TaskState,
    register_task_type,
)
from tidb_tpu.dxf.framework import HEARTBEAT_TTL_S
from tidb_tpu.session.session import Session


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create table t (a int, b varchar(8))")
    s.execute(
        "insert into t values "
        + ",".join(f"({i % 9},'v{i % 4}')" for i in range(500))
    )
    return s


def test_distributed_analyze(sess):
    m = TaskManager(sess.catalog)
    tid = m.submit("analyze", {"db": "test", "table": "t"})
    assert m.run_to_completion(tid, executors=3) == "succeed"
    t = sess.catalog.table("test", "t")
    assert sorted(t.stats) == ["a", "b"] and t.stats["a"].ndv == 9


def test_chunked_import_exact(sess, tmp_path):
    path = str(tmp_path / "data.tsv")
    with open(path, "w") as f:
        for i in range(5000):
            f.write(f"{i}\tx{i % 7}\n")
    sess.execute("create table imp (a int, b varchar(8))")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "imp", "path": path, "chunk_bytes": 8192},
    )
    assert m.run_to_completion(tid, executors=4) == "succeed"
    assert sess.execute("select count(*), sum(a) from imp").rows == [
        (5000, sum(range(5000)))
    ]


def test_index_backfill(sess):
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "index_backfill",
        {"db": "test", "table": "t", "column": "a", "index": "ia"},
    )
    assert m.run_to_completion(tid) == "succeed"
    assert sess.catalog.table("test", "t").indexes == {"ia": ["a"]}


def test_owner_failover_resume(sess, tmp_path):
    path = str(tmp_path / "data.tsv")
    with open(path, "w") as f:
        for i in range(3000):
            f.write(f"{i}\ty\n")
    sess.execute("create table imp2 (a int, b varchar(8))")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "imp2", "path": path, "chunk_bytes": 8192},
    )
    m.schedule_once()  # plan subtasks
    TaskExecutor(m, "solo").run_one()  # partially execute, then "crash"
    m2 = TaskManager(sess.catalog)  # new owner over the same store
    assert m2.task_state(tid) == TaskState.RUNNING.value
    assert m2.run_to_completion(tid, executors=2) == "succeed"
    assert sess.execute("select count(*) from imp2").rows == [(3000,)]


def test_failed_subtask_fails_task(sess):
    def bad_run(meta, catalog):
        raise RuntimeError("boom")

    register_task_type(
        "always_fails", lambda m, c: [{"i": 1}, {"i": 2}], bad_run
    )
    m = TaskManager(sess.catalog)
    tid = m.submit("always_fails", {})
    assert m.run_to_completion(tid) == "failed"
    assert "boom" in m.tasks[tid]["error"]


def test_dead_executor_rebalance(sess, monkeypatch):
    """A claimed-but-silent subtask goes back to the pool once the
    heartbeat expires (scheduler-side failure detection)."""
    monkeypatch.setattr("tidb_tpu.dxf.framework.HEARTBEAT_TTL_S", 0.05)
    done = []
    register_task_type(
        "rebal",
        lambda m, c: [{"i": 0}],
        lambda m, c: (done.append(m["i"]), {"ok": 1})[1],
    )
    m = TaskManager(sess.catalog)
    tid = m.submit("rebal", {})
    m.schedule_once()
    # dead executor claims the subtask and never reports
    claimed = m.claim_subtask("dead-node")
    assert claimed is not None
    time.sleep(0.1)
    m.schedule_once()  # heartbeat expired -> back to pending
    sid = claimed["id"]
    assert m.subtasks[sid]["state"] == SubtaskState.PENDING.value
    assert m.run_to_completion(tid) == "succeed"
    assert done == [0]


def test_system_tables_queryable(sess):
    m = TaskManager(sess.catalog)
    tid = m.submit("analyze", {"db": "test", "table": "t"})
    m.run_to_completion(tid, executors=2)
    rows = sess.execute(
        "select type, state from mysql.tidb_global_task"
    ).rows
    assert ("analyze", "succeed") in rows
    sub = sess.execute(
        "select count(*) from mysql.tidb_background_subtask "
        "where state = 'succeed'"
    ).rows
    assert sub[0][0] >= 2  # one per column


def test_bad_planner_fails_task_not_scheduler(sess):
    m = TaskManager(sess.catalog)
    bad = m.submit(
        "import", {"db": "test", "table": "t", "path": "/no/such/file"}
    )
    good = m.submit("analyze", {"db": "test", "table": "t"})
    assert m.run_to_completion(good, executors=2) == "succeed"
    assert m.task_state(bad) == TaskState.FAILED.value
    assert "planner" in m.tasks[bad]["error"]


def test_empty_import_succeeds(sess, tmp_path):
    path = tmp_path / "empty.tsv"
    path.write_text("")
    sess.execute("create table emp (a int)")
    m = TaskManager(sess.catalog)
    tid = m.submit("import", {"db": "test", "table": "emp", "path": str(path)})
    assert m.run_to_completion(tid) == "succeed"


def test_multibyte_chunk_boundaries(sess, tmp_path):
    path = tmp_path / "uni.tsv"
    with open(path, "w", encoding="utf-8") as f:
        for i in range(2000):
            f.write(f"{i}\té中{i % 5}\n")  # multi-byte strings
    sess.execute("create table uni (a int, b varchar(16))")
    m = TaskManager(sess.catalog)
    tid = m.submit(
        "import",
        {"db": "test", "table": "uni", "path": str(path), "chunk_bytes": 4096},
    )
    assert m.run_to_completion(tid, executors=3) == "succeed"
    assert sess.execute("select count(*), sum(a) from uni").rows == [
        (2000, sum(range(2000)))
    ]


def test_slow_subtask_not_double_executed(sess, monkeypatch):
    """The heartbeat ticker keeps long runners alive past the TTL, and
    fencing drops a late report from a rebalanced executor."""
    monkeypatch.setattr("tidb_tpu.dxf.framework.HEARTBEAT_TTL_S", 0.2)
    runs = []

    def slow_run(meta, catalog):
        runs.append(meta["i"])
        time.sleep(0.6)  # 3x the TTL
        return {"ok": 1}

    register_task_type("slow", lambda m, c: [{"i": 0}], slow_run)
    m = TaskManager(sess.catalog)
    tid = m.submit("slow", {})
    assert m.run_to_completion(tid, executors=2, timeout_s=30) == "succeed"
    assert runs == [0]  # ran exactly once despite TTL << runtime
