"""Memory governance, kill switch, and failpoint coverage.

Reference: pkg/util/memory/tracker.go:74 + action.go:30 (quota with
escalation), pkg/util/sqlkiller/sqlkiller.go:41 (kill safepoints),
pingcap/failpoint (587 sites). VERDICT round-1 criteria: an over-quota
query fails with a tracker report; injection tests exercise exchange and
commit paths.
"""

import threading
import time

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog
from tidb_tpu.utils import failpoint
from tidb_tpu.utils.sqlkiller import QueryKilled


@pytest.fixture()
def sess():
    s = Session(Catalog())
    yield s
    failpoint.disable_all()


def _mk(sess, n=512):
    sess.execute("create table t (a bigint, b bigint)")
    rows = ",".join(f"({i}, {i % 7})" for i in range(n))
    sess.execute(f"insert into t values {rows}")


def test_over_quota_query_rejected_with_report(sess):
    _mk(sess)
    sess.execute("set tidb_mem_quota_query = 16777216")  # 16 MiB floor
    sess.must_query("select count(*) from t")  # fits
    # force a plan whose admission bytes blow the quota: a cross join
    # tile of 512x512 rows x many columns still fits; shrink quota via
    # the executor knob directly to hit the admission path determin-
    # istically (sysvar floor is 16 MiB)
    sess.executor.quota_bytes = 20_000
    from tidb_tpu.planner.physical import ExecError

    with pytest.raises(ExecError, match="tracker report"):
        sess.executor.run(_plan(sess, "select a, count(*) from t group by a"))
    sess.executor.quota_bytes = None


def _plan(sess, sql):
    from tidb_tpu.parser import parse
    from tidb_tpu.planner import build_query

    st = parse(sql)
    st = st[0] if isinstance(st, list) else st
    return build_query(st, sess.catalog, sess.db, sess._scalar_subquery)


def test_kill_query_from_other_thread(sess):
    _mk(sess)
    # hold the statement at a failpoint long enough to kill it
    release = threading.Event()

    def stall():
        sess.killer.kill()
        return None

    failpoint.enable("executor/before-discover", stall)
    try:
        with pytest.raises(QueryKilled):
            sess.execute("select sum(a) from t where b = 3")
    finally:
        failpoint.disable("executor/before-discover")
    # engine recovers: next statement runs normally
    r = sess.must_query("select count(*) from t")
    assert r.rows == [(512,)]


def test_failpoint_commit_conflict_path(sess):
    _mk(sess, 8)

    class Boom(RuntimeError):
        pass

    failpoint.enable("session/commit-apply", Boom)
    sess.execute("begin")
    sess.execute("insert into t values (1000, 0)")
    with pytest.raises(Boom):
        sess.execute("commit")
    failpoint.disable("session/commit-apply")
    # txn state was consumed; table unchanged by the failed apply
    r = sess.must_query("select count(*) from t")
    assert r.rows == [(8,)]


def test_failpoint_scan_and_dml_sites(sess):
    _mk(sess, 8)

    class ScanBoom(RuntimeError):
        pass

    failpoint.enable("storage/scan", ScanBoom)
    with pytest.raises(ScanBoom):
        sess.execute("select * from t")
    failpoint.disable("storage/scan")

    class InsBoom(RuntimeError):
        pass

    failpoint.enable("dml/insert", InsBoom)
    with pytest.raises(InsBoom):
        sess.execute("insert into t values (9, 9)")
    failpoint.disable("dml/insert")
    assert sess.must_query("select count(*) from t").rows == [(8,)]


def test_failpoint_site_inventory():
    """At least 20 named inject() sites exist across the engine."""
    import pathlib
    import re

    root = pathlib.Path(__file__).resolve().parent.parent / "tidb_tpu"
    sites = set()
    for p in root.rglob("*.py"):
        for m in re.finditer(r'inject\("([^"]+)"', p.read_text()):
            sites.add(m.group(1))
    assert len(sites) >= 20, sorted(sites)


class TestRound3Failpoints:
    """Fault injection at the round-3 sites (VERDICT round-2 weak #8:
    storage GC, lock manager, FK cascades, persistence writes)."""

    def test_persist_crash_mid_backup_resumes(self, tmp_path):
        from tidb_tpu.storage import Catalog
        from tidb_tpu.storage.persist import load_catalog, save_catalog

        cat = Catalog()
        s = Session(cat, db="test")
        s.execute("create table a (x int)")
        s.execute("create table b (x int)")
        s.execute("insert into a values (1)")
        s.execute("insert into b values (2)")
        calls = []

        def boom():
            calls.append(1)
            if len(calls) == 2:
                raise RuntimeError("disk full")

        failpoint.enable("persist/backup-table", boom)
        try:
            with pytest.raises(RuntimeError, match="disk full"):
                save_catalog(cat, str(tmp_path))
        finally:
            failpoint.disable("persist/backup-table")
        # resume completes the interrupted backup from the ledger
        save_catalog(cat, str(tmp_path), resume=True)
        cat2 = load_catalog(str(tmp_path))
        s2 = Session(cat2, db="test")
        assert s2.execute("select x from a").rows == [(1,)]
        assert s2.execute("select x from b").rows == [(2,)]

    def test_gc_site_fires_and_pinned_survive(self):
        from tidb_tpu.storage import Catalog

        cat = Catalog()
        s = Session(cat, db="test")
        s.execute("create table t (x int)")
        t = cat.table("test", "t")
        s.execute("insert into t values (-1)")
        pinned = t.version
        t.pin(pinned)
        hits = []
        failpoint.enable("storage/gc-drop-version", lambda: hits.append(1))
        try:
            for i in range(5):
                s.execute(f"insert into t values ({i})")
        finally:
            failpoint.disable("storage/gc-drop-version")
            t.unpin(pinned)
        assert hits, "version GC must run under repeated writes"
        assert pinned in t._versions, "pinned snapshot must survive GC"

    def test_cascade_failpoint_error_restores_all_tables(self):
        from tidb_tpu.storage import Catalog

        cat = Catalog()
        s = Session(cat, db="test")
        s.execute("create table p (id int primary key)")
        s.execute(
            "create table c (id int, pid int, constraint fc foreign key "
            "(pid) references p (id) on delete cascade)"
        )
        s.execute("insert into p values (1)")
        s.execute("insert into c values (10, 1)")
        failpoint.enable("fk/cascade-delete", RuntimeError("crash mid-cascade"))
        try:
            with pytest.raises(RuntimeError, match="mid-cascade"):
                s.execute("delete from p where id = 1")
        finally:
            failpoint.disable("fk/cascade-delete")
        # the whole statement rolled back: both tables intact
        assert s.execute("select count(*) from p").rows == [(1,)]
        assert s.execute("select count(*) from c").rows == [(1,)]

    def test_lock_acquire_site(self):
        from tidb_tpu.storage import Catalog

        cat = Catalog()
        s = Session(cat, db="test")
        s.execute("create table t (x int)")
        hits = []
        failpoint.enable("locks/acquire", lambda: hits.append(1))
        try:
            s.execute("insert into t values (1)")
        finally:
            failpoint.disable("locks/acquire")
        assert hits, "autocommit DML must pass through the lock manager"


class TestResourceGroups:
    """RU-based resource control (reference: TiDB resource groups,
    pkg/domain/resourcegroup + calibrate_resource RU model)."""

    def test_ddl_and_infoschema(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create resource group rg1 ru_per_sec = 1000")
        s.execute("create resource group rg2 ru_per_sec = 50 burstable")
        with pytest.raises(ValueError, match="already exists"):
            s.execute("create resource group rg1 ru_per_sec = 1")
        s.execute("create resource group if not exists rg1 ru_per_sec = 1")
        rows = s.execute(
            "select name, ru_per_sec, burstable from "
            "information_schema.resource_groups order by name"
        ).rows
        assert ("rg1", 1000, "NO") in rows and ("rg2", 50, "YES") in rows
        assert ("default", -1, "YES") in rows
        s.execute("alter resource group rg1 ru_per_sec = 2000")
        s.execute("drop resource group rg2")
        names = [r[0] for r in s.execute(
            "select name from information_schema.resource_groups"
        ).rows]
        assert "rg2" not in names and "rg1" in names
        with pytest.raises(ValueError, match="default"):
            s.execute("drop resource group default")

    def test_throttling_blocks_next_statement(self):
        import time as _t

        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table t (a int)")
        s.execute("insert into t values (1)")
        s.execute("select * from t")  # warm the jit OUTSIDE the group
        s.execute("create resource group slow ru_per_sec = 1000")
        s.execute("set resource group slow")
        s.execute("select * from t")
        # overdraw the bucket deterministically (a 2s statement = 2000
        # RU against a 1000 RU/s fill): the next statement must wait
        # ~1s for refill
        s.catalog.resource_groups.debit("slow", elapsed_s=2.0)
        t0 = _t.monotonic()
        s.execute("select * from t")
        waited = _t.monotonic() - t0
        s.execute("set resource group default")
        assert 0.2 < waited < 20, waited
        consumed = s.execute(
            "select consumed_ru, queries from "
            "information_schema.resource_groups where name = 'slow'"
        ).rows[0]
        assert consumed[0] > 0 and consumed[1] >= 2

    def test_unknown_group_rejected(self):
        from tidb_tpu.session import Session

        s = Session()
        with pytest.raises(ValueError, match="unknown resource group"):
            s.execute("set resource group nope")

    def test_dropped_bound_group_degrades_gracefully(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create resource group g1 ru_per_sec = 100")
        s.execute("set resource group g1")
        s.execute("drop resource group g1")
        # the session must not wedge: statements run unthrottled and
        # rebinding works
        assert s.execute("select 1").rows == [(1,)]
        s.execute("set resource group default")

    def test_zero_rate_rejected_and_burstable_revocable(self):
        from tidb_tpu.session import Session

        s = Session()
        with pytest.raises(ValueError, match="RU_PER_SEC"):
            s.execute("create resource group z ru_per_sec = 0")
        s.execute("create resource group b ru_per_sec = 100 burstable")
        s.execute("alter resource group b burstable = false")
        rows = s.execute(
            "select burstable from information_schema.resource_groups "
            "where name = 'b'"
        ).rows
        assert rows == [("NO",)]

    def test_nonliteral_string_set_falls_back(self):
        from tidb_tpu.session import Session

        s = Session()
        s.execute("create table t (id int, st varchar(16))")
        s.execute("insert into t values (1, 'a'), (2, 'b')")
        s.execute("update t set st = concat(st, 'x') where id = 1")
        assert s.execute(
            "select id, st from t order by id"
        ).rows == [(1, "ax"), (2, "b")]


class TestProcesslistAndKill:
    """SHOW PROCESSLIST + KILL <id> over the catalog session registry
    (reference: the server connection registry, pkg/server/server.go;
    kill routing via util/sqlkiller)."""

    def test_processlist_lists_sessions(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s2.execute("create database d2")
        s2.execute("use d2")
        rows = s1.execute("show processlist").rows
        ids = {r[0] for r in rows}
        assert s1.conn_id in ids and s2.conn_id in ids
        by_id = {r[0]: r for r in rows}
        # the session RUNNING the statement shows it; the idle one sleeps
        assert by_id[s1.conn_id][3] == "Query"
        assert "processlist" in by_id[s1.conn_id][5]
        assert by_id[s2.conn_id][3] == "Sleep"
        assert by_id[s2.conn_id][2] == "d2"

    def test_kill_by_connection_id(self):
        from tidb_tpu.utils.sqlkiller import QueryKilled

        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s2.execute("create table t (a int)")
        s2.execute("insert into t values (1)")

        def stall():
            s1.execute(f"kill query {s2.conn_id}")

        failpoint.enable("executor/before-discover", stall)
        try:
            with pytest.raises(QueryKilled):
                s2.execute("select sum(a) from t where a > 0")
        finally:
            failpoint.disable("executor/before-discover")
        # the killed session recovers
        assert s2.execute("select count(*) from t").rows == [(1,)]

    def test_kill_unknown_id(self):
        s = Session()
        with pytest.raises(ValueError, match="unknown connection"):
            s.execute("kill 999999")

    def test_kill_connection_closes_session(self):
        cat = Catalog()
        s1 = Session(cat)
        s2 = Session(cat)
        s1.execute(f"kill connection {s2.conn_id}")
        with pytest.raises(ConnectionError, match="was killed"):
            s2.execute("select 1")
        # KILL QUERY does NOT close: the session keeps working
        s3 = Session(cat)
        s1.execute(f"kill query {s3.conn_id}")
        assert s3.execute("select 1").rows == [(1,)]
