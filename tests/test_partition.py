"""Table partitioning: RANGE / HASH DDL, planner pruning, mesh scans.

Reference: pkg/table/tables/partition.go (bound evaluation + row
routing) and the partitionProcessor pruning rule
(pkg/planner/core/rule_partition_processor.go). VERDICT round-2 item
#6: pruning visible in EXPLAIN and shard-local scans skipping pruned
partitions on the mesh.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture()
def env():
    cat = Catalog()
    s = Session(cat, db="test")
    s.execute(
        "create table sales (id int, amt int, d date) "
        "partition by range (d) ("
        "partition p22 values less than (date '2023-01-01'), "
        "partition p23 values less than (date '2024-01-01'), "
        "partition pmax values less than maxvalue)"
    )
    s.execute(
        "insert into sales values "
        "(1, 10, date '2022-06-01'), (2, 20, date '2023-06-01'), "
        "(3, 30, date '2024-06-01'), (4, 40, date '2023-01-15'), "
        "(5, 50, NULL)"  # NULL routes to the first partition (MySQL)
    )
    return cat, s


def explain_text(s, q):
    return "\n".join(r[0] for r in s.execute("explain " + q).rows)


def test_rows_route_to_partitions(env):
    cat, s = env
    t = cat.table("test", "sales")
    by_pid = {}
    for b in t.blocks():
        by_pid[b.part_id] = by_pid.get(b.part_id, 0) + b.nrows
    assert by_pid == {0: 2, 1: 2, 2: 1}  # NULL -> p22


def test_range_pruning_correct_and_visible(env):
    _cat, s = env
    q = "select sum(amt) from sales where d < date '2023-01-01'"
    assert s.execute(q).rows == [(10,)]
    assert "partitions=[p22]" in explain_text(s, q)
    q2 = (
        "select sum(amt) from sales where d >= date '2023-01-01' "
        "and d < date '2024-01-01'"
    )
    assert s.execute(q2).rows == [(60,)]
    assert "partitions=[p23]" in explain_text(s, q2)
    q3 = "select sum(amt) from sales where d >= date '2024-06-01'"
    assert s.execute(q3).rows == [(30,)]
    assert "partitions=[pmax]" in explain_text(s, q3)
    # unprunable predicate: all partitions scan
    assert "partitions=" not in explain_text(
        s, "select sum(amt) from sales where amt > 0"
    )


def test_hash_partitioning(env):
    cat, s = env
    s.execute("create table h (k int, v int) partition by hash (k) partitions 4")
    s.execute("insert into h values (0,1),(1,2),(2,3),(3,4),(4,5),(5,6)")
    t = cat.table("test", "h")
    assert sorted({b.part_id for b in t.blocks()}) == [0, 1, 2, 3]
    assert "partitions=[p1]" in explain_text(s, "select v from h where k = 5")
    assert s.execute("select v from h where k = 5").rows == [(6,)]
    # negative keys route like MySQL (mod of abs pattern)
    s.execute("insert into h values (-3, 99)")
    assert s.execute("select v from h where k = -3").rows == [(99,)]


def test_mesh_scans_pruned(env):
    cat, _s = env
    s2 = Session(cat, db="test", mesh_devices=8)
    q = "select sum(amt) from sales where d < date '2023-01-01'"
    # NULL d rows live in p22 but the predicate still filters them
    assert s2.execute(q).rows == [(10,)]


def test_show_create_and_persistence(env, tmp_path):
    cat, s = env
    ddl = s.execute("show create table sales").rows[0][1]
    assert "partition by range (d)" in ddl
    assert "values less than maxvalue" in ddl

    from tidb_tpu.storage.persist import load_catalog, save_catalog

    save_catalog(cat, str(tmp_path))
    cat2 = load_catalog(str(tmp_path))
    t2 = cat2.table("test", "sales")
    assert t2.partition[0] == "range"
    s3 = Session(cat2, db="test")
    q = "select sum(amt) from sales where d < date '2023-01-01'"
    assert s3.execute(q).rows == [(10,)]
    assert "partitions=[p22]" in explain_text(s3, q)


def test_range_insert_out_of_range_errors(env):
    _cat, s = env
    s.execute(
        "create table bounded (a int) partition by range (a) ("
        "partition p0 values less than (10))"
    )
    with pytest.raises(Exception, match="no partition"):
        s.execute("insert into bounded values (10)")


def test_update_keeps_rows_visible(env):
    cat, s = env
    s.execute("update sales set amt = amt + 1 where id = 2")
    # rebuilt blocks may lose their partition tag; pruned scans must
    # still see every matching row (untagged blocks always scan)
    q = (
        "select sum(amt) from sales where d >= date '2023-01-01' "
        "and d < date '2024-01-01'"
    )
    assert s.execute(q).rows == [(61,)]
