"""Table partitioning: RANGE / HASH DDL, planner pruning, mesh scans.

Reference: pkg/table/tables/partition.go (bound evaluation + row
routing) and the partitionProcessor pruning rule
(pkg/planner/core/rule_partition_processor.go). VERDICT round-2 item
#6: pruning visible in EXPLAIN and shard-local scans skipping pruned
partitions on the mesh.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.storage import Catalog


@pytest.fixture()
def env():
    cat = Catalog()
    s = Session(cat, db="test")
    s.execute(
        "create table sales (id int, amt int, d date) "
        "partition by range (d) ("
        "partition p22 values less than (date '2023-01-01'), "
        "partition p23 values less than (date '2024-01-01'), "
        "partition pmax values less than maxvalue)"
    )
    s.execute(
        "insert into sales values "
        "(1, 10, date '2022-06-01'), (2, 20, date '2023-06-01'), "
        "(3, 30, date '2024-06-01'), (4, 40, date '2023-01-15'), "
        "(5, 50, NULL)"  # NULL routes to the first partition (MySQL)
    )
    return cat, s


def explain_text(s, q):
    return "\n".join(r[0] for r in s.execute("explain " + q).rows)


def test_rows_route_to_partitions(env):
    cat, s = env
    t = cat.table("test", "sales")
    by_pid = {}
    for b in t.blocks():
        by_pid[b.part_id] = by_pid.get(b.part_id, 0) + b.nrows
    assert by_pid == {0: 2, 1: 2, 2: 1}  # NULL -> p22


def test_range_pruning_correct_and_visible(env):
    _cat, s = env
    q = "select sum(amt) from sales where d < date '2023-01-01'"
    assert s.execute(q).rows == [(10,)]
    assert "partitions=[p22]" in explain_text(s, q)
    q2 = (
        "select sum(amt) from sales where d >= date '2023-01-01' "
        "and d < date '2024-01-01'"
    )
    assert s.execute(q2).rows == [(60,)]
    assert "partitions=[p23]" in explain_text(s, q2)
    q3 = "select sum(amt) from sales where d >= date '2024-06-01'"
    assert s.execute(q3).rows == [(30,)]
    assert "partitions=[pmax]" in explain_text(s, q3)
    # unprunable predicate: all partitions scan
    assert "partitions=" not in explain_text(
        s, "select sum(amt) from sales where amt > 0"
    )


def test_hash_partitioning(env):
    cat, s = env
    s.execute("create table h (k int, v int) partition by hash (k) partitions 4")
    s.execute("insert into h values (0,1),(1,2),(2,3),(3,4),(4,5),(5,6)")
    t = cat.table("test", "h")
    assert sorted({b.part_id for b in t.blocks()}) == [0, 1, 2, 3]
    assert "partitions=[p1]" in explain_text(s, "select v from h where k = 5")
    assert s.execute("select v from h where k = 5").rows == [(6,)]
    # negative keys route like MySQL (mod of abs pattern)
    s.execute("insert into h values (-3, 99)")
    assert s.execute("select v from h where k = -3").rows == [(99,)]


def test_mesh_scans_pruned(env):
    cat, _s = env
    s2 = Session(cat, db="test", mesh_devices=8)
    q = "select sum(amt) from sales where d < date '2023-01-01'"
    # NULL d rows live in p22 but the predicate still filters them
    assert s2.execute(q).rows == [(10,)]


def test_show_create_and_persistence(env, tmp_path):
    cat, s = env
    ddl = s.execute("show create table sales").rows[0][1]
    assert "partition by range (d)" in ddl
    assert "values less than maxvalue" in ddl

    from tidb_tpu.storage.persist import load_catalog, save_catalog

    save_catalog(cat, str(tmp_path))
    cat2 = load_catalog(str(tmp_path))
    t2 = cat2.table("test", "sales")
    assert t2.partition[0] == "range"
    s3 = Session(cat2, db="test")
    q = "select sum(amt) from sales where d < date '2023-01-01'"
    assert s3.execute(q).rows == [(10,)]
    assert "partitions=[p22]" in explain_text(s3, q)


def test_range_insert_out_of_range_errors(env):
    _cat, s = env
    s.execute(
        "create table bounded (a int) partition by range (a) ("
        "partition p0 values less than (10))"
    )
    with pytest.raises(Exception, match="no partition"):
        s.execute("insert into bounded values (10)")


def test_update_keeps_rows_visible(env):
    cat, s = env
    s.execute("update sales set amt = amt + 1 where id = 2")
    # rebuilt blocks may lose their partition tag; pruned scans must
    # still see every matching row (untagged blocks always scan)
    q = (
        "select sum(amt) from sales where d >= date '2023-01-01' "
        "and d < date '2024-01-01'"
    )
    assert s.execute(q).rows == [(61,)]


class TestPartitionManagementDDL:
    """ALTER TABLE ... ADD/DROP/TRUNCATE PARTITION (reference:
    pkg/ddl/partition.go onAddTablePartition / onDropTablePartition /
    onTruncateTablePartition — RANGE only, as in the reference)."""

    @pytest.fixture()
    def env2(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table m (id int, d int) partition by range (d) ("
            "partition p0 values less than (10), "
            "partition p1 values less than (20), "
            "partition p2 values less than (30))"
        )
        s.execute(
            "insert into m values (1, 5), (2, 15), (3, 25), (4, 16)"
        )
        return cat, s

    def test_add_partition_extends_range(self, env2):
        cat, s = env2
        s.execute(
            "alter table m add partition ("
            "partition p3 values less than (40), "
            "partition pmax values less than maxvalue)"
        )
        s.execute("insert into m values (5, 35), (6, 99)")
        assert s.execute("select count(*) from m").rows == [(6,)]
        assert s.execute(
            "select id from m where d >= 30 order by id"
        ).rows == [(5,), (6,)]
        assert "partitions=[p3]" in explain_text(
            s, "select id from m where d between 30 and 39"
        )

    def test_add_partition_validation(self, env2):
        cat, s = env2
        with pytest.raises(Exception, match="increasing"):
            s.execute(
                "alter table m add partition "
                "(partition bad values less than (25))"
            )
        with pytest.raises(Exception, match="duplicate"):
            s.execute(
                "alter table m add partition "
                "(partition p1 values less than (40))"
            )
        s.execute(
            "alter table m add partition "
            "(partition pmax values less than maxvalue)"
        )
        with pytest.raises(Exception, match="MAXVALUE"):
            s.execute(
                "alter table m add partition "
                "(partition p9 values less than (99))"
            )

    def test_drop_partition_removes_rows_and_remaps(self, env2):
        cat, s = env2
        s.execute("alter table m drop partition p1")
        assert s.execute("select id from m order by id").rows == [
            (1,), (3,)
        ]
        # remaining partitions keep working: routing and pruning
        s.execute("insert into m values (7, 8), (8, 27)")
        assert s.execute(
            "select id from m where d >= 20 order by id"
        ).rows == [(3,), (8,)]
        assert "partitions=[p2]" in explain_text(
            s, "select id from m where d >= 20"
        )
        t = cat.table("test", "m")
        assert t.partition_names() == ["p0", "p2"]
        # part ids remapped: p2 blocks now tagged 1
        assert {b.part_id for b in t.blocks()} == {0, 1}
        with pytest.raises(Exception, match="unknown partition"):
            s.execute("alter table m drop partition nope")

    def test_drop_all_partitions_rejected(self, env2):
        cat, s = env2
        with pytest.raises(Exception, match="all partitions"):
            s.execute("alter table m drop partition p0, p1, p2")

    def test_truncate_partition_keeps_definition(self, env2):
        cat, s = env2
        s.execute("alter table m truncate partition p1")
        assert s.execute("select id from m order by id").rows == [
            (1,), (3,)
        ]
        t = cat.table("test", "m")
        assert t.partition_names() == ["p0", "p1", "p2"]
        # the emptied partition still accepts rows
        s.execute("insert into m values (9, 12)")
        assert s.execute(
            "select id from m where d between 10 and 19"
        ).rows == [(9,)]

    def test_hash_table_rejected(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table h (id int) partition by hash (id) partitions 4"
        )
        with pytest.raises(Exception, match="RANGE"):
            s.execute(
                "alter table h add partition "
                "(partition p9 values less than (10))"
            )
        with pytest.raises(Exception, match="RANGE"):
            s.execute("alter table h drop partition p0")

    def test_show_create_reflects_changes(self, env2):
        cat, s = env2
        s.execute("alter table m drop partition p0")
        s.execute(
            "alter table m add partition "
            "(partition p3 values less than (40))"
        )
        ddl = s.execute("show create table m").rows[0][1]
        assert "p0" not in ddl
        assert "p3" in ddl

    def test_update_then_drop_partition_no_ghost_rows(self, env2):
        # UPDATE/DELETE rebuild blocks; part_id must survive the rebuild
        # or dropped partitions leave ghost rows behind
        cat, s = env2
        s.execute("update m set id = id + 10 where d = 16")
        s.execute("delete from m where d = 5")
        s.execute("alter table m drop partition p1")
        assert s.execute("select id, d from m order by id").rows == [(3, 25)]
        t = cat.table("test", "m")
        assert all(b.part_id is not None for b in t.blocks())

    def test_drop_partition_fk_restrict_and_cascade(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table parent (pk int primary key, d int) "
            "partition by range (d) ("
            "partition p0 values less than (10), "
            "partition p1 values less than (20))"
        )
        s.execute("insert into parent values (1, 5), (2, 15)")
        s.execute(
            "create table child (id int, ref int, "
            "foreign key (ref) references parent (pk))"
        )
        s.execute("insert into child values (100, 2)")
        # RESTRICT: dropping the partition holding pk=2 must fail whole
        with pytest.raises(Exception, match="[Ff]oreign|FOREIGN|restrict|child"):
            s.execute("alter table parent drop partition p1")
        assert s.execute("select count(*) from parent").rows == [(2,)]
        t = cat.table("test", "parent")
        assert t.partition_names() == ["p0", "p1"]  # defs restored
        # CASCADE: child rows follow the dropped partition
        s.execute("drop table child")
        s.execute(
            "create table child (id int, ref int, foreign key (ref) "
            "references parent (pk) on delete cascade)"
        )
        s.execute("insert into child values (100, 2), (101, 1)")
        s.execute("alter table parent drop partition p1")
        assert s.execute("select id from child order by id").rows == [(101,)]

    def test_pinned_snapshot_prunes_with_old_defs(self, env2):
        cat, s = env2
        s2 = Session(cat, db="test")
        s2.execute("begin")
        assert s2.execute(
            "select id from m where d >= 20 order by id"
        ).rows == [(3,)]  # pins the pre-DDL version
        s.execute("alter table m drop partition p0")
        # the pinned txn keeps seeing the old defs AND old rows
        assert s2.execute(
            "select id from m where d < 10 order by id"
        ).rows == [(1,)]
        assert s2.execute(
            "select id from m where d >= 20 order by id"
        ).rows == [(3,)]
        s2.execute("commit")
        # after commit the new defs apply: p0 rows are gone
        assert s2.execute("select id from m order by id").rows == [
            (2,), (3,), (4,)
        ]

    def test_partition_ddl_rejected_inside_txn(self, env2):
        cat, s = env2
        s.execute("begin")
        with pytest.raises(Exception, match="transaction"):
            s.execute("alter table m drop partition p1")
        s.execute("rollback")
        assert cat.table("test", "m").partition_names() == [
            "p0", "p1", "p2"
        ]

    def test_explain_prunes_with_pinned_defs(self, env2):
        cat, s = env2
        s2 = Session(cat, db="test")
        s2.execute("begin")
        s2.execute("select count(*) from m")  # pin pre-DDL version
        s.execute("alter table m drop partition p0")
        # the pinned txn's EXPLAIN shows the defs execution will use
        assert "partitions=[p0]" in explain_text(
            s2, "select id from m where d < 10"
        )
        s2.execute("commit")
        assert "partitions=[p0]" not in explain_text(
            s2, "select id from m where d < 10"
        )

    def test_exchange_partition(self, env2):
        cat, s = env2
        s.execute("create table stage (id int, d int)")
        s.execute("insert into stage values (50, 11), (51, 19)")
        s.execute("alter table m exchange partition p1 with table stage")
        # staged rows are now partition p1; old p1 rows moved to stage
        assert s.execute("select id from m order by id").rows == [
            (1,), (3,), (50,), (51,)
        ]
        assert s.execute("select id from stage order by id").rows == [
            (2,), (4,)
        ]
        assert "partitions=[p1]" in explain_text(
            s, "select id from m where d between 10 and 19"
        )
        assert s.execute(
            "select id from m where d between 10 and 19 order by id"
        ).rows == [(50,), (51,)]

    def test_exchange_partition_validation(self, env2):
        cat, s = env2
        s.execute("create table stage (id int, d int)")
        s.execute("insert into stage values (50, 25)")  # routes to p2
        with pytest.raises(Exception, match="does not match"):
            s.execute("alter table m exchange partition p1 with table stage")
        # WITHOUT VALIDATION lets mismatched rows through (MySQL parity)
        s.execute(
            "alter table m exchange partition p1 with table stage "
            "without validation"
        )
        assert s.execute("select id from stage order by id").rows == [
            (2,), (4,)
        ]

    def test_exchange_partition_schema_mismatch(self, env2):
        cat, s = env2
        s.execute("create table bad1 (id int, d varchar(10))")
        with pytest.raises(Exception, match="definitions"):
            s.execute("alter table m exchange partition p1 with table bad1")
        s.execute(
            "create table bad2 (id int, d int) "
            "partition by range (d) (partition q values less than (99))"
        )
        with pytest.raises(Exception, match="unpartitioned"):
            s.execute("alter table m exchange partition p1 with table bad2")

    def test_exchange_partition_strings_cross_dictionaries(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table logs (d int, msg varchar(40)) "
            "partition by range (d) ("
            "partition a values less than (10), "
            "partition b values less than (20))"
        )
        s.execute(
            "insert into logs values (1, 'alpha'), (15, 'kappa'), "
            "(16, 'zeta')"
        )
        s.execute("create table stage (d int, msg varchar(40))")
        s.execute(
            "insert into stage values (12, 'omega'), (13, 'alpha')"
        )
        s.execute("alter table logs exchange partition b with table stage")
        assert s.execute(
            "select msg from logs order by d"
        ).rows == [("alpha",), ("omega",), ("alpha",)]
        assert s.execute(
            "select msg from stage order by d"
        ).rows == [("kappa",), ("zeta",)]
        # string equality still works across the merged dictionaries
        assert s.execute(
            "select count(*) from logs where msg = 'alpha'"
        ).rows == [(2,)]

    def test_exchange_partition_unique_conflict_rejected(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table m (id int primary key, d int) "
            "partition by range (d) ("
            "partition p0 values less than (10), "
            "partition p1 values less than (20))"
        )
        s.execute("insert into m values (5, 1), (6, 15)")
        s.execute("create table stage (id int primary key, d int)")
        s.execute("insert into stage values (5, 15)")  # id=5 already in p0
        with pytest.raises(Exception, match="duplicate"):
            s.execute("alter table m exchange partition p1 with table stage")
        assert s.execute("select count(*) from m").rows == [(2,)]
        assert s.execute("select count(*) from stage").rows == [(1,)]

    def test_exchange_partition_multiblock_dictionary_shift(self):
        # two staged blocks whose second merge shifts the first block's
        # codes: the two-pass alignment must keep values stable
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table t (d int, w varchar(10)) "
            "partition by range (d) ("
            "partition a values less than (10), "
            "partition b values less than (20))"
        )
        s.execute("insert into t values (1, 'mmm'), (15, 'zzz')")
        s.execute("create table stage (d int, w varchar(10))")
        s.execute("insert into stage values (11, 'omega')")  # block 1
        s.execute("insert into stage values (12, 'beta')")   # block 2 shifts omega
        s.execute("alter table t exchange partition b with table stage")
        assert s.execute("select w from t order by d").rows == [
            ("mmm",), ("omega",), ("beta",)
        ]
        assert s.execute("select w from stage order by d").rows == [("zzz",)]
        assert s.execute(
            "select count(*) from t where w = 'omega'"
        ).rows == [(1,)]


class TestListPartitioning:
    """PARTITION BY LIST (vs pkg/ddl/partition.go list-partition
    support): explicit value sets per partition, NULL listable in one
    partition, full management-DDL parity with RANGE."""

    @pytest.fixture()
    def env3(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table r (id int, region int) "
            "partition by list (region) ("
            "partition east values in (1, 3), "
            "partition west values in (2, 4), "
            "partition other values in (9, null))"
        )
        s.execute(
            "insert into r values (1, 1), (2, 2), (3, 3), (4, 9), "
            "(5, NULL)"
        )
        return cat, s

    def test_rows_route_by_list(self, env3):
        cat, s = env3
        t = cat.table("test", "r")
        by_pid = {}
        for b in t.blocks():
            by_pid[b.part_id] = by_pid.get(b.part_id, 0) + b.nrows
        assert by_pid == {0: 2, 1: 1, 2: 2}  # NULL routes to 'other'

    def test_unlisted_value_rejected(self, env3):
        cat, s = env3
        with pytest.raises(Exception, match="no partition"):
            s.execute("insert into r values (9, 7)")

    def test_pruning_visible_and_correct(self, env3):
        cat, s = env3
        assert "partitions=[east]" in explain_text(
            s, "select id from r where region = 3"
        )
        assert s.execute(
            "select id from r where region = 3"
        ).rows == [(3,)]
        assert s.execute(
            "select id from r where region in (2, 9) order by id"
        ).rows == [(2,), (4,)]

    def test_management_ddl(self, env3):
        cat, s = env3
        s.execute(
            "alter table r add partition (partition north values in (5, 6))"
        )
        s.execute("insert into r values (6, 5)")
        with pytest.raises(Exception, match="already belongs"):
            s.execute(
                "alter table r add partition (partition dup values in (3))"
            )
        s.execute("alter table r truncate partition west")
        assert s.execute("select count(*) from r").rows == [(5,)]
        s.execute("alter table r drop partition east")
        assert s.execute("select id from r order by id").rows == [
            (4,), (5,), (6,)
        ]
        t = cat.table("test", "r")
        assert t.partition_names() == ["west", "other", "north"]
        # remapped ids still route and prune correctly
        assert s.execute(
            "select id from r where region = 5"
        ).rows == [(6,)]

    def test_null_without_null_partition_rejected(self):
        cat = Catalog()
        s = Session(cat, db="test")
        s.execute(
            "create table q (id int, k int) partition by list (k) ("
            "partition a values in (1))"
        )
        with pytest.raises(Exception, match="NULL"):
            s.execute("insert into q values (1, NULL)")

    def test_show_create_and_br_roundtrip(self, env3, tmp_path):
        cat, s = env3
        ddl = s.execute("show create table r").rows[0][1]
        assert "partition by list (region)" in ddl
        assert "values in (1, 3)" in ddl
        assert "null" in ddl
        s.execute(f"backup database test to '{tmp_path}/b'")
        cat2 = Catalog()
        s2 = Session(cat2, db="test")
        s2.execute(f"restore database test from '{tmp_path}/b'")
        assert s2.execute(
            "select id from r where region = 3"
        ).rows == [(3,)]
        t2 = cat2.table("test", "r")
        assert t2.partition == cat.table("test", "r").partition

    def test_exchange_partition_list(self, env3):
        cat, s = env3
        s.execute("create table stage (id int, region int)")
        s.execute("insert into stage values (70, 2), (71, 4)")
        s.execute("alter table r exchange partition west with table stage")
        assert s.execute(
            "select id from r where region in (2, 4) order by id"
        ).rows == [(70,), (71,)]
        assert s.execute("select id from stage").rows == [(2,)]
        # validation: a row listed under another partition is rejected
        s.execute("create table bad (id int, region int)")
        s.execute("insert into bad values (9, 1)")
        with pytest.raises(Exception, match="does not match"):
            s.execute("alter table r exchange partition west with table bad")
