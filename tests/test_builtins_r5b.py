"""Round-5 builtin completion: misc/info/legacy-crypto family + user
variables.

Reference: pkg/expression/builtin_miscellaneous.go (VITESS_HASH:1406,
TIDB_SHARD:1606), util/vitess/vitess_hash.go:37 (+ its test vectors,
vitess_hash_test.go — matched bit-exactly here), builtin_time.go
(CONVERT_TZ/TIMEDIFF/TIME_FORMAT), builtin_encryption.go,
builtin_info.go, builtin_other.go (getVar/setVar).
"""

import pytest

from tidb_tpu.session import Session


@pytest.fixture
def sess():
    return Session()


class TestVitessHashParity:
    """Bit-exact against the reference's own test vectors.
    vitess_hash keys through single-block DES from the optional
    `cryptography` package — stub-or-gate rule: environments without
    it skip instead of failing on the kernel's import."""

    VECTORS = [
        (30375298039, 0x031265661E5F1133),
        (1123, 0x031B565D41BDF8CA),
        (30573721600, 0x1EFD6439F2050FFD),
    ]

    def test_vitess_hash_vectors(self, sess):
        pytest.importorskip("cryptography")
        for v, want in self.VECTORS:
            assert sess.execute(f"select vitess_hash({v})").rows == [
                (want,)
            ]

    def test_tidb_shard_is_hash_mod_256(self, sess):
        pytest.importorskip("cryptography")
        for v, want in self.VECTORS:
            assert sess.execute(f"select tidb_shard({v})").rows == [
                (want % 256,)
            ]

    def test_null_propagates(self, sess):
        assert sess.execute("select vitess_hash(NULL)").rows == [(None,)]


class TestTimeFamily:
    def test_convert_tz_offsets(self, sess):
        assert sess.execute(
            "select convert_tz('2024-01-01 12:00:00', '+00:00', '+08:00')"
        ).rows == [("2024-01-01 20:00:00",)]
        assert sess.execute(
            "select convert_tz('2024-01-01 01:00:00', '+02:00', '-03:00')"
        ).rows == [("2023-12-31 20:00:00",)]

    def test_convert_tz_named_zone_is_null(self, sess):
        # no tz tables loaded: named zones -> NULL (MySQL behavior)
        assert sess.execute(
            "select convert_tz('2024-01-01 12:00:00', 'US/Eastern', 'UTC')"
        ).rows == [(None,)]

    def test_timediff(self, sess):
        assert sess.execute(
            "select timediff('10:00:00', '08:30:00')"
        ).rows == [("01:30:00",)]
        assert sess.execute(
            "select timediff('08:00:00', '10:30:00')"
        ).rows == [("-02:30:00",)]
        assert sess.execute(
            "select timediff('2024-01-02 00:00:00', '2024-01-01 22:00:00')"
        ).rows == [("02:00:00",)]
        # mixed kinds -> NULL (MySQL: operands must be the same type)
        assert sess.execute(
            "select timediff('2024-01-02 00:00:00', '10:00:00')"
        ).rows == [(None,)]

    def test_time_format(self, sess):
        assert sess.execute(
            "select time_format('25:30:45', '%H %k %h %i %s')"
        ).rows == [("25 25 01 30 45",)]
        assert sess.execute(
            "select time_format('09:05:00', '%r')"
        ).rows == [("09:05:00 AM",)]


class TestCryptoMisc:
    def test_sm3_known_vector(self, sess):
        assert sess.execute("select sm3('abc')").rows == [(
            "66c7f0f462eeedd9d1f2d46bdc10e4e24167c4875cf2f7a2297da02b8f4ba8e0",
        )]

    def test_password_strength_tiers(self, sess):
        cases = [("ab", 0), ("abcde", 25), ("abcdefgh", 50),
                 ("Abcdefg1", 75), ("Abcdef1!", 100)]
        for pw, want in cases:
            assert sess.execute(
                f"select validate_password_strength('{pw}')"
            ).rows == [(want,)], pw

    def test_encode_decode_roundtrip(self, sess):
        assert sess.execute(
            "select decode(encode('secret text', 'pw'), 'pw')"
        ).rows == [("secret text",)]
        # wrong password does not round-trip
        wrong = sess.execute(
            "select decode(encode('secret text', 'pw'), 'other')"
        ).rows[0][0]
        assert wrong != "secret text"

    def test_removed_functions_return_null(self, sess):
        for q in ["des_encrypt('x')", "des_decrypt('x')", "encrypt('x')",
                  "old_password('x')", "load_file('/nope')",
                  "master_pos_wait('f', 4)"]:
            assert sess.execute(f"select {q}").rows == [(None,)], q

    def test_translate(self, sess):
        assert sess.execute(
            "select translate('abcba', 'abc', 'xy')"
        ).rows == [("xyyx",)]  # 'c' has no target -> deleted


class TestTidbInfoFunctions:
    def test_parse_tso(self, sess):
        # physical ms = tso >> 18
        tso = (1700000000000 << 18) | 5
        r = sess.execute(f"select tidb_parse_tso({tso})").rows[0][0]
        assert r.startswith("2023-11-")
        assert sess.execute(
            f"select tidb_parse_tso_logical({tso})"
        ).rows == [(5,)]

    def test_current_tso_and_ddl_owner(self, sess):
        tso = sess.execute("select tidb_current_tso()").rows[0][0]
        assert tso > (1 << 50)  # physical ms in the high bits
        assert sess.execute("select tidb_is_ddl_owner()").rows == [(1,)]

    def test_bounded_staleness(self, sess):
        assert sess.execute(
            "select tidb_bounded_staleness('2024-01-01 00:00:00',"
            " '2024-01-02 00:00:00')"
        ).rows == [("2024-01-02 00:00:00",)]

    def test_encode_decode_sql_digest(self, sess):
        d1 = sess.execute(
            "select tidb_encode_sql_digest('select 1')"
        ).rows[0][0]
        d2 = sess.execute(
            "select tidb_encode_sql_digest('select   2')"
        ).rows[0][0]
        assert d1 == d2  # literals normalize to '?'
        assert len(d1) == 64


class TestUserVariables:
    def test_set_and_read(self, sess):
        sess.execute("set @x = 42")
        assert sess.execute("select @x").rows == [(42,)]
        sess.execute("set @s = 'hello'")
        assert sess.execute("select @s, @x").rows == [("hello", 42)]

    def test_unset_is_null(self, sess):
        assert sess.execute("select @never_set").rows == [(None,)]

    def test_usable_in_expressions(self, sess):
        sess.execute("set @n = 10")
        assert sess.execute("select @n + 5").rows == [(15,)]
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (5), (15)")
        assert sess.execute(
            "select a from t where a > @n"
        ).rows == [(15,)]


class TestIlike:
    def test_ilike_shapes(self, sess):
        sess.execute("create table il (v varchar(16))")
        sess.execute("insert into il values ('Apple'), ('BANANA'), ('cherry')")
        assert sess.execute(
            "select v from il where v ilike 'a%' order by v"
        ).rows == [("Apple",)]
        assert sess.execute(
            "select v from il where v not ilike '%AN%' order by v"
        ).rows == [("Apple",), ("cherry",)]
        assert sess.execute("select 'ABC' ilike 'abc'").rows == [(True,)]
