"""Tier-1 gate for scripts/check_backend_gates.py: the repo stays free
of raw `== "tpu"` backend string compares (PERF_NOTES forensics: the
compare is always False through the axon PJRT tunnel, so TPU-only
engine paths silently never fired on hardware — utils/backend.is_tpu()
is the one sanctioned check)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "scripts", "check_backend_gates.py")


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, LINT, REPO], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, (
        f"backend-gate violations:\n{proc.stdout}{proc.stderr}"
    )


def test_lint_catches_violations(tmp_path):
    pkg = tmp_path / "tidb_tpu"
    pkg.mkdir()
    (pkg / "bad_gate.py").write_text(
        'import jax\n'
        'ON_TPU = jax.default_backend() == "tpu"\n'   # rule 1  # backend-gate-ok
        'OTHER = backend != "tpu"\n'                  # rule 2
        'OK = backend == "tpu"  # backend-gate-ok\n'  # pragma exempts
    )
    (tmp_path / "outside.py").write_text(
        'x = store == "tpu"\n'  # outside tidb_tpu/: rule 2 not applied
    )
    proc = subprocess.run(
        [sys.executable, LINT, str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "bad_gate.py:2" in proc.stdout
    assert "bad_gate.py:3" in proc.stdout
    assert "bad_gate.py:4" not in proc.stdout
    assert "outside.py" not in proc.stdout


def test_is_tpu_is_importable_and_boolean():
    from tidb_tpu.utils.backend import is_tpu

    assert is_tpu() in (True, False)
