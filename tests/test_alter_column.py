"""ALTER TABLE MODIFY/CHANGE COLUMN, RENAME COLUMN/TABLE.

Reference: onModifyColumn + the write-reorg backfill
(pkg/ddl/column.go:518), onRenameTable (pkg/ddl/table.go). The columnar
analog converts immutable blocks lock-free and retries the atomic swap
when concurrent DML published a newer version (delta-only reconvert) —
see Table.alter_modify_column.
"""

import pytest

from tidb_tpu.session import Session
from tidb_tpu.utils import failpoint


@pytest.fixture()
def sess():
    s = Session()
    s.execute("create database altc")
    s.execute("use altc")
    yield s
    failpoint.disable_all()


class TestModifyColumn:
    def test_int_to_decimal_and_back(self, sess):
        sess.execute("create table t (a int, b int)")
        sess.execute("insert into t values (1, 10), (2, -3), (null, 0)")
        sess.execute("alter table t modify a decimal(10,2)")
        rows = sess.execute("select a, b from t order by b").rows
        assert [(None if a is None else float(a), b) for a, b in rows] == [
            (2.0, -3), (None, 0), (1.0, 10)
        ]
        # decimal -> int rounds half away from zero
        sess.execute("update t set a = 2.5 where b = -3")
        sess.execute("alter table t modify a int")
        rows = sess.execute("select a, b from t order by b").rows
        assert rows == [(3, -3), (None, 0), (1, 10)]

    def test_decimal_scale_change_rounds(self, sess):
        sess.execute("create table t (a decimal(10,3), k int)")
        sess.execute(
            "insert into t values (1.2345, 1), (1.005, 2), (-1.0005, 3)"
        )
        # parser/encoding rounds inserts to scale 3 first: 1.234|1.005|-1.001
        sess.execute("alter table t modify a decimal(10,2)")
        rows = sess.execute("select a from t order by k").rows
        assert [float(r[0]) for r in rows] == [1.23, 1.01, -1.0]
        sess.execute("alter table t modify a decimal(10,4)")
        rows = sess.execute("select a from t order by k").rows
        assert [float(r[0]) for r in rows] == [1.23, 1.01, -1.0]

    def test_int_string_roundtrip(self, sess):
        sess.execute("create table t (a int, k int)")
        sess.execute("insert into t values (42, 1), (-7, 2), (null, 3)")
        sess.execute("alter table t modify a varchar(20)")
        assert sess.execute("select a from t order by k").rows == [
            ("42",), ("-7",), (None,)
        ]
        assert sess.execute(
            "select a from t where a = '42'"
        ).rows == [("42",)]
        sess.execute("alter table t modify a bigint")
        assert sess.execute("select a from t order by k").rows == [
            (42,), (-7,), (None,)
        ]

    def test_bad_string_to_int_aborts_clean(self, sess):
        sess.execute("create table t (a varchar(10))")
        sess.execute("insert into t values ('12'), ('oops')")
        with pytest.raises(ValueError, match="Truncated|incorrect"):
            sess.execute("alter table t modify a int")
        # no visible state change: still a string column
        assert sess.execute("select a from t order by a").rows == [
            ("12",), ("oops",)
        ]

    def test_date_datetime_roundtrip(self, sess):
        sess.execute("create table t (d date)")
        sess.execute("insert into t values ('2024-03-05')")
        sess.execute("alter table t modify d datetime")
        # midnight-exact: comparisons and formatting see the instant
        assert sess.execute(
            "select count(*) from t where d = '2024-03-05 00:00:00'"
        ).rows == [(1,)]
        assert sess.execute(
            "select year(d), month(d), day(d), hour(d) from t"
        ).rows == [(2024, 3, 5, 0)]
        sess.execute("alter table t modify d date")
        assert sess.execute(
            "select count(*) from t where d = '2024-03-05'"
        ).rows == [(1,)]

    def test_change_renames_and_converts(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (5)")
        sess.execute("alter table t change a b decimal(8,2)")
        assert float(sess.execute("select b from t").rows[0][0]) == 5.0
        cols = [r[0] for r in sess.execute("show columns from t").rows]
        assert cols == ["b"]

    def test_not_null_with_nulls_rejected(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (1), (null)")
        with pytest.raises(ValueError, match="NULL"):
            sess.execute("alter table t modify a bigint not null")

    def test_unique_index_dup_after_narrowing_aborts(self, sess):
        sess.execute("create table t (a decimal(10,2))")
        sess.execute("create unique index ua on t (a)")
        sess.execute("insert into t values (1.24), (1.21)")
        with pytest.raises(ValueError, match="Duplicate"):
            sess.execute("alter table t modify a decimal(10,1)")
        # aborted BEFORE publish: still scale 2, both rows distinct
        rows = sess.execute("select a from t order by a").rows
        assert [float(r[0]) for r in rows] == [1.21, 1.24]

    def test_fk_and_check_guards(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c (x int, pid int, "
            "constraint f foreign key (pid) references p (id))"
        )
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            sess.execute("alter table c modify pid varchar(10)")
        with pytest.raises(ValueError, match="FOREIGN KEY"):
            sess.execute("alter table p modify id varchar(10)")
        sess.execute("create table ck (a int, check (a > 0))")
        with pytest.raises(ValueError, match="CHECK"):
            sess.execute("alter table ck modify a varchar(10)")

    def test_concurrent_dml_during_reorg_retries(self, sess):
        sess.execute("create table t (a int, k int)")
        sess.execute("insert into t values (1, 1), (2, 2)")
        state = {"fired": False}

        def racing_dml():
            if not state["fired"]:
                state["fired"] = True
                # a concurrent writer lands between snapshot and swap:
                # the reorg must retry and convert the delta block too
                s2 = Session(sess.catalog, db="altc")
                s2.execute("insert into t values (3, 3)")

        failpoint.enable("ddl/modify-column-reorg", racing_dml)
        try:
            sess.execute("alter table t modify a decimal(10,2)")
        finally:
            failpoint.disable("ddl/modify-column-reorg")
        assert state["fired"]
        rows = sess.execute("select a from t order by k").rows
        assert [float(r[0]) for r in rows] == [1.0, 2.0, 3.0]

    def test_indexes_survive_modify(self, sess):
        sess.execute("create table t (a int, b int)")
        sess.execute("create index ia on t (a)")
        sess.execute("insert into t values (3, 1), (1, 2), (2, 3)")
        sess.execute("alter table t modify a decimal(6,1)")
        rows = sess.execute("select a from t order by a").rows
        assert [float(r[0]) for r in rows] == [1.0, 2.0, 3.0]
        assert sess.catalog.table("altc", "t").indexes["ia"] == ["a"]


class TestRename:
    def test_rename_column_metadata_only(self, sess):
        sess.execute("create table t (a int, b varchar(5))")
        sess.execute("insert into t values (1, 'x')")
        sess.execute("create index ib on t (b)")
        sess.execute("alter table t rename column b to c")
        assert sess.execute("select c from t").rows == [("x",)]
        assert sess.catalog.table("altc", "t").indexes["ib"] == ["c"]
        with pytest.raises(Exception):
            sess.execute("select b from t")

    def test_alter_rename_table(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("insert into t values (7)")
        sess.execute("alter table t rename to t2")
        assert sess.execute("select a from t2").rows == [(7,)]
        with pytest.raises(Exception):
            sess.execute("select a from t")

    def test_rename_table_statement_updates_fks(self, sess):
        sess.execute("create table p (id int primary key)")
        sess.execute(
            "create table c (pid int, "
            "constraint f foreign key (pid) references p (id))"
        )
        sess.execute("insert into p values (1)")
        sess.execute("insert into c values (1)")
        sess.execute("rename table p to parent")
        # FK now points at the new name: violations still caught
        with pytest.raises(ValueError):
            sess.execute("insert into c values (99)")
        sess.execute("insert into c values (1)")
        assert sess.execute("select count(*) from c").rows == [(2,)]

    def test_rename_table_multi_pair_atomic(self, sess):
        sess.execute("create table a1 (x int)")
        sess.execute("create table b1 (x int)")
        sess.execute("insert into a1 values (1)")
        # second pair fails (target exists) -> first pair rolls back
        with pytest.raises(ValueError):
            sess.execute("rename table a1 to a2, b1 to a2")
        assert sess.execute("select x from a1").rows == [(1,)]

    def test_swap_via_three_way_rename(self, sess):
        sess.execute("create table x (v int)")
        sess.execute("create table y (v int)")
        sess.execute("insert into x values (1)")
        sess.execute("insert into y values (2)")
        sess.execute("rename table x to tmp, y to x, tmp to y")
        assert sess.execute("select v from x").rows == [(2,)]
        assert sess.execute("select v from y").rows == [(1,)]


class TestReviewRegressions:
    def test_default_follows_change_rename(self, sess):
        sess.execute("create table t (a int default 5, b int)")
        sess.execute("alter table t change a a2 varchar(10)")
        sess.execute("insert into t (b) values (1)")
        assert sess.execute("select a2 from t").rows == [("5",)]

    def test_alter_rename_needs_drop_create(self, sess):
        sess.execute("create table t (a int)")
        sess.execute("create user u1 identified by ''")
        sess.execute("grant alter on altc.* to u1")
        s2 = Session(sess.catalog, db="altc")
        s2.user = "u1"
        with pytest.raises(PermissionError):
            s2.execute("alter table t rename to t9")

    def test_huge_string_to_int_out_of_range(self, sess):
        sess.execute("create table t (a varchar(32))")
        sess.execute("insert into t values ('99999999999999999999999')")
        with pytest.raises(ValueError, match="Out of range|Truncated"):
            sess.execute("alter table t modify a bigint")
        assert sess.execute("select count(*) from t").rows == [(1,)]
