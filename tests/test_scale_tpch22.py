"""Scale-tier (@slow) TPC-H parity: ALL 22 ladder queries at SF0.1 with a
memory quota small enough that the streamed (spill-analog) agg and
host-staged sort paths actually engage, golden-checked by the same
plain-Python oracles as the default-tier run.

Reference: realtikvtest runs SF-sized workloads against the real engine
(VERDICT round-2 item #9). The queries and oracles live in
tests/test_tpch_sql.py; this driver re-runs that module in a child
pytest with TIDB_TPU_TPCH_SF / TIDB_TPU_TPCH_QUOTA set, so the whole
22-query surface is exercised at scale without duplicating oracles.

Run with RUN_SLOW=1 python -m pytest tests/test_scale_tpch22.py -q
(SF via TIDB_TPU_SCALE22_SF, default 0.1; quota via
TIDB_TPU_SCALE22_QUOTA, default 48MB — small enough at SF0.1 that Q1's
aggregation goes through the streamed path and Q18's sort is staged).
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# All 22 ladder queries run under the forced small quota: single-big
# shapes stream (row chunking), both-sides-big shapes grace-hash
# partition (try_partitioned), and default join tiles clamp to the
# quota with grow-on-proof. Kept as a hook for future exclusions.
_UNSTREAMABLE: list = []


def _run_tier(sf: str, quota: str | None, extra: list | None = None) -> None:
    env = dict(os.environ)
    env["TIDB_TPU_TPCH_SF"] = sf
    if quota:
        env["TIDB_TPU_TPCH_QUOTA"] = quota
    else:
        env.pop("TIDB_TPU_TPCH_QUOTA", None)
    env.pop("RUN_SLOW", None)  # the child runs the default tier only
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_tpch_sql.py", "-q",
         *(extra or [])],
        cwd=_REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=5400,
    )
    if proc.returncode != 0:
        tail = "\n".join((proc.stdout or "").splitlines()[-40:])
        raise AssertionError(
            f"SF{sf} tier failed (quota={quota}):\n{tail}\n{proc.stderr[-2000:]}"
        )


def test_tpch22_sf01_small_quota():
    """The streaming-capable ladder queries at SF0.1 under a quota that
    forces the streamed aggregation / staged sort paths to engage."""
    sf = os.environ.get("TIDB_TPU_SCALE22_SF", "0.1")
    quota = os.environ.get("TIDB_TPU_SCALE22_QUOTA", str(48 << 20))
    _run_tier(
        sf,
        quota,
        extra=[
            f"--deselect=tests/test_tpch_sql.py::{t}"
            for t in _UNSTREAMABLE
        ],
    )


def test_tpch22_sf01_default_quota():
    """Same 22 queries at SF0.1 with the default quota: the in-HBM path
    at a size where tiling decisions matter. Parity across BOTH quota
    tiers means the spill path and the resident path agree with the
    oracles independently."""
    sf = os.environ.get("TIDB_TPU_SCALE22_SF", "0.1")
    _run_tier(sf, None)
