"""Benchmark: TPC-H on the device engine vs a vectorized-numpy CPU baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference baseline (BASELINE.md) is TiDB's own embedded CPU engine
(unistore/mocktikv vectorized coprocessor); a vectorized numpy
implementation of the same query over the same data stands in for it
here (same columnar layout, single CPU core — generous to the baseline
since numpy's C kernels are at least as fast as the Go engine's
per-chunk loops).

Robustness (round-2 hardening): the default invocation is a *supervisor*
that never imports jax itself. It runs the measurement in a child
process; if the TPU/axon backend fails to initialize or crashes
mid-run (round 1 died with "Unable to initialize backend 'axon'"), it
retries once and then falls back to a pure-CPU child. Whatever happens,
the supervisor prints the JSON result line — annotated with the backend
actually used and per-attempt diagnostics — and exits 0 as long as any
measurement succeeded.

Usage: python bench.py [--sf 1.0] [--query q1|q6|q18] [--repeat 5] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

Q1_SQL = (
    "select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, "
    "sum(l_extendedprice) as sum_base_price, "
    "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
    "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
    "avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price, "
    "avg(l_discount) as avg_disc, count(*) as count_order "
    "from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day "
    "group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"
)
Q6_SQL = (
    "select sum(l_extendedprice * l_discount) as revenue from lineitem "
    "where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01' "
    "and l_discount between 0.05 and 0.07 and l_quantity < 24"
)
Q18_SQL = (
    "select o_orderkey, sum(l_quantity) from lineitem, orders "
    "where o_orderkey = l_orderkey "
    "group by o_orderkey having sum(l_quantity) > 1250 "
    "order by sum(l_quantity) desc limit 100"
)
Q5_SQL = (
    "select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue "
    "from customer, orders, lineitem, supplier, nation, region "
    "where c_custkey = o_custkey and l_orderkey = o_orderkey "
    "and l_suppkey = s_suppkey and c_nationkey = s_nationkey "
    "and s_nationkey = n_nationkey and n_regionkey = r_regionkey "
    "and r_name = 'ASIA' "
    "and o_orderdate >= date '1994-01-01' and o_orderdate < date '1995-01-01' "
    "group by n_name order by revenue desc"
)
QUERIES = {"q1": Q1_SQL, "q5": Q5_SQL, "q6": Q6_SQL, "q18": Q18_SQL}
# ladder #5: TPC-DS Q95 (correlated subqueries + multi-join)
_TABLES = {
    "q1": ["orders", "lineitem"],
    "q6": ["orders", "lineitem"],
    "q18": ["orders", "lineitem"],
    "q5": ["orders", "lineitem", "customer", "supplier", "nation", "region"],
}


# ---------------------------------------------------------------------------
# numpy oracle/baseline kernels (child-side)
# ---------------------------------------------------------------------------


def numpy_q1(np, blk, cutoff):
    ship = blk["l_shipdate"]
    m = ship <= cutoff
    rf = blk["l_returnflag"][m].astype(np.int64)
    ls = blk["l_linestatus"][m].astype(np.int64)
    qty = blk["l_quantity"][m]
    price = blk["l_extendedprice"][m]
    disc = blk["l_discount"][m]
    tax = blk["l_tax"][m]
    key = rf * 2 + ls
    nk = 6
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    out = {
        "sum_qty": np.bincount(key, qty, minlength=nk),
        "sum_base": np.bincount(key, price, minlength=nk),
        "sum_disc": np.bincount(key, disc_price, minlength=nk),
        "sum_charge": np.bincount(key, charge, minlength=nk),
        "cnt": np.bincount(key, minlength=nk),
    }
    out["avg_qty"] = out["sum_qty"] / np.maximum(out["cnt"], 1)
    out["avg_base"] = out["sum_base"] / np.maximum(out["cnt"], 1)
    return out


def numpy_q6(np, blk, d0, d1):
    ship = blk["l_shipdate"]
    m = (
        (ship >= d0)
        & (ship < d1)
        & (blk["l_discount"] >= 5)
        & (blk["l_discount"] <= 7)
        & (blk["l_quantity"] < 2400)
    )
    return (blk["l_extendedprice"][m] * blk["l_discount"][m]).sum()


def numpy_q18(np, blk, thresh):
    ok = blk["l_orderkey"]
    qty = blk["l_quantity"]
    sums = np.bincount(ok, qty)
    big = np.nonzero(sums > thresh)[0]
    return big, sums[big]


def numpy_q5(np, cat, d0, d1):
    """Vectorized Q5 over raw columns (dense 1..N keys -> array lookups)."""

    def cols(t):
        tt = cat.table("tpch", t)
        b = tt.blocks()[0]
        return {n: c for n, c in b.columns.items()}

    reg = cols("region")
    nat = cols("nation")
    cust = cols("customer")
    supp = cols("supplier")
    orders = cols("orders")
    li = cols("lineitem")
    asia_code = np.searchsorted(
        np.asarray(reg["r_name"].dictionary, dtype=object), "ASIA"
    )
    asia = set(reg["r_regionkey"].data[reg["r_name"].data == asia_code].tolist())
    nat_in = np.array([rk in asia for rk in nat["n_regionkey"].data])
    n_nat = len(nat_in)
    cust_nation = np.zeros(int(cust["c_custkey"].data.max()) + 1, dtype=np.int64)
    cust_nation[cust["c_custkey"].data] = cust["c_nationkey"].data
    supp_nation = np.zeros(int(supp["s_suppkey"].data.max()) + 1, dtype=np.int64)
    supp_nation[supp["s_suppkey"].data] = supp["s_nationkey"].data
    om = (orders["o_orderdate"].data >= d0) & (orders["o_orderdate"].data < d1)
    ord_cust = np.zeros(int(orders["o_orderkey"].data.max()) + 2, dtype=np.int64)
    ord_ok = np.zeros(int(orders["o_orderkey"].data.max()) + 2, dtype=bool)
    ord_cust[orders["o_orderkey"].data[om]] = orders["o_custkey"].data[om]
    ord_ok[orders["o_orderkey"].data[om]] = True
    lo = li["l_orderkey"].data
    ls = li["l_suppkey"].data
    cn = cust_nation[ord_cust[lo]]
    sn = supp_nation[ls]
    m = ord_ok[lo] & (cn == sn) & nat_in[np.clip(sn, 0, n_nat - 1)]
    rev = li["l_extendedprice"].data[m] * (100 - li["l_discount"].data[m])
    return np.bincount(sn[m], rev, minlength=n_nat)


# ---------------------------------------------------------------------------
# child: actually measure (imports jax via tidb_tpu)
# ---------------------------------------------------------------------------


def _force_cpu_in_process() -> None:
    """Make this interpreter CPU-only even though sitecustomize may have
    registered a TPU-tunnel PJRT plugin already."""
    from tidb_tpu.utils.backend import force_cpu

    force_cpu()


def _phase(name: str) -> None:
    """Per-phase progress marker on stderr, flushed immediately: when the
    supervisor kills a hung child it reports the LAST phase reached, so a
    timeout distinguishes 'tunnel init hung' from 'first jit too slow'
    (round-2 verdict: the 900s TPU timeout was untriaged)."""
    print(f"[phase {time.strftime('%H:%M:%S')}] {name}", file=sys.stderr, flush=True)


def _metrics_snapshot() -> dict:
    """{metric_name: (kind, value)} view of the engine registry."""
    from tidb_tpu.utils.metrics import REGISTRY

    return {name: (kind, val) for name, kind, val in REGISTRY.rows()}


def _metrics_delta(before: dict, after: dict) -> dict:
    """Registry movement across the benchmarked query: what the engine
    actually did (jit compiles, retraces, transfer bytes, cache hits)
    alongside the latency headline. Counters/histograms report the
    delta; gauges (e.g. device-mem high-water — a lifetime max that may
    not move during the measured window) report their absolute value."""
    out = {}
    for name, (kind, v) in sorted(after.items()):
        if kind == "gauge":
            if v:
                out[name] = round(v, 6)
            continue
        d = v - before.get(name, ("", 0.0))[1]
        if d:
            out[name] = round(d, 6)
    return out


def _emit_metrics(args, result, before: dict, after=None) -> None:
    """Stamp the per-query registry delta into result.detail and, with
    --metrics-out, snapshot it to a JSON file next to the bench output."""
    delta = _metrics_delta(before, after if after is not None else _metrics_snapshot())
    result.setdefault("detail", {})["engine_metrics"] = delta
    if getattr(args, "metrics_out", None):
        with open(args.metrics_out, "w") as f:
            json.dump(
                {
                    "query": args.query,
                    "sf": args.sf,
                    "metrics_delta": delta,
                },
                f, indent=1,
            )
    _write_flight_out(args)
    _write_timeline_out(args)


def _start_timeline(args) -> bool:
    """Arm the fleet timeline tracer when --timeline-out asked for a
    capture (any bench mode). Returns whether a capture is live."""
    if not getattr(args, "timeline_out", None):
        return False
    from tidb_tpu.obs.timeline import TIMELINE

    TIMELINE.start()
    return True


def _write_timeline_out(args) -> None:
    """--timeline-out: dump the captured fleet timeline as Chrome
    trace-event JSON (open the file in Perfetto / chrome://tracing).
    One process track per host, thread tracks per session/worker task,
    counter tracks from the sampled gauges."""
    path = getattr(args, "timeline_out", None)
    if not path:
        return
    from tidb_tpu.obs.timeline import TIMELINE

    TIMELINE.stop()
    with open(path, "w") as f:
        json.dump(TIMELINE.dump(), f)


def _write_flight_out(args) -> None:
    """--flight-out: snapshot the flight recorder's view of the bench
    run — per-query phase timelines, the per-digest statements summary
    (percentiles + mean phase breakdown + engine columns) and the DCN
    link registry — to a JSON file. The same breakdown
    information_schema serves, captured for the bench ladder."""
    path = getattr(args, "flight_out", None)
    if not path:
        return
    from tidb_tpu.obs.flight import FLIGHT, LINKS
    from tidb_tpu.utils.metrics import STMT_SUMMARY

    with open(path, "w") as f:
        json.dump(
            {
                "flights": FLIGHT.rows(),
                "statements": STMT_SUMMARY.rows_full(),
                "links": LINKS.snapshot(),
            },
            f, indent=1,
        )


def measure(args) -> int:
    if os.environ.get("TIDB_TPU_BENCH_CPU") == "1":
        _force_cpu_in_process()

    _start_timeline(args)

    import numpy as np

    _phase("import tidb_tpu/jax")
    from tidb_tpu.bench import load_tpch
    from tidb_tpu.dtypes import date_to_days
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog

    import jax

    # persistent compilation cache: repeat runs (and the steady-state
    # program after a capacity re-discovery) skip recompiles even across
    # processes — bounds the TPU first-compile cost to one payment
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    _phase("backend init (devices query)")
    # PERF_NOTES forensics: default_backend() returns the PJRT plugin's
    # name — 'axon' through the TPU tunnel — so `== "tpu"` string
    # compares (and provenance records) silently mislabel hardware runs.
    # is_tpu() (Device.platform) is the proven check; keep the raw
    # plugin name alongside for provenance.
    from tidb_tpu.utils.backend import is_tpu

    jax_backend = jax.default_backend()
    backend = "tpu" if is_tpu() else jax_backend
    _phase(f"backend ready: {backend} (pjrt={jax_backend})")

    cat = Catalog()
    t0 = time.perf_counter()
    if args.query == "q95":
        from tidb_tpu.bench.tpcds import Q95_SQL, load_tpcds, numpy_q95

        load_tpcds(cat, sf=args.sf, seed=1)
        gen_s = time.perf_counter() - t0
        sess = Session(cat, db="test")
        # benchmark machines have tens of GB of device/host memory; the
        # conservative 8GB default admission quota is for servers
        sess.execute(f"set tidb_mem_quota_query = {64 << 30}")
        nrows = cat.table("test", "web_sales").nrows
        sql = Q95_SQL
        m0 = _metrics_snapshot()
        sess.execute(sql)  # warmup
        times = []
        for _ in range(args.repeat):
            t0 = time.perf_counter()
            sess.execute(sql)
            times.append(time.perf_counter() - t0)
        dev_s = float(np.median(times))
        m_after = _metrics_snapshot()  # before the baseline, like tpch
        base_times = []
        for _ in range(min(max(args.repeat, 2), 3)):
            t0 = time.perf_counter()
            numpy_q95(cat)
            base_times.append(time.perf_counter() - t0)
        base_s = float(np.median(base_times))
        value = nrows / dev_s
        baseline = nrows / base_s
        result = {
            "metric": f"tpcds_q95_sf{args.sf:g}_rows_per_sec",
            "value": round(value, 1),
            "unit": "rows/s",
            "vs_baseline": round(value / baseline, 3),
            "detail": {
                "rows": nrows,
                "device_median_s": round(dev_s, 4),
                "numpy_baseline_s": round(base_s, 4),
                "datagen_s": round(gen_s, 2),
                "repeat": args.repeat,
                "backend": backend,
                "pjrt_backend": jax_backend,
            },
        }
        _emit_metrics(args, result, m0, m_after)
        print(json.dumps(result))
        return 0
    tables = _TABLES[args.query]
    _phase("datagen")
    load_tpch(cat, sf=args.sf, tables=tables, seed=1)
    gen_s = time.perf_counter() - t0
    sess = Session(cat, db="tpch")
    sess.execute(f"set tidb_mem_quota_query = {64 << 30}")
    _phase("analyze tables")
    for tname in tables:
        # reference benchmark methodology: ANALYZE before measuring so
        # the CBO sizes join tiles from real stats
        sess.execute(f"analyze table {tname}")
    li = cat.table("tpch", "lineitem")
    nrows = li.nrows

    sql = QUERIES[args.query]

    # device engine (includes host->device on first run; cached after)
    _phase("warmup execute (h2d + discovery + first jit)")
    m0 = _metrics_snapshot()
    sess.execute(sql)  # warmup: compile + scan cache
    _phase("steady-state runs")
    times = []
    for _ in range(args.repeat):
        t0 = time.perf_counter()
        sess.execute(sql)
        times.append(time.perf_counter() - t0)
    dev_s = float(np.median(times))
    m_after = _metrics_snapshot()
    _phase("numpy baseline")

    # numpy baseline over the same host-resident columns
    blk = {}
    b = li.blocks()[0]
    for c in (
        "l_shipdate l_returnflag l_linestatus l_quantity l_extendedprice "
        "l_discount l_tax l_orderkey".split()
    ):
        blk[c] = b.columns[c].data
    base_times = []
    cutoff = int(date_to_days("1998-12-01")) - 90
    d0, d1 = int(date_to_days("1994-01-01")), int(date_to_days("1995-01-01"))
    for _ in range(min(max(args.repeat, 2), 3)):
        t0 = time.perf_counter()
        if args.query == "q1":
            numpy_q1(np, blk, cutoff)
        elif args.query == "q6":
            numpy_q6(np, blk, d0, d1)
        elif args.query == "q5":
            numpy_q5(np, cat, d0, d1)
        else:
            numpy_q18(np, blk, 125000)
        base_times.append(time.perf_counter() - t0)
    base_s = float(np.median(base_times))

    value = nrows / dev_s
    baseline = nrows / base_s
    result = {
        "metric": f"tpch_{args.query}_sf{args.sf:g}_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(value / baseline, 3),
        "detail": {
            "rows": nrows,
            "device_median_s": round(dev_s, 4),
            "numpy_baseline_s": round(base_s, 4),
            "datagen_s": round(gen_s, 2),
            "repeat": args.repeat,
            "backend": backend,
            "pjrt_backend": jax_backend,
        },
    }
    _emit_metrics(args, result, m0, m_after)
    print(json.dumps(result))
    return 0


# ---------------------------------------------------------------------------
# supervisor: run the measurement in a child, retry, fall back to CPU
# ---------------------------------------------------------------------------


def _run_child(argv, env, timeout_s):
    """Run one measurement attempt; return (result_dict|None, attempt_info)."""
    t0 = time.perf_counter()
    info = {"backend": "cpu" if env.get("TIDB_TPU_BENCH_CPU") == "1" else "tpu"}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--_measure", *argv],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        info["rc"] = proc.returncode
        info["seconds"] = round(time.perf_counter() - t0, 1)
        if proc.returncode == 0:
            for line in reversed(proc.stdout.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        return json.loads(line), info
                    except json.JSONDecodeError:
                        continue
            info["error"] = "child exited 0 but printed no JSON"
        else:
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            info["error"] = " | ".join(tail[-4:])[-800:]
    except subprocess.TimeoutExpired as te:
        info["rc"] = -1
        info["seconds"] = round(time.perf_counter() - t0, 1)
        # report the last phase marker the child reached: distinguishes a
        # hung backend/tunnel init from a too-slow first compile
        last_phase = None
        try:
            err = te.stderr or b""
            if isinstance(err, bytes):
                err = err.decode("utf-8", "replace")
            for line in err.splitlines():
                if line.startswith("[phase "):
                    last_phase = line
        except Exception:
            pass
        info["error"] = f"timeout after {timeout_s}s"
        if last_phase:
            info["last_phase"] = last_phase
    except Exception as exc:  # supervisor must never die
        info["rc"] = -2
        info["seconds"] = round(time.perf_counter() - t0, 1)
        info["error"] = f"{type(exc).__name__}: {exc}"
    return None, info


_TPU_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_CACHE.json")


def _code_version() -> str:
    """Current commit (+dirty marker) — cached TPU numbers from other
    code versions must not be reported for this one."""
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        h = subprocess.run(
            ["git", "-C", repo, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip()
        dirty = subprocess.run(
            ["git", "-C", repo, "status", "--porcelain", "--untracked-files=no"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip()
        return h + ("+dirty" if dirty else "") if h else "unknown"
    except Exception:
        return "unknown"


#: working-tree dirt that does NOT change engine code: the capture
#: loop's own artifacts and the driver's bookkeeping
_BENIGN_DIRT = (
    "BENCH_TPU_CACHE.json", "PROGRESS.jsonl", "PALLAS_TPU.json",
)


def _dirty_paths():
    try:
        proc = subprocess.run(
            ["git", "status", "--porcelain"],
            capture_output=True, timeout=10, cwd=os.path.dirname(
                os.path.abspath(__file__)
            ),
        )
        return sorted(
            line[3:].strip()
            for line in proc.stdout.decode().splitlines()
            if line.strip()
        )
    except Exception:
        return None  # unknown: treated as NOT benign


def _cache_key(args) -> str:
    return f"{args.query}_sf{args.sf:g}"


def _load_tpu_cache(args, exact_only: bool = False):
    """Most recent successful real-TPU measurement of this (query, sf),
    captured by an earlier bench run while the TPU tunnel was up.
    exact_only=True returns None unless the REQUESTED sf is cached —
    used to decide whether the cache may be the HEADLINE: a cached
    capture at a different sf (or an old code version) rides along as
    detail.stale_tpu_reference instead, and the headline is measured
    LIVE at the requested config (round-4 verdict: a stale
    different-config capture must not be the headline)."""
    try:
        with open(_TPU_CACHE) as f:
            cache = json.load(f)
    except Exception:
        return None
    exact = cache.get(_cache_key(args))
    if exact is not None:
        return exact
    if exact_only:
        return None
    prefix = f"{args.query}_sf"
    best_sf, best = None, None
    for k, v in cache.items():
        if not k.startswith(prefix):
            continue
        try:
            sf = float(k[len(prefix):])
        except ValueError:
            continue
        if best_sf is None or sf > best_sf:
            best_sf, best = sf, v
    return best


def _store_tpu_cache(args, result) -> None:
    try:
        cache = {}
        if os.path.exists(_TPU_CACHE):
            with open(_TPU_CACHE) as f:
                cache = json.load(f)
        entry = dict(result)
        d = entry.setdefault("detail", {})
        d["captured_unix"] = int(time.time())
        d["captured_at_version"] = _code_version()
        d["captured_dirty_paths"] = _dirty_paths()
        cache[_cache_key(args)] = entry
        with open(_TPU_CACHE, "w") as f:
            json.dump(cache, f, indent=1)
    except Exception:
        pass  # caching is best-effort; never fail the bench over it


def _tpu_tunnel_up(timeout_s: int = 90) -> bool:
    """Cheap probe: can a fresh process see the TPU at all? The tunnel
    flaps; when it's down, jax.devices() hangs forever — probing for
    90s beats burning the full measurement timeout to learn the same
    thing (BENCH_r02's 900s mystery timeout, diagnosed: backend init)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True,
            timeout=timeout_s,
        )
        return proc.returncode == 0
    except Exception:
        return False


def _cached_tpu_result(args, attempts, exact_only: bool = False):
    """The most recent real-TPU measurement of this (query, sf), dressed
    with full provenance (the measurement's code version vs the code
    being benchmarked now, plus the failed attempts that led here).
    exact_only=True additionally requires the capture's CODE VERSION to
    match HEAD — only a same-config, same-code hardware capture may be
    the headline; anything staler becomes detail.stale_tpu_reference
    under a live measurement."""
    cached = _load_tpu_cache(args, exact_only=exact_only)
    if cached is None:
        return None
    cur_v = _code_version()
    if exact_only:
        det = cached.get("detail", {})
        cap_v = det.get("captured_at_version")
        # same COMMIT qualifies even when the dirty flags differ — but
        # ONLY when the capture-time dirt was the capture loop's own
        # artifacts (recorded at store time and checked against the
        # allowlist): a capture taken with modified engine code must
        # never be the headline for the committed code.
        if cap_v is None or cap_v.split("+")[0] != cur_v.split("+")[0]:
            return None
        if "+dirty" in cap_v and cap_v != cur_v:
            dirt = det.get("captured_dirty_paths")
            if dirt is None or any(
                p not in _BENIGN_DIRT for p in dirt
            ):
                return None
    result = dict(cached)
    d = dict(result.get("detail", {}))
    d["cached_tpu_result"] = True
    d["current_version"] = cur_v
    d["version_match"] = d.get("captured_at_version") == d["current_version"]
    d["tunnel_attempts_now"] = attempts
    result["detail"] = d
    return result


def _result_is_tpu(obj) -> bool:
    """Was this result (raw, or a driver wrapper with 'parsed') a real
    hardware capture — not a CPU fallback, not marked fallback?"""
    if not isinstance(obj, dict):
        return False
    detail = obj.get("detail")
    if detail is None and isinstance(obj.get("parsed"), dict):
        detail = obj["parsed"].get("detail")
    detail = detail or {}
    return detail.get("backend") == "tpu" and not detail.get("fallback")


def _write_out(args, result) -> int:
    """Write the result to --out with backend provenance, refusing to
    overwrite a real-TPU capture with a CPU-fallback run unless
    --allow-fallback (the BENCH_r05 mixup: a CPU fallback silently
    became the official capture). Fallback captures written with
    --allow-fallback are marked {"fallback": true} so no consumer can
    mistake them for hardware numbers. Returns process exit code."""
    detail = result.setdefault("detail", {})
    if detail.get("backend") != "tpu" and not args.cpu:
        # TPU was requested but CPU ran: a fallback capture. A
        # deliberate --cpu baseline is labeled by its backend field
        # alone — this flag must agree with backend_provenance.fallback.
        detail["fallback"] = True
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                existing = json.load(f)
        except Exception:
            existing = None
        if (
            _result_is_tpu(existing)
            and not _result_is_tpu(result)
            and not args.allow_fallback
        ):
            print(
                f"REFUSING to overwrite TPU capture {args.out} with a "
                f"{detail.get('backend', '?')} fallback run; pass "
                "--allow-fallback to mark-and-overwrite",
                file=sys.stderr,
            )
            return 1
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    return 0


def supervise(args, passthrough) -> int:
    attempts = []
    tpu_timeout = int(os.environ.get("TIDB_TPU_BENCH_TIMEOUT", "900"))

    plans = []
    if not args.cpu:
        if _tpu_tunnel_up():
            plans.append(("tpu", tpu_timeout))
        else:
            attempts.append(
                {
                    "backend": "tpu",
                    "rc": -1,
                    "seconds": 0,
                    "error": "tunnel probe failed: jax.devices() hung/errored",
                }
            )
            cached = _cached_tpu_result(args, attempts, exact_only=True)
            if cached is not None:
                print(json.dumps(cached))
                return 0
    plans.append(("cpu", tpu_timeout))

    result = None
    for i, (backend, timeout_s) in enumerate(plans):
        env = dict(os.environ)
        if backend == "cpu":
            env["TIDB_TPU_BENCH_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
        result, info = _run_child(passthrough, env, timeout_s)
        attempts.append(info)
        if result is not None:
            break
        # A fast TPU failure is likely a transient tunnel/init error:
        # retry once before giving up on the backend.
        if backend == "tpu" and info.get("seconds", 0) < 120 and i == 0:
            time.sleep(10)
            result, info2 = _run_child(passthrough, env, timeout_s)
            attempts.append(info2)
            if result is not None:
                break
        if backend == "tpu" and result is None:
            # The TPU tunnel flaps (round 1 died on it entirely): fall
            # back to the cached hardware measurement at this exact
            # config if one exists.
            cached = _cached_tpu_result(args, attempts, exact_only=True)
            if cached is not None:
                result = cached
                break

    if result is None:
        print(
            json.dumps(
                {
                    "metric": f"tpch_{args.query}_sf{args.sf:g}_rows_per_sec",
                    "value": 0,
                    "unit": "rows/s",
                    "vs_baseline": 0,
                    "detail": {"error": "all attempts failed", "attempts": attempts},
                }
            )
        )
        return 1

    detail = result.setdefault("detail", {})
    detail["attempts"] = attempts
    # backend provenance stamped into every emitted result (and thus
    # every BENCH_*.json the driver or --out captures): what actually
    # ran, the raw PJRT plugin name, and the code version measured
    detail["backend_provenance"] = {
        "backend": detail.get("backend"),
        "pjrt_backend": detail.get("pjrt_backend"),
        "code_version": _code_version(),
        "captured_unix": int(time.time()),
        "fallback": detail.get("backend") != "tpu" and not args.cpu,
    }
    if detail.get("backend") == "tpu" and not detail.get("cached_tpu_result"):
        _store_tpu_cache(args, result)
    elif detail.get("backend") != "tpu":
        # a stale/different-config hardware capture rides along as a
        # labeled REFERENCE, never as the headline
        ref = _load_tpu_cache(args)
        if ref is not None:
            detail["stale_tpu_reference"] = {
                "metric": ref.get("metric"),
                "value": ref.get("value"),
                "vs_baseline": ref.get("vs_baseline"),
                "captured_at_version": ref.get("detail", {}).get(
                    "captured_at_version"
                ),
            }
    rc = 0
    if args.out:
        rc = _write_out(args, result)
    print(json.dumps(result))
    return rc


def measure_multihost_shuffle(args) -> int:
    """Multihost shuffle-join scenario: a 2-worker x 4-device CPU
    dryrun runs one repartition-join query BOTH ways — partial-agg
    staging through the coordinator vs direct worker-to-worker tunnels
    — and records where the inter-host bytes actually went
    (bytes_over_coordinator vs bytes_over_tunnels) alongside the
    timings. This is a DATA-PLANE benchmark, deliberately CPU (the
    workers are subprocesses; backend provenance is stamped like every
    other result so no consumer can mistake it for a hardware
    capture)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import re
    import statistics

    timeline_on = _start_timeline(args)

    from tidb_tpu.bench import load_tpch
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.parser.sqlparse import parse
    from tidb_tpu.planner.logical import build_query
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog
    from tidb_tpu.utils.metrics import REGISTRY

    # 2 CPU worker processes can't chew SF10: cap the dryrun scale
    sf = args.sf if args.sf <= 1.0 else 0.02
    seed = 3
    workers = []
    try:
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        if getattr(args, "racecheck", False):
            # the whole data plane (ShuffleStore cv, tunnel cv, exec
            # rlock, metrics) runs order-tracked in the workers: a
            # clean capture PROVES no lock-order inversion fired under
            # real produce/push/decode/stage interleaving
            env["TIDB_TPU_RACECHECK"] = "1"
        ports = []
        for _ in range(2):
            p = subprocess.Popen(
                [
                    sys.executable, "-m", "tidb_tpu.parallel.dcn_worker",
                    "--port", "0", "--mesh-devices", "4",
                    "--tpch-sf", str(sf), "--seed", str(seed),
                    "--tables", "orders,lineitem",
                ],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            )
            workers.append(p)
            line = p.stdout.readline()
            m = re.match(r"DCN_WORKER_READY port=(\d+)", line)
            if not m:
                # drain the merged stdout/stderr so a startup crash
                # (jax init, import error) is diagnosable
                try:
                    rest, _ = p.communicate(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    rest = ""
                raise RuntimeError(
                    f"worker not ready: {line!r}\n{rest[-3000:]}"
                )
            ports.append(int(m.group(1)))

        cat = Catalog()
        load_tpch(cat, sf=sf, seed=seed, tables=["orders", "lineitem"])
        sess = Session(cat, db="tpch")
        # a true repartition-join shape: neither side pre-aggregates
        # below the join (Q18's planner rewrites the agg under the
        # join, which removes the shuffle cut entirely)
        sql = (
            "select o_orderpriority, count(*), sum(l_extendedprice) "
            "from orders join lineitem on o_orderkey = l_orderkey "
            "where l_quantity < 24 "
            "group by o_orderpriority order by o_orderpriority"
        )
        plan = build_query(
            parse(sql)[0], cat, "tpch", sess._scalar_subquery
        )

        def _reg_total(prefix):
            return sum(
                v for n, _k, v in REGISTRY.rows() if n.startswith(prefix)
            )

        def run_mode(mode, codec="binary", pipeline=True):
            sched = DCNFragmentScheduler(
                [("127.0.0.1", pt) for pt in ports],
                catalog=cat, shuffle_mode=mode, shuffle_codec=codec,
                shuffle_pipeline=pipeline,
            )
            try:
                # one untimed warmup: the workers' persistent executors
                # pay the producer/consumer XLA compile here, so the
                # timed repeats (and the mode/pipeline A/Bs) compare
                # steady-state data-plane behavior, not compile order
                sched.execute_plan(plan)
                before = {
                    p: _reg_total(p)
                    for p in (
                        "tidbtpu_dcn_bytes_staged",
                        "tidbtpu_shuffle_bytes_total",
                        "tidbtpu_shuffle_encode_seconds",
                        "tidbtpu_shuffle_decode_seconds",
                        "tidbtpu_shuffle_wait_idle_seconds",
                    )
                }
                times, rows = [], []
                rows_tunneled = 0
                ttff = 0.0
                stage_walls = []
                for _ in range(max(args.repeat, 1)):
                    t0 = time.perf_counter()
                    _cols, out = sched.execute_plan(plan)
                    times.append(time.perf_counter() - t0)
                    rows = out
                    if mode != "never":
                        # summed across repeats — the byte counters
                        # below accumulate across repeats too
                        lq = sched.last_query or {}
                        sh = lq.get("shuffle", {})
                        rows_tunneled += sh.get("rows_tunneled", 0)
                        ttff = max(ttff, sh.get("ttff_s", 0.0))
                        # the shuffle STAGE wall-clock: the slowest
                        # partition's produce+push+wait+stage+consume
                        # on the workers (excludes dispatch RPC and the
                        # coordinator's final merge, identical in both
                        # pipeline modes)
                        stage_walls.append(max(
                            (f.get("exec_s", 0.0)
                             for f in lq.get("fragments", [])),
                            default=0.0,
                        ))
                delta = {
                    p: _reg_total(p) - v0 for p, v0 in before.items()
                }
                tunneled = delta["tidbtpu_shuffle_bytes_total"]
                return {
                    "seconds": statistics.median(times),
                    "stage_seconds": (
                        statistics.median(stage_walls)
                        if stage_walls else None
                    ),
                    "rows": len(rows),
                    "codec": codec if mode != "never" else None,
                    "pipeline": pipeline if mode != "never" else None,
                    "bytes_over_coordinator":
                        delta["tidbtpu_dcn_bytes_staged"],
                    "bytes_over_tunnels": tunneled,
                    # wire efficiency of the exchange codec (the A/B
                    # PERF_NOTES "Shuffle wire format" cites): counters
                    # ship back from the worker processes via the
                    # piggybacked registry deltas
                    "bytes_per_row": (
                        round(tunneled / rows_tunneled, 2)
                        if rows_tunneled else None
                    ),
                    "encode_seconds": round(
                        delta["tidbtpu_shuffle_encode_seconds"], 6
                    ),
                    "decode_seconds": round(
                        delta["tidbtpu_shuffle_decode_seconds"], 6
                    ),
                    "wait_idle_seconds": round(
                        delta["tidbtpu_shuffle_wait_idle_seconds"], 6
                    ),
                    "time_to_first_frame_seconds": round(ttff, 6),
                    "rows_tunneled": rows_tunneled,
                    "result": rows,
                }
            finally:
                sched.close()

        staged = run_mode("never")
        tunnel = run_mode("always")                  # binary, pipelined
        barrier = run_mode("always", pipeline=False)  # pipeline A/B ref
        tunnel_json = run_mode("always", codec="json")    # A/B reference

        def run_pipeline_pairs(pairs):
            """Interleaved pipelined/barrier timing pairs on two live
            schedulers: block-sequential A/B timing is dominated by
            system drift at this stage scale (~10^-1 s); alternating
            runs sample the same machine state for both modes."""
            scheds = {
                mode: DCNFragmentScheduler(
                    [("127.0.0.1", pt) for pt in ports],
                    catalog=cat, shuffle_mode="always",
                    shuffle_pipeline=(mode == "pipelined"),
                )
                for mode in ("pipelined", "barrier")
            }
            out = {
                mode: {"wall": [], "stage": [], "idle": 0.0, "ttff": 0.0}
                for mode in scheds
            }
            try:
                for sched in scheds.values():  # warm both
                    sched.execute_plan(plan)
                for _ in range(pairs):
                    for mode, sched in scheds.items():
                        t0 = time.perf_counter()
                        _cols, res = sched.execute_plan(plan)
                        out[mode]["wall"].append(
                            time.perf_counter() - t0
                        )
                        assert res == staged["result"], (
                            f"pipeline A/B parity broke ({mode})"
                        )
                        lq = sched.last_query or {}
                        sh = lq.get("shuffle", {})
                        out[mode]["stage"].append(max(
                            (f.get("exec_s", 0.0)
                             for f in lq.get("fragments", [])),
                            default=0.0,
                        ))
                        out[mode]["idle"] += sh.get("wait_idle_s", 0.0)
                        out[mode]["ttff"] = max(
                            out[mode]["ttff"], sh.get("ttff_s", 0.0)
                        )
            finally:
                for sched in scheds.values():
                    sched.close()
            return out

        def run_dag_ab(pairs):
            """Shuffle-DAG A/B (ISSUE 11): the join -> RE-KEYED
            DISTINCT group-by -> ORDER BY LIMIT query runs CHAINED
            (hash join stage -> held-output re-key stage -> range
            top-K stage; both sides fragment-sliced) vs the SINGLE-CUT
            group-by baseline (only lineitem sliced — every host
            re-scans the whole orders side). Interleaved pairs, same
            workers; reports wall + per-host scanned base rows +
            per-host produced exchange bytes."""
            dag_sql = (
                "select o_orderpriority, count(distinct l_suppkey), "
                "sum(l_extendedprice) from orders join lineitem "
                "on o_orderkey = l_orderkey group by o_orderpriority "
                "order by sum(l_extendedprice) desc limit 3"
            )
            dag_plan = build_query(
                parse(dag_sql)[0], cat, "tpch", sess._scalar_subquery
            )
            scheds = {
                "chained": DCNFragmentScheduler(
                    [("127.0.0.1", pt) for pt in ports],
                    catalog=cat, shuffle_mode="always",
                    shuffle_dag="always",
                ),
                "single_cut": DCNFragmentScheduler(
                    [("127.0.0.1", pt) for pt in ports],
                    catalog=cat, shuffle_mode="always",
                    shuffle_dag="never",
                ),
            }
            out = {
                mode: {
                    "wall": [], "scan_rows_per_host": 0,
                    "bytes_per_host": 0, "stages": 0,
                }
                for mode in scheds
            }

            def scan_bytes_per_host(sched):
                """Per-host base-table PRODUCE bytes of this
                scheduler's chosen cut: every Scan it executes per
                host (sliced scans read nrows/2, re-scanned unsliced
                sides read ALL nrows on EVERY host) times the pruned
                column set at 8 B/col — the scan-work cost the
                chained DAG removes, priced from the plan the
                scheduler actually picked."""
                from tidb_tpu.planner import logical as L

                kind, cut2 = sched._choose_cut(dag_plan)
                sides = (
                    [s for st in cut2.stages for s in st.sides]
                    if kind == "dag" else list(cut2.sides)
                )
                total = 0.0
                for s in sides:
                    if s.frag_scan is None:
                        continue  # re-staged held output: no scan
                    scans = []

                    def walk(p):
                        if isinstance(p, L.Scan):
                            scans.append(p)
                            return
                        for a in ("child", "left", "right"):
                            c = getattr(p, a, None)
                            if c is not None:
                                walk(c)
                        for c in getattr(p, "children", []) or []:
                            walk(c)

                    walk(s.template)
                    for sc in scans:
                        nrows = cat.table(sc.db, sc.table).nrows
                        share = nrows / 2 if sc is s.frag_scan else nrows
                        total += share * 8 * len(sc.columns)
                return int(total)

            ref = None
            try:
                for sched in scheds.values():  # warm (XLA compiles)
                    sched.execute_plan(dag_plan)
                for _ in range(pairs):
                    for mode, sched in scheds.items():
                        t0 = time.perf_counter()
                        _cols, res = sched.execute_plan(dag_plan)
                        out[mode]["wall"].append(
                            time.perf_counter() - t0
                        )
                        if ref is None:
                            ref = res
                        assert res == ref, f"dag A/B parity broke ({mode})"
                        lq = sched.last_query or {}
                        frags = lq.get("fragments", [])
                        by_host = {}
                        for f in frags:
                            h = by_host.setdefault(
                                f.get("host"), [0, 0]
                            )
                            h[0] += int(f.get("scan_rows", 0))
                            h[1] += int(f.get("pushed_bytes", 0))
                        if by_host:
                            out[mode]["scan_rows_per_host"] = max(
                                v[0] for v in by_host.values()
                            )
                            out[mode]["bytes_per_host"] = max(
                                v[1] for v in by_host.values()
                            )
                        out[mode]["stages"] = len(
                            lq.get("shuffle_stages")
                            or ([lq["shuffle"]] if lq.get("shuffle")
                                else [])
                        )
            finally:
                for sched in scheds.values():
                    sched.close()
            ch, sc = out["chained"], out["single_cut"]
            produce_ch = scan_bytes_per_host(scheds["chained"])
            produce_sc = scan_bytes_per_host(scheds["single_cut"])
            return {
                "pairs": pairs,
                "query": dag_sql,
                # per-host base-table produce bytes (pruned columns x
                # slice share): the chained DAG slices BOTH sides; the
                # single cut re-scans the whole unsliced orders side
                # on every host
                "produce_bytes_per_host_chained": produce_ch,
                "produce_bytes_per_host_single_cut": produce_sc,
                "produce_bytes_ratio": round(
                    produce_sc / max(produce_ch, 1), 4
                ),
                "seconds_chained": round(
                    statistics.median(ch["wall"]), 6
                ),
                "seconds_single_cut": round(
                    statistics.median(sc["wall"]), 6
                ),
                "speedup": round(
                    statistics.median(sc["wall"])
                    / max(statistics.median(ch["wall"]), 1e-9), 4
                ),
                "stages_chained": ch["stages"],
                "stages_single_cut": sc["stages"],
                # the headline: scanned base rows per host — the
                # chained DAG slices BOTH sides (~ total/N per host);
                # the single cut re-scans the unsliced orders side on
                # every host
                "scan_rows_per_host_chained": ch["scan_rows_per_host"],
                "scan_rows_per_host_single_cut":
                    sc["scan_rows_per_host"],
                "scan_rows_ratio": round(
                    sc["scan_rows_per_host"]
                    / max(ch["scan_rows_per_host"], 1), 4
                ),
                "bytes_per_host_chained": ch["bytes_per_host"],
                "bytes_per_host_single_cut": sc["bytes_per_host"],
            }

        # flight-recorder attribution through the session routing path
        # (PR 6): the SAME query executed as SQL with the scheduler
        # ATTACHED — statements_summary picks up the worker-reported
        # shuffle phase breakdown, and --flight-out snapshots it
        def run_flight_attributed():
            from tidb_tpu.utils.metrics import STMT_SUMMARY, sql_digest

            sched = DCNFragmentScheduler(
                [("127.0.0.1", pt) for pt in ports],
                catalog=cat, shuffle_mode="always",
            )
            sess.attach_dcn_scheduler(sched)
            try:
                for _ in range(max(args.repeat, 2)):
                    sess.execute(sql)
            finally:
                sess.attach_dcn_scheduler(None)
                sched.close()
            ent = next(
                (
                    e for e in STMT_SUMMARY.rows_full()
                    if e["digest_text"] == sql_digest(sql)
                ),
                None,
            )
            if ent is None:
                return None
            n = max(ent["exec_count"], 1)
            return {
                "exec_count": ent["exec_count"],
                "p50_latency_s": round(ent["p50_latency"], 6),
                "p99_latency_s": round(ent["p99_latency"], 6),
                "avg_phase_seconds": {
                    p: round(v[0] / n, 6)
                    for p, v in sorted(ent["phases"].items())
                },
                "shuffle_bytes": ent["phases"].get(
                    "shuffle-push", (0.0, 0, 0)
                )[1],
                "rows_sent": ent["rows_sent"],
            }

        flight_breakdown = run_flight_attributed()

        def run_feedback_pair():
            """AQE feedback warm/cold pair (ISSUE 15): a join whose
            filtered side collapses far below its static catalog
            estimate runs twice under tidb_tpu_aqe_feedback=on — the
            COLD run plans from static stats (repartition) and
            records the observed side rows; the WARM run's cost model
            seeds from those actuals and switches the edge to
            broadcast (adaptive=feedback, fewer tunnel bytes)."""
            from tidb_tpu.parallel import aqe
            from tidb_tpu.planner.cardinality import CARD_FEEDBACK
            from tidb_tpu.utils.metrics import sql_digest

            q = (
                "select count(*), sum(l_quantity) from lineitem "
                "join orders on l_orderkey = o_orderkey "
                "where o_custkey < 5"
            )
            digest = sql_digest(q)
            CARD_FEEDBACK.reset()
            fb_plan = build_query(
                parse(q)[0], cat, "tpch", sess._scalar_subquery
            )
            sched = DCNFragmentScheduler(
                [("127.0.0.1", pt) for pt in ports],
                catalog=cat, shuffle_mode="always",
                shuffle_dag="never", aqe_feedback=True,
                shuffle_broadcast_rows=max(
                    int(cat.table("tpch", "orders").nrows * 0.2), 64
                ),
            )
            out = {}
            try:
                sched.execute_plan(fb_plan)  # compile warmup
                d0 = aqe.decision_counts().get("feedback", 0.0)
                ref = None
                for phase in ("cold", "warm"):
                    kind, cut = sched._choose_cut(
                        fb_plan, digest=digest
                    )
                    t0 = time.perf_counter()
                    _c, rows = sched.execute_plan(
                        fb_plan, cut_hint=(kind, cut), digest=digest
                    )
                    st = (sched.last_query_mine() or {}).get(
                        "shuffle", {}
                    )
                    if ref is None:
                        ref = rows
                    assert rows == ref, "feedback pair parity broke"
                    out[phase] = {
                        "seconds": round(time.perf_counter() - t0, 6),
                        "modes": [s.mode for s in cut.sides],
                        "adaptive": list(st.get("adaptive") or []),
                        "bytes_tunneled": st.get("bytes_tunneled"),
                    }
                out["feedback_decisions"] = (
                    aqe.decision_counts().get("feedback", 0.0) - d0
                )
                out["changed"] = (
                    out["cold"]["modes"] != out["warm"]["modes"]
                )
                return out
            finally:
                sched.close()

        def run_rf_pairs(pairs):
            """Runtime-filter on/off pairs (ISSUE 19): a repartition
            join whose build side (orders, o_custkey < 5) rejects
            nearly every probe-side lineitem row runs INTERLEAVED on
            two live schedulers — runtime_filter=always vs off — so
            both arms sample the same machine state. The filtered arm
            pays a build-side probe round and the filter broadcast;
            it saves the dropped rows' partition+encode+tunnel bytes.
            Exact row parity is asserted every pair."""
            q = (
                "select count(*), sum(l_extendedprice) from lineitem "
                "join orders on l_orderkey = o_orderkey "
                "where o_custkey < 5"
            )
            rf_plan = build_query(
                parse(q)[0], cat, "tpch", sess._scalar_subquery
            )
            scheds = {
                arm: DCNFragmentScheduler(
                    [("127.0.0.1", pt) for pt in ports],
                    catalog=cat, shuffle_mode="always",
                    shuffle_dag="never",
                    runtime_filter=(
                        "always" if arm == "filtered" else "off"
                    ),
                )
                for arm in ("filtered", "unfiltered")
            }
            out = {
                arm: {"wall": [], "bytes": [], "encode": [],
                      "stage": []}
                for arm in scheds
            }
            rf_info = {}
            try:
                for sched in scheds.values():  # compile warmup
                    sched.execute_plan(rf_plan)
                ref = None
                for _ in range(pairs):
                    for arm, sched in scheds.items():
                        e0 = _reg_total(
                            "tidbtpu_shuffle_encode_seconds"
                        )
                        t0 = time.perf_counter()
                        _c, rows = sched.execute_plan(rf_plan)
                        wall = time.perf_counter() - t0
                        if ref is None:
                            ref = rows
                        assert rows == ref, "rf pair parity broke"
                        lq = sched.last_query_mine() or {}
                        st = lq.get("shuffle", {})
                        rec = out[arm]
                        rec["wall"].append(wall)
                        rec["bytes"].append(
                            st.get("bytes_tunneled", 0)
                        )
                        rec["encode"].append(
                            _reg_total(
                                "tidbtpu_shuffle_encode_seconds"
                            ) - e0
                        )
                        rec["stage"].append(max(
                            (f.get("exec_s", 0.0)
                             for f in lq.get("fragments", [])),
                            default=0.0,
                        ))
                        if arm == "filtered" and st.get("rf"):
                            rf_info = dict(st["rf"])
                f, u = out["filtered"], out["unfiltered"]
                med = statistics.median
                return {
                    "pairs": pairs,
                    "filter_kind": rf_info.get("kind"),
                    "filter_bytes": rf_info.get("nbytes"),
                    # observed keep-rate at the producers (the rf=
                    # sel_obs EXPLAIN field): what fraction of probe
                    # rows the build side actually admitted
                    "observed_selectivity": rf_info.get("sel_obs"),
                    "rows_dropped": rf_info.get("dropped"),
                    "bytes_filtered": med(f["bytes"]),
                    "bytes_unfiltered": med(u["bytes"]),
                    "bytes_ratio": round(
                        med(u["bytes"]) / max(med(f["bytes"]), 1), 4
                    ),
                    "encode_seconds_filtered": round(
                        med(f["encode"]), 6
                    ),
                    "encode_seconds_unfiltered": round(
                        med(u["encode"]), 6
                    ),
                    "stage_seconds_filtered": round(
                        med(f["stage"]), 6
                    ),
                    "stage_seconds_unfiltered": round(
                        med(u["stage"]), 6
                    ),
                    "seconds_filtered": round(med(f["wall"]), 6),
                    "seconds_unfiltered": round(med(u["wall"]), 6),
                    "speedup": round(
                        med(u["wall"]) / max(med(f["wall"]), 1e-9), 4
                    ),
                }
            finally:
                for sched in scheds.values():
                    sched.close()

        feedback_ab = run_feedback_pair()
        runtime_filter_ab = run_rf_pairs(pairs=max(args.repeat, 5))

        ab = run_pipeline_pairs(pairs=max(args.repeat, 5))
        dag_ab = run_dag_ab(pairs=max(args.repeat, 3))
        assert tunnel["result"] == staged["result"], "mode parity broke"
        assert tunnel_json["result"] == staged["result"], (
            "codec parity broke"
        )
        assert barrier["result"] == staged["result"], (
            "pipeline parity broke"
        )
        # pipelined vs barrier A/B (PERF_NOTES "Shuffle pipelining"):
        # same query, same codec, same workers — only the stage shape
        # differs (overlapped produce/push/decode/stage vs the four
        # sequential phases). Row counts must match exactly; tunnel
        # bytes track closely (chunked frames re-prune dictionaries
        # per chunk, so a small delta is framing overhead, not data).
        assert barrier["rows_tunneled"] == tunnel["rows_tunneled"], (
            "pipeline row parity broke"
        )
        pipe, barr = ab["pipelined"], ab["barrier"]
        pipeline_ab = {
            # stage wall-clock (the slowest worker partition's whole
            # produce->push->wait->stage->consume): what pipelining
            # actually restructures — end-to-end seconds additionally
            # carry the dispatch RPC + coordinator final merge common
            # to both modes. Medians over interleaved pairs.
            "pairs": len(pipe["wall"]),
            "stage_seconds_pipelined": round(
                statistics.median(pipe["stage"]), 6
            ),
            "stage_seconds_barrier": round(
                statistics.median(barr["stage"]), 6
            ),
            "stage_speedup": round(
                statistics.median(barr["stage"])
                / max(statistics.median(pipe["stage"]), 1e-9), 4
            ),
            "seconds_pipelined": round(
                statistics.median(pipe["wall"]), 6
            ),
            "seconds_barrier": round(
                statistics.median(barr["wall"]), 6
            ),
            "speedup": round(
                statistics.median(barr["wall"])
                / max(statistics.median(pipe["wall"]), 1e-9), 4
            ),
            "wait_idle_pipelined_s": round(pipe["idle"], 6),
            "wait_idle_barrier_s": round(barr["idle"], 6),
            "ttff_pipelined_s": round(pipe["ttff"], 6),
            "ttff_barrier_s": round(barr["ttff"], 6),
            "rows_tunneled": tunnel["rows_tunneled"],
            "bytes_pipelined": tunnel["bytes_over_tunnels"],
            "bytes_barrier": barrier["bytes_over_tunnels"],
        }
        codec_ab = {
            "bytes_binary": tunnel["bytes_over_tunnels"],
            "bytes_json": tunnel_json["bytes_over_tunnels"],
            "bytes_ratio": round(
                tunnel["bytes_over_tunnels"]
                / max(tunnel_json["bytes_over_tunnels"], 1), 4
            ),
            "encode_seconds_binary": tunnel["encode_seconds"],
            "encode_seconds_json": tunnel_json["encode_seconds"],
            "decode_seconds_binary": tunnel["decode_seconds"],
            "decode_seconds_json": tunnel_json["decode_seconds"],
        }
        nrows_lineitem = cat.table("tpch", "lineitem").nrows
        result = {
            "metric": f"multihost_shuffle_join_sf{sf:g}_rows_per_sec",
            "value": round(nrows_lineitem / tunnel["seconds"], 2),
            "unit": "rows/s",
            "vs_baseline": round(
                staged["seconds"] / tunnel["seconds"], 4
            ),
            "detail": {
                "backend": "cpu",
                "scenario": "multihost_shuffle",
                "workers": 2,
                "mesh_devices": 4,
                "sf": sf,
                "repeat": args.repeat,
                "staged": {
                    k: v for k, v in staged.items() if k != "result"
                },
                "tunneled": {
                    k: v for k, v in tunnel.items() if k != "result"
                },
                "tunneled_barrier": {
                    k: v for k, v in barrier.items() if k != "result"
                },
                "tunneled_json": {
                    k: v for k, v in tunnel_json.items() if k != "result"
                },
                "codec_ab": codec_ab,
                "pipeline_ab": pipeline_ab,
                # ISSUE 11: chained shuffle DAG vs single-cut re-scan
                # (wall + per-host scanned rows + exchange bytes)
                "dag_ab": dag_ab,
                # --racecheck: workers ran with TIDB_TPU_RACECHECK=1
                # (order-tracked locks); a worker inversion raises and
                # fails the run, so True here means the data plane ran
                # clean under the detector
                "racecheck": bool(getattr(args, "racecheck", False)),
                # the flight recorder's per-digest view of this query
                # (phase means, percentiles) — the information_schema.
                # statements_summary breakdown as the bench sees it
                "flight": flight_breakdown,
                # ISSUE 15: AQE feedback warm/cold pair — the warm
                # run's seeded cost model flips repartition to
                # broadcast (adaptive=feedback)
                "feedback_ab": feedback_ab,
                # ISSUE 19: runtime-filter on/off pairs — build-side
                # key summary drops probe rows before partition+encode
                # (tunnel bytes, encode CPU, observed selectivity)
                "runtime_filter_ab": runtime_filter_ab,
                "backend_provenance": {
                    "backend": "cpu",
                    "pjrt_backend": "cpu",
                    "code_version": _code_version(),
                    "captured_unix": int(time.time()),
                    # a deliberate CPU data-plane dryrun, not a TPU
                    # capture that fell back
                    "fallback": False,
                },
            },
        }
        if timeline_on:
            # the trace PROVES the overlap claim: pipelined tasks'
            # produce/push windows intersect, the barrier escape
            # hatch's do not (per-track report from the captured
            # worker events, PERF_NOTES "reading a timeline")
            from tidb_tpu.obs.timeline import (
                TIMELINE,
                shuffle_overlap_report,
            )

            rep = shuffle_overlap_report(TIMELINE.events())
            result["detail"]["timeline"] = {
                "hosts": TIMELINE.dump()["otherData"]["hosts"],
                "events": len(TIMELINE),
                "produce_push_overlap_s_pipelined": round(max(
                    (r["produce_push_overlap_s"]
                     for r in rep.values() if r["pipeline"]),
                    default=0.0,
                ), 6),
                "produce_push_overlap_s_barrier": round(max(
                    (r["produce_push_overlap_s"]
                     for r in rep.values() if not r["pipeline"]),
                    default=0.0,
                ), 6),
            }
    finally:
        for p in workers:
            p.kill()
    _write_flight_out(args)
    _write_timeline_out(args)
    rc = 0
    if args.out:
        args.cpu = True  # deliberate CPU scenario: not a fallback
        rc = _write_out(args, result)
    print(json.dumps(result))
    return rc


def measure_skew(args) -> int:
    """AQE skew ladder (ISSUE 15): a zipf-keyed join+group-by runs at
    2-3 skew exponents over a 4-server in-process fleet, interleaved
    A/B with salting armed (tidb_tpu_shuffle_skew_ratio) vs off, at
    EXACT row parity both arms. Stamps detail.aqe per rung: walls,
    max-partition received rows (the skew the salting removed),
    decisions taken. CPU data-plane scenario (in-process servers: the
    fleet shares one catalog; XLA consumer work releases the GIL, so
    hot-partition serialization is real), provenance-stamped."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import statistics

    import numpy as np

    from tidb_tpu.parallel import aqe
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.parser.sqlparse import parse
    from tidb_tpu.planner.logical import build_query
    from tidb_tpu.server.engine_rpc import EngineServer
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog

    n_rows = int(50_000 * (args.sf if args.sf <= 1.0 else 0.5))
    n_keys = max(n_rows // 50, 16)
    m_hosts = 4
    rungs = (1.1, 1.5, 2.0)
    cat = Catalog()
    sess = Session(cat, db="test")
    rng = np.random.default_rng(7)
    sess.execute("create table skew_dim (k int, g int)")
    sess.execute(
        "insert into skew_dim values "
        + ",".join(f"({k},{k % 16})" for k in range(n_keys))
    )
    ladder = {}
    servers = [EngineServer(cat, port=0) for _ in range(m_hosts)]
    for s in servers:
        s.start_background()
    try:
        for z in rungs:
            # zipf-ranked keys: rank r gets mass ~ 1/r^z (clipped to
            # the key domain); z=2.0 puts ~half the rows on rank 1
            ranks = np.minimum(
                rng.zipf(z, size=n_rows), n_keys
            ).astype(np.int64) - 1
            tbl = f"skew_f_{int(z * 10)}"
            sess.execute(f"create table {tbl} (k int, v int)")
            vals = ",".join(
                f"({int(k)},{i % 97})" for i, k in enumerate(ranks)
            )
            sess.execute(f"insert into {tbl} values {vals}")
            q = (
                f"select g, count(*), sum(v) from {tbl} f "
                "join skew_dim d on f.k = d.k "
                "group by g order by g"
            )
            plan = build_query(
                parse(q)[0], cat, "test", sess._scalar_subquery
            )
            mk = lambda ratio: DCNFragmentScheduler(
                [("127.0.0.1", s.port) for s in servers],
                catalog=cat, shuffle_mode="always",
                shuffle_dag="never", shuffle_wait_timeout_s=60.0,
                shuffle_skew_ratio=ratio, shuffle_skew_salt_k=4,
            )
            scheds = {"salted": mk(1.5), "plain": mk(0.0)}
            entry = {}
            try:
                for arm in scheds.values():
                    arm.execute_plan(plan)  # compile warmup
                walls = {"salted": [], "plain": []}
                stats = {}
                ref = None
                d0 = aqe.decision_counts().get("salted", 0.0)
                for _ in range(max(args.repeat, 3)):
                    for arm, sched in scheds.items():  # interleaved
                        t0 = time.perf_counter()
                        _c, rows = sched.execute_plan(plan)
                        walls[arm].append(time.perf_counter() - t0)
                        if ref is None:
                            ref = rows
                        assert rows == ref, f"z={z} {arm} parity broke"
                        st = (sched.last_query_mine() or {}).get(
                            "shuffle", {}
                        )
                        stats[arm] = st
                for arm in scheds:
                    st = stats[arm]
                    entry[arm] = {
                        "seconds": round(
                            statistics.median(walls[arm]), 6
                        ),
                        "max_partition_rows": max(
                            st.get("part_rows") or [0]
                        ),
                        "skew": st.get("skew"),
                        "adaptive": list(st.get("adaptive") or []),
                        "salt_k": st.get("salted", 0),
                    }
                entry["salted_decisions"] = (
                    aqe.decision_counts().get("salted", 0.0) - d0
                )
                entry["speedup"] = round(
                    entry["plain"]["seconds"]
                    / max(entry["salted"]["seconds"], 1e-9), 4
                )
                entry["rows"] = len(ref)
                entry["query"] = q
            finally:
                for sched in scheds.values():
                    sched.close()
            ladder[f"z{z:g}"] = entry
    finally:
        for s in servers:
            s.shutdown()
    top = ladder[f"z{rungs[-1]:g}"]
    result = {
        "metric": f"aqe_skew_salting_n{n_rows}_rows_per_sec",
        "value": round(n_rows / top["salted"]["seconds"], 2),
        "unit": "rows/s",
        "vs_baseline": top["speedup"],
        "detail": {
            "backend": "cpu",
            "scenario": "aqe_skew_salting",
            "servers": m_hosts,
            "rows": n_rows,
            "keys": n_keys,
            "repeat": args.repeat,
            "aqe": ladder,
            "backend_provenance": {
                "backend": "cpu",
                "pjrt_backend": "cpu",
                "code_version": _code_version(),
                "captured_unix": int(time.time()),
                "fallback": False,
            },
        },
    }
    rc = 0
    if args.out:
        args.cpu = True
        rc = _write_out(args, result)
    print(json.dumps(result))
    return rc


def measure_order_by(args) -> int:
    """Distributed ORDER BY ladder (ISSUE 11): range-partitioned
    exchanges vs the coordinator-sort baseline on a 2-worker x
    4-device CPU dryrun. Each rung runs one ORDER BY (LIMIT) query
    both ways — shuffle_dag="always" (boundary-sampled range exchange,
    per-partition sort/top-K, order-preserving concat) vs
    shuffle_mode="never" (the fragment cut ships EVERY row to the
    coordinator, which re-sorts) — at exact row parity, recording
    walls, rows shipped to the coordinator, and per-partition top-K
    row caps. CPU data-plane scenario, provenance-stamped."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import re
    import statistics

    from tidb_tpu.bench import load_tpch
    from tidb_tpu.parallel.dcn import DCNFragmentScheduler
    from tidb_tpu.parser.sqlparse import parse
    from tidb_tpu.planner.logical import build_query
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog
    from tidb_tpu.utils.metrics import REGISTRY

    sf = args.sf if args.sf <= 1.0 else 0.02
    seed = 3
    workers = []
    try:
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        ports = []
        for _ in range(2):
            p = subprocess.Popen(
                [
                    sys.executable, "-m", "tidb_tpu.parallel.dcn_worker",
                    "--port", "0", "--mesh-devices", "4",
                    "--tpch-sf", str(sf), "--seed", str(seed),
                    "--tables", "orders,lineitem",
                ],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env,
            )
            workers.append(p)
            line = p.stdout.readline()
            m = re.match(r"DCN_WORKER_READY port=(\d+)", line)
            if not m:
                try:
                    rest, _ = p.communicate(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    rest = ""
                raise RuntimeError(
                    f"worker not ready: {line!r}\n{rest[-3000:]}"
                )
            ports.append(int(m.group(1)))

        cat = Catalog()
        load_tpch(cat, sf=sf, seed=seed, tables=["orders", "lineitem"])
        sess = Session(cat, db="tpch")
        #: the ladder: top-K, aggregate-then-order, and a full sort
        RUNGS = [
            ("topk",
             "select l_orderkey, l_extendedprice from lineitem "
             "order by l_extendedprice desc limit 100"),
            ("agg_topk",
             "select l_suppkey, count(*), sum(l_quantity) from "
             "lineitem group by l_suppkey "
             "order by sum(l_quantity) desc limit 10"),
            ("full_sort",
             "select l_extendedprice, l_orderkey from lineitem "
             "order by l_extendedprice"),
        ]

        def _reg_total(prefix):
            return sum(
                v for n, _k, v in REGISTRY.rows() if n.startswith(prefix)
            )

        def run_rung(name, sql):
            plan = build_query(
                parse(sql)[0], cat, "tpch", sess._scalar_subquery
            )
            scheds = {
                "range": DCNFragmentScheduler(
                    [("127.0.0.1", pt) for pt in ports],
                    catalog=cat, shuffle_mode="always",
                    shuffle_dag="always",
                ),
                "staged": DCNFragmentScheduler(
                    [("127.0.0.1", pt) for pt in ports],
                    catalog=cat, shuffle_mode="never",
                    shuffle_dag="never",
                ),
            }
            out = {}
            try:
                kind, cut = scheds["range"]._choose_cut(plan)
                assert kind == "dag", (
                    f"rung {name} did not plan a range DAG ({kind})"
                )
                ref = None
                for mode, sched in scheds.items():
                    sched.execute_plan(plan)  # warm the compiles
                    staged0 = _reg_total("tidbtpu_dcn_bytes_staged")
                    walls = []
                    rows = []
                    for _ in range(max(args.repeat, 3)):
                        t0 = time.perf_counter()
                        _cols, rows = sched.execute_plan(plan)
                        walls.append(time.perf_counter() - t0)
                    if ref is None:
                        ref = rows
                    assert rows == ref, f"rung {name} parity broke"
                    lq = sched.last_query or {}
                    entry = {
                        "seconds": round(statistics.median(walls), 6),
                        "rows": len(rows),
                        "bytes_over_coordinator": _reg_total(
                            "tidbtpu_dcn_bytes_staged"
                        ) - staged0,
                    }
                    if mode == "range":
                        st = (lq.get("shuffle_stages") or [{}])[-1]
                        frags = lq.get("fragments", [])
                        last_stage = st.get("stage", 0)
                        entry["boundaries"] = st.get("boundaries")
                        entry["max_partition_rows"] = max(
                            (
                                f.get("rows", 0) for f in frags
                                if f.get("stage", 0) == last_stage
                            ),
                            default=0,
                        )
                    out[mode] = entry
            finally:
                for sched in scheds.values():
                    sched.close()
            out["speedup_vs_staged"] = round(
                out["staged"]["seconds"]
                / max(out["range"]["seconds"], 1e-9), 4
            )
            out["query"] = sql
            return name, out

        ladder = dict(run_rung(n, s) for n, s in RUNGS)
        nrows = cat.table("tpch", "lineitem").nrows
        result = {
            "metric": f"order_by_range_exchange_sf{sf:g}_rows_per_sec",
            "value": round(
                nrows / ladder["topk"]["range"]["seconds"], 2
            ),
            "unit": "rows/s",
            "vs_baseline": ladder["topk"]["speedup_vs_staged"],
            "detail": {
                "backend": "cpu",
                "scenario": "order_by_range_exchange",
                "workers": 2,
                "mesh_devices": 4,
                "sf": sf,
                "repeat": args.repeat,
                "order_by": ladder,
                "backend_provenance": {
                    "backend": "cpu",
                    "pjrt_backend": "cpu",
                    "code_version": _code_version(),
                    "captured_unix": int(time.time()),
                    "fallback": False,
                },
            },
        }
    finally:
        for p in workers:
            p.kill()
    rc = 0
    if args.out:
        args.cpu = True
        rc = _write_out(args, result)
    print(json.dumps(result))
    return rc


def _write_inspect_out(args, detail: dict) -> None:
    """--inspect-out: snapshot detail.inspection to a JSON file."""
    from tidb_tpu.obs.inspection import write_inspect_out

    write_inspect_out(getattr(args, "inspect_out", None), detail)


def measure_chaos(args) -> int:
    """Chaos robustness scenario: N seeded composed-fault episodes
    (worker crash / hang / frame loss / delay / slow peer / tunnel
    partition / clock skew) against an in-process 2-server fleet
    running --multihost-shuffle-shaped workloads (repartition joins +
    distinct group-bys over the tunnels, grouped aggregates over the
    partial-agg cut), with the fleet invariants audited after EVERY
    episode. Stamps detail.chaos — episodes, faults injected,
    invariant violations (0 is the bar), recovery-wall p50/p95 — so
    the robustness trajectory is benchable like perf: a regression
    that slows recovery or leaks a buffer moves a number here."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    from tidb_tpu.chaos import ChaosHarness

    episodes = max(int(args.chaos_episodes), 1)
    seed = int(args.chaos_seed)
    false_positive = None
    with ChaosHarness(seed=seed, wait_timeout_s=2.0) as h:
        # false-positive guard FIRST: a fault-free calibration episode
        # must not yield a critical inspection finding — a diagnosis
        # tier that alarms on a healthy fleet fails the bench before
        # any chaos is injected
        baseline_viol, (b0, b1) = h.baseline_episode()
        from tidb_tpu.obs.inspection import run_inspection

        baseline_critical = [
            f.to_dict() for f in run_inspection(t_lo=b0, t_hi=b1)
            if f.severity == "critical"
        ]
        if baseline_critical:
            false_positive = baseline_critical
        # the headline wall starts AFTER calibration: the episodes/s
        # metric must stay comparable with pre-PR-12 captures that
        # had no baseline episode or inspection pass in the window
        t0 = time.time()
        rep = h.run(episodes)
    wall = time.time() - t0
    detail = rep.to_dict()
    if baseline_viol:
        # a fleet invariant breached with NOTHING injected is a
        # stronger red flag than the same breach under faults: count
        # it into the run's violation total (which fails the bench)
        detail["invariant_violations"] += len(baseline_viol)
        detail["violations"] = (
            list(baseline_viol) + list(detail["violations"])
        )
    from tidb_tpu.obs.inspection import inspection_detail

    inspection = inspection_detail(windows=rep.windows)
    inspection["baseline_critical"] = false_positive or []
    inspection["baseline_violations"] = list(baseline_viol)
    _write_inspect_out(args, inspection)
    result = {
        "metric": f"chaos_episodes_seed{seed}_per_sec",
        "value": round(episodes / max(wall, 1e-9), 4),
        "unit": "episodes/s",
        "detail": {
            "backend": "cpu",
            "scenario": "chaos",
            "workers": 2,
            "wall_seconds": round(wall, 3),
            "chaos": detail,
            "inspection": inspection,
            "backend_provenance": {
                "backend": "cpu",
                "pjrt_backend": "cpu",
                "code_version": _code_version(),
                "captured_unix": int(time.time()),
                "fallback": False,
            },
        },
    }
    rc = 0
    if args.out:
        args.cpu = True  # deliberate CPU scenario: not a fallback
        rc = _write_out(args, result)
    if detail["invariant_violations"]:
        # a violated invariant fails the run loudly — AFTER the
        # capture is written (the violating run's record is exactly
        # the artifact a robustness regression needs)
        rc = 1
    if false_positive:
        # the false-positive guard: a CRITICAL inspection finding over
        # the fault-free calibration window means the diagnosis tier
        # alarms on a healthy fleet — fail loudly, after the capture
        print(json.dumps({
            "inspection_false_positive": false_positive
        }), file=sys.stderr)
        rc = 1
    print(json.dumps(result))
    return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    # SF10 headline: BASELINE.md's ladder runs SF10-SF100 and the north
    # star is SF100 rows/sec/chip. At SF1 the measurement is dominated
    # by the TPU tunnel's fixed ~65ms result-fetch latency (PERF_NOTES),
    # not engine throughput.
    ap.add_argument("--sf", type=float, default=10.0)
    ap.add_argument("--query", default="q1", choices=sorted(QUERIES) + ["q95"])
    # 3 repeats (median): at SF10 the whole child — datagen + sampled
    # ANALYZE + h2d + first jit + runs + numpy baselines — must fit the
    # 900s attempt budget on a 1-core host
    ap.add_argument("--repeat", type=int, default=3)
    ap.add_argument("--quick", action="store_true", help="sf=0.01 sanity run")
    ap.add_argument("--cpu", action="store_true", help="skip TPU, measure on CPU")
    ap.add_argument(
        "--out", default=None,
        help="also write the result JSON (with backend provenance) to "
        "this BENCH_*.json path; refuses to overwrite a TPU capture "
        "with a CPU fallback unless --allow-fallback",
    )
    ap.add_argument(
        "--allow-fallback", action="store_true",
        help="permit --out to overwrite a TPU capture with a CPU "
        "fallback result (marked {\"fallback\": true})",
    )
    ap.add_argument(
        "--metrics-out", default=None, metavar="FILE",
        help="snapshot the engine-metrics registry delta across the "
        "benchmarked query (jit compiles, retraces, transfer bytes, "
        "tidbtpu_* counters) to this JSON file; the delta is also "
        "stamped into detail.engine_metrics of the result",
    )
    ap.add_argument(
        "--flight-out", default=None, metavar="FILE",
        help="snapshot the query flight recorder after the run — "
        "per-query phase timelines, the per-digest statements summary "
        "(p50/p95/p99 + mean phase breakdown + engine columns) and the "
        "DCN link registry — to this JSON file (the information_schema "
        "breakdown, captured for the bench ladder)",
    )
    ap.add_argument(
        "--timeline-out", default=None, metavar="FILE",
        help="capture the fleet timeline across the run and write it "
        "as Chrome trace-event JSON (open in Perfetto / "
        "chrome://tracing): one process track per host, thread tracks "
        "per session/worker task, counter tracks from existing gauges;"
        " works in every mode incl. --serve-load and "
        "--multihost-shuffle (worker events ship back on the fenced "
        "replies, rebased through the handshake clock offsets)",
    )
    ap.add_argument(
        "--inspect-out", default=None, metavar="FILE",
        help="with --chaos or --serve-load: run the inspection engine "
        "(information_schema.inspection_result's evaluator, "
        "obs/inspection.py) over the run's sampled metric history and "
        "write the findings + evidence windows to this JSON file; "
        "detail.inspection is stamped either way. --chaos additionally "
        "exits nonzero on a critical finding over its fault-free "
        "calibration episode (false-positive guard)",
    )
    ap.add_argument(
        "--multihost-shuffle", action="store_true",
        help="run the 2-worker DCN shuffle-join dryrun instead of the "
        "single-engine ladder: measures a repartition-join query "
        "(orders JOIN lineitem GROUP BY o_orderpriority — Q18 itself "
        "pre-aggregates below the join, which removes the shuffle cut) "
        "with partial-agg coordinator staging vs direct worker-to-"
        "worker tunnels and records bytes_over_coordinator vs "
        "bytes_over_tunnels, plus the binary-vs-JSON shuffle wire "
        "codec A/B (bytes per row, encode/decode seconds — "
        "detail.codec_ab) (CPU data-plane scenario; SF capped at "
        "0.02 unless --sf <= 1)",
    )
    ap.add_argument(
        "--skew", action="store_true",
        help="AQE skew ladder (ISSUE 15): zipf-keyed join+group-by at "
        "3 skew exponents over a 4-server in-process fleet, "
        "interleaved A/B with hot-key salting armed vs off at exact "
        "row parity; stamps detail.aqe (walls, max-partition rows, "
        "decisions taken)",
    )
    ap.add_argument(
        "--order-by", action="store_true",
        help="run the distributed ORDER BY range-exchange ladder "
        "instead of the single-engine ladder: top-K / aggregate-then-"
        "order / full-sort queries each run range-partitioned "
        "(boundary-sampled exchange, per-partition sort with pushed-"
        "down top-K, order-preserving concat) vs the coordinator-sort "
        "baseline at exact parity; stamps detail.order_by (CPU "
        "data-plane scenario; SF capped at 0.02 unless --sf <= 1)",
    )
    ap.add_argument(
        "--serve-load", action="store_true",
        help="run the serving-tier load scenario instead of the "
        "single-engine ladder: N concurrent MySQL-protocol sessions "
        "(--serve-sessions) drive a mixed HIGH_PRIORITY/LOW_PRIORITY "
        "workload through one coordinator Server routing across a "
        "worker fleet with admission control; reports p50/p99 latency "
        "per class + fleet queries/sec, proves >= 2 sessions' "
        "fragments overlap (flight timelines), shared-plan-cache "
        "cross-session hits > 0, and kill-a-worker-under-load "
        "recovery (CPU data-plane scenario)",
    )
    ap.add_argument("--serve-sessions", type=int, default=64,
                    help="concurrent MySQL-protocol sessions (>= 64 "
                    "for the acceptance run)")
    ap.add_argument("--serve-statements", type=int, default=6,
                    help="statements per session")
    ap.add_argument("--serve-workers", type=int, default=2,
                    help="worker processes in the fleet")
    ap.add_argument("--serve-pool-size", type=int, default=4,
                    help="control connections per worker host")
    ap.add_argument("--serve-budget-mb", type=int, default=2048,
                    help="fleet device-memory admission budget (MiB)")
    ap.add_argument("--write-mix", action="store_true",
                    help="with --serve-load: a concurrent writer "
                    "session streams INSERTs through the HTAP delta "
                    "tier (read-your-writes verified per commit) while "
                    "reader sessions run both freshness modes; stamps "
                    "detail.delta (depth, per-host sync lag, "
                    "read-your-writes vs bounded-staleness p99)")
    ap.add_argument("--serve-kill-worker", action="store_true",
                    default=True,
                    help="hard-kill one worker mid-load (default on; "
                    "--no-serve-kill-worker disables)")
    ap.add_argument("--no-serve-kill-worker", dest="serve_kill_worker",
                    action="store_false")
    ap.add_argument(
        "--chaos", action="store_true",
        help="run the chaos robustness scenario instead of the "
        "single-engine ladder: N seeded composed-fault episodes "
        "(crash/hang/frame loss/delay/slow peer/tunnel partition/"
        "clock skew) over an in-process 2-server fleet, auditing "
        "fleet invariants after every episode; stamps detail.chaos "
        "(episodes, faults, invariant violations, recovery-wall "
        "p50/p95). A violated invariant exits nonzero.",
    )
    ap.add_argument("--chaos-episodes", type=int, default=20,
                    help="episodes per chaos run")
    ap.add_argument("--chaos-seed", type=int, default=1,
                    help="schedule seed (the same seed replays the "
                    "same fault schedule exactly)")
    ap.add_argument(
        "--racecheck", action="store_true",
        help="with --multihost-shuffle: run the worker processes under "
        "TIDB_TPU_RACECHECK=1 (order-tracked locks, utils/racecheck.py)"
        " and stamp detail.racecheck so the capture proves the data "
        "plane ran clean under the lock-order detector",
    )
    ap.add_argument("--_measure", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.quick:
        args.sf = 0.01
    if args.serve_load:
        from tidb_tpu.bench.serve_load import run_serve_load

        # the serving scenario picks its own dryrun scale cap
        if args.sf == 10.0:  # the ladder default is not a dryrun scale
            args.sf = 0.005
        return run_serve_load(args)
    if args.chaos:
        return measure_chaos(args)
    if args.multihost_shuffle:
        return measure_multihost_shuffle(args)
    if args.skew:
        return measure_skew(args)
    if args.order_by:
        return measure_order_by(args)

    if args._measure:
        return measure(args)

    passthrough = ["--sf", str(args.sf), "--query", args.query, "--repeat", str(args.repeat)]
    if args.metrics_out:
        passthrough += ["--metrics-out", args.metrics_out]
    if args.flight_out:
        passthrough += ["--flight-out", args.flight_out]
    if args.timeline_out:
        passthrough += ["--timeline-out", args.timeline_out]
    return supervise(args, passthrough)


if __name__ == "__main__":
    sys.exit(main())
