"""Benchmark: TPC-H on the device engine vs a vectorized-numpy CPU baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference baseline (BASELINE.md) is TiDB's own embedded CPU engine
(unistore/mocktikv vectorized coprocessor); a vectorized numpy
implementation of the same query over the same data stands in for it
here (same columnar layout, single CPU core — generous to the baseline
since numpy's C kernels are at least as fast as the Go engine's
per-chunk loops).

Usage: python bench.py [--sf 1.0] [--query q1|q6|q18] [--repeat 5] [--quick]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def numpy_q1(blk, cutoff):
    ship = blk["l_shipdate"]
    m = ship <= cutoff
    rf = blk["l_returnflag"][m].astype(np.int64)
    ls = blk["l_linestatus"][m].astype(np.int64)
    qty = blk["l_quantity"][m]
    price = blk["l_extendedprice"][m]
    disc = blk["l_discount"][m]
    tax = blk["l_tax"][m]
    key = rf * 2 + ls
    nk = 6
    disc_price = price * (100 - disc)
    charge = disc_price * (100 + tax)
    out = {
        "sum_qty": np.bincount(key, qty, minlength=nk),
        "sum_base": np.bincount(key, price, minlength=nk),
        "sum_disc": np.bincount(key, disc_price, minlength=nk),
        "sum_charge": np.bincount(key, charge, minlength=nk),
        "cnt": np.bincount(key, minlength=nk),
    }
    out["avg_qty"] = out["sum_qty"] / np.maximum(out["cnt"], 1)
    out["avg_base"] = out["sum_base"] / np.maximum(out["cnt"], 1)
    return out


def numpy_q6(blk, d0, d1):
    ship = blk["l_shipdate"]
    m = (
        (ship >= d0)
        & (ship < d1)
        & (blk["l_discount"] >= 5)
        & (blk["l_discount"] <= 7)
        & (blk["l_quantity"] < 2400)
    )
    return (blk["l_extendedprice"][m] * blk["l_discount"][m]).sum()


def numpy_q18(blk, thresh):
    ok = blk["l_orderkey"]
    qty = blk["l_quantity"]
    sums = np.bincount(ok, qty)
    big = np.nonzero(sums > thresh)[0]
    return big, sums[big]


Q1_SQL = (
    "select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, "
    "sum(l_extendedprice) as sum_base_price, "
    "sum(l_extendedprice * (1 - l_discount)) as sum_disc_price, "
    "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge, "
    "avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price, "
    "avg(l_discount) as avg_disc, count(*) as count_order "
    "from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day "
    "group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"
)
Q6_SQL = (
    "select sum(l_extendedprice * l_discount) as revenue from lineitem "
    "where l_shipdate >= date '1994-01-01' and l_shipdate < date '1995-01-01' "
    "and l_discount between 0.05 and 0.07 and l_quantity < 24"
)
Q18_SQL = (
    "select o_orderkey, sum(l_quantity) from lineitem, orders "
    "where o_orderkey = l_orderkey "
    "group by o_orderkey having sum(l_quantity) > 1250 "
    "order by sum(l_quantity) desc limit 100"
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--query", default="q1", choices=["q1", "q6", "q18"])
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--quick", action="store_true", help="sf=0.01 sanity run")
    args = ap.parse_args()
    if args.quick:
        args.sf = 0.01

    from tidb_tpu.bench import load_tpch
    from tidb_tpu.dtypes import date_to_days
    from tidb_tpu.session import Session
    from tidb_tpu.storage import Catalog

    cat = Catalog()
    t0 = time.perf_counter()
    tables = ["orders", "lineitem"]
    load_tpch(cat, sf=args.sf, tables=tables, seed=1)
    gen_s = time.perf_counter() - t0
    sess = Session(cat, db="tpch")
    li = cat.table("tpch", "lineitem")
    nrows = li.nrows

    sql = {"q1": Q1_SQL, "q6": Q6_SQL, "q18": Q18_SQL}[args.query]

    # device engine (includes host->device on first run; cached after)
    sess.execute(sql)  # warmup: compile + scan cache
    times = []
    for _ in range(args.repeat):
        t0 = time.perf_counter()
        sess.execute(sql)
        times.append(time.perf_counter() - t0)
    dev_s = float(np.median(times))

    # numpy baseline over the same host-resident columns
    blk = {}
    b = li.blocks()[0]
    for c in (
        "l_shipdate l_returnflag l_linestatus l_quantity l_extendedprice "
        "l_discount l_tax l_orderkey".split()
    ):
        blk[c] = b.columns[c].data
    base_times = []
    cutoff = int(date_to_days("1998-12-01")) - 90
    d0, d1 = int(date_to_days("1994-01-01")), int(date_to_days("1995-01-01"))
    for _ in range(max(args.repeat, 2)):
        t0 = time.perf_counter()
        if args.query == "q1":
            numpy_q1(blk, cutoff)
        elif args.query == "q6":
            numpy_q6(blk, d0, d1)
        else:
            numpy_q18(blk, 12500)
        base_times.append(time.perf_counter() - t0)
    base_s = float(np.median(base_times))

    value = nrows / dev_s
    baseline = nrows / base_s
    result = {
        "metric": f"tpch_{args.query}_sf{args.sf:g}_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(value / baseline, 3),
        "detail": {
            "rows": nrows,
            "device_median_s": round(dev_s, 4),
            "numpy_baseline_s": round(base_s, 4),
            "datagen_s": round(gen_s, 2),
            "repeat": args.repeat,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
