#!/usr/bin/env python
"""tidb_tpu server binary.

Reference: cmd/tidb-server/main.go — flags (main.go:200-262), store
registry (registerStores main.go:397), server start (createServer
main.go:895). The TPU engine is the only store ("--store=tpu" is the
default and the point); data can be bootstrapped from TPC-H datagen or
loaded via LOAD DATA INFILE / INSERT over the wire.
"""

from __future__ import annotations

import argparse
import signal
import sys


def main() -> int:
    ap = argparse.ArgumentParser(description="TPU-native MySQL-compatible SQL engine")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("-P", "--port", type=int, default=4000)
    ap.add_argument("--store", default="tpu", choices=["tpu"],
                    help="storage/compute engine (TPU device engine)")
    ap.add_argument("--tpch", type=float, default=None, metavar="SF",
                    help="bootstrap with TPC-H data at scale factor SF")
    args = ap.parse_args()

    from tidb_tpu.server import Server
    from tidb_tpu.storage import Catalog

    catalog = Catalog()
    if args.tpch:
        from tidb_tpu.bench import load_tpch

        print(f"generating TPC-H sf={args.tpch} ...", flush=True)
        load_tpch(catalog, sf=args.tpch)
    srv = Server(catalog, host=args.host, port=args.port)
    print(f"tidb_tpu listening on {args.host}:{srv.port} (store={args.store})", flush=True)
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
