#!/usr/bin/env python
"""tidb_tpu server binary.

Reference: cmd/tidb-server/main.go — flags (main.go:200-262), TOML config
(pkg/config/config.go, loaded by InitializeConfig main.go:275), store
registry (registerStores main.go:397), server start (createServer
main.go:895), graceful shutdown (main.go:330-341). Layers: built-in
defaults <- --config TOML <- CLI flags. With --path the catalog loads
from the snapshot directory on boot and persists back on shutdown
(the durability story; storage/persist.py).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def main() -> int:
    ap = argparse.ArgumentParser(description="TPU-native MySQL-compatible SQL engine")
    ap.add_argument("--config", default=None, metavar="FILE",
                    help="TOML config file (pkg/config analog)")
    ap.add_argument("--host", default=None)
    ap.add_argument("-P", "--port", type=int, default=None)
    ap.add_argument("--path", default=None,
                    help="persistence dir: load on boot, snapshot on shutdown")
    ap.add_argument("--status-port", type=int, default=None,
                    help="HTTP status/metrics port (reference :10080)")
    ap.add_argument("--store", default=None, choices=["tpu"],
                    help="storage/compute engine (TPU device engine)")
    ap.add_argument("--tpch", type=float, default=None, metavar="SF",
                    help="bootstrap with TPC-H data at scale factor SF")
    args = ap.parse_args()

    from tidb_tpu.server import Server
    from tidb_tpu.storage import Catalog
    from tidb_tpu.utils.config import Config

    cfg = Config.from_toml(args.config) if args.config else Config()
    cfg = cfg.override(
        host=args.host, port=args.port, path=args.path, store=args.store
    )

    catalog = Catalog()
    if cfg.path and os.path.exists(os.path.join(cfg.path, "manifest.json")):
        from tidb_tpu.storage.persist import load_catalog

        print(f"loading catalog from {cfg.path} ...", flush=True)
        load_catalog(cfg.path, catalog)
    cfg.apply_variables(catalog)
    if args.tpch:
        from tidb_tpu.bench import load_tpch

        print(f"generating TPC-H sf={args.tpch} ...", flush=True)
        load_tpch(catalog, sf=args.tpch)

    sp = args.status_port if args.status_port is not None else cfg.status_port
    srv = Server(catalog, host=cfg.host, port=cfg.port, status_port=sp)
    srv.stats_handle.interval_s = cfg.auto_analyze_interval_s
    from tidb_tpu.utils.watchdog import ensure_watchdog

    ensure_watchdog(catalog)  # memory alarm / expensive-query / mem-limit
    print(
        f"tidb_tpu listening on {cfg.host}:{srv.port} (store={cfg.store})",
        flush=True,
    )

    def on_sigterm(*_):
        # TCPServer.shutdown() blocks until serve_forever() returns, and
        # the signal handler runs ON serve_forever's thread — stop the
        # accept loop from a helper thread; the main thread then falls
        # out of serve_forever() and persists below
        import threading

        threading.Thread(target=srv.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, on_sigterm)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.shutdown()
    if cfg.path:
        from tidb_tpu.storage.persist import save_catalog

        print(f"snapshotting catalog to {cfg.path} ...", flush=True)
        save_catalog(catalog, cfg.path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
